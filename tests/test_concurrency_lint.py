"""SL40x concurrency lint: seeded-defect corpus + self-lint gate.

Mirrors tests/lint_corpus/: every tests/concurrency_corpus/sl4NN_*.py file
must produce its filename-prefix rule, and the engine's own source must be
SL4xx-ERROR-free (the same gate CI runs via `lint --self`).
"""

from pathlib import Path

import pytest

from siddhi_tpu.analysis import lint_package, lint_python_source
from siddhi_tpu.analysis.concurrency import package_root

CORPUS = Path(__file__).parent / "concurrency_corpus"
CORPUS_FILES = sorted(CORPUS.glob("sl4*.py"))


def _report_for(path: Path):
    return lint_python_source(path.read_text(), name=path.name)


@pytest.mark.parametrize("path", CORPUS_FILES, ids=lambda p: p.stem)
def test_corpus_file_triggers_its_rule(path):
    expected = path.stem.split("_")[0].upper()
    report = _report_for(path)
    rules = {d.rule_id for d in report.diagnostics}
    assert expected in rules, (
        f"{path.name}: expected {expected}, got {sorted(rules)}\n"
        + report.format())


def test_corpus_is_complete():
    # one seeded defect per rule in the catalog
    stems = {p.stem.split("_")[0].upper() for p in CORPUS_FILES}
    assert stems == {"SL401", "SL402", "SL403", "SL404", "SL405"}


def test_sl401_counts_each_primitive():
    report = _report_for(CORPUS / "sl401_raw_lock.py")
    sl401 = [d for d in report.diagnostics if d.rule_id == "SL401"]
    assert len(sl401) == 3  # Lock, RLock, Condition; Event not flagged
    assert all(d.severity.value == "error" for d in sl401)


def test_sl403_is_error_and_names_both_sites():
    report = _report_for(CORPUS / "sl403_lock_order.py")
    sl403 = [d for d in report.diagnostics if d.rule_id == "SL403"]
    assert sl403 and all(d.severity.value == "error" for d in sl403)
    assert any("corpus.accounts" in d.message and "corpus.audit" in d.message
               for d in sl403)


def test_sl404_spares_str_join():
    report = _report_for(CORPUS / "sl404_sleep_under_lock.py")
    sl404 = [d for d in report.diagnostics if d.rule_id == "SL404"]
    assert len(sl404) == 3  # sleep, fsync, thread join — NOT str.join


def test_noqa_comment_suppresses():
    src = (CORPUS / "sl405_global_dict.py").read_text()
    src = src.replace("_REGISTRY[name] = value                   # SL405",
                      "_REGISTRY[name] = value  # noqa: SL405")
    report = lint_python_source(src, name="suppressed.py")
    assert not any(d.rule_id == "SL405" for d in report.diagnostics), \
        report.format()


def test_parse_error_reports_sl000():
    report = lint_python_source("def broken(:\n", name="broken.py")
    assert any(d.rule_id == "SL000" for d in report.diagnostics)
    assert report.has_errors


def test_self_lint_is_error_free():
    """The CI zero-ERROR gate: the in-tree runtime must pass its own
    concurrency catalog."""
    report = lint_package(package_root())
    assert not report.has_errors, report.format()


def test_self_lint_covers_the_tree():
    # sanity: the walk actually visited the runtime (not an empty dir scan)
    report = lint_package(package_root())
    assert report.app_name.startswith("self:")
