"""Removal-capable min/max over sliding windows (reference:
core/query/selector/attribute/aggregator/MinAttributeAggregatorExecutor.java
processAdd/processRemove; query/aggregator AggregatorTestCases)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError

S = "define stream S (symbol string, price double, volume long);\n"


def build(app, batch_size=4):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    rt.start()
    return rt


def collect(rt, name="q"):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.extend(
        tuple(e.data) for e in i or []))
    return got


class TestSlidingMin:
    def test_length_window_min_recovers_after_eviction(self):
        rt = build(S + "@info(name='q') from S#window.length(3) "
                   "select min(price) as mn insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([5.0, 1.0, 7.0, 9.0, 8.0, 2.0]):
            h.send(("s", p, i), timestamp=i)
        rt.flush()
        # windows: [5] [5,1] [5,1,7] [1,7,9] [7,9,8] [9,8,2]
        assert [r[0] for r in got] == [5.0, 1.0, 1.0, 1.0, 7.0, 2.0]

    def test_length_window_max(self):
        rt = build(S + "@info(name='q') from S#window.length(2) "
                   "select max(price) as mx insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([5.0, 9.0, 1.0, 3.0, 2.0]):
            h.send(("s", p, i), timestamp=i)
        rt.flush()
        # windows: [5] [5,9] [9,1] [1,3] [3,2]
        assert [r[0] for r in got] == [5.0, 9.0, 9.0, 3.0, 3.0]

    def test_time_window_min_expiry_via_heartbeat(self):
        rt = build(S + "@info(name='q') from S#window.time(5 sec) "
                   "select min(price) as mn insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("s", 1.0, 0), timestamp=1_000)
        h.send(("s", 4.0, 1), timestamp=2_000)
        rt.flush()
        assert [r[0] for r in got] == [1.0, 1.0]
        # ts 1000 expires at 6000 (before the 6500 arrival processes), so
        # the arrival lane sees min{4.0, 9.0} — the removal took effect
        h.send(("s", 9.0, 2), timestamp=6_500)
        rt.flush()
        assert [r[0] for r in got][-1] == 4.0

    def test_min_carries_across_batches(self):
        rt = build(S + "@info(name='q') from S#window.length(4) "
                   "select min(volume) as mn insert into Out;", batch_size=2)
        got = collect(rt)
        h = rt.get_input_handler("S")
        vols = [7, 3, 9, 5, 8, 6]
        for i, v in enumerate(vols):
            h.send(("s", 1.0, v), timestamp=i)
            rt.flush()
        # windows (len 4): [7] [7,3] [7,3,9] [7,3,9,5] [3,9,5,8] [9,5,8,6]
        assert [r[0] for r in got] == [7, 3, 3, 3, 3, 5]

    def test_grouped_sliding_min_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="GROUP BY"):
            build(S + "@info(name='q') from S#window.length(3) "
                  "select symbol, min(price) as mn group by symbol "
                  "insert into Out;")

    def test_min_over_batch_window_still_works(self):
        rt = build(S + "@info(name='q') from S#window.lengthBatch(3) "
                   "select min(price) as mn insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([5.0, 1.0, 7.0]):
            h.send(("s", p, i), timestamp=i)
        rt.flush()
        assert [r[0] for r in got] == [5.0, 1.0, 1.0]


class TestExtremaEligibility:
    def test_post_window_filter_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="post-window"):
            build(S + "@info(name='q') from S#window.length(3)[price > 1.0] "
                  "select min(price) as mn insert into Out;")

    def test_delay_window_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="delay"):
            build(S + "@info(name='q') from S#window.delay(1 sec) "
                  "select min(price) as mn insert into Out;")


class TestExpressionWindowExtrema:
    def test_min_over_expression_window(self):
        rt = build(S + "@info(name='q') from S"
                   "#window.expression('count() <= 2') "
                   "select min(price) as mn insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([1.0, 5.0, 7.0, 9.0]):
            h.send(("s", p, i), timestamp=i)
        rt.flush()
        # pop-after-arrival: arrival lane sees pre-pop window, so windows at
        # emission are [1] [1,5] [1,5,7]->pop1 [5,7,9]->pop5
        assert [r[0] for r in got] == [1.0, 1.0, 1.0, 5.0]
