"""Removal-capable min/max over sliding windows (reference:
core/query/selector/attribute/aggregator/MinAttributeAggregatorExecutor.java
processAdd/processRemove; query/aggregator AggregatorTestCases)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError

S = "define stream S (symbol string, price double, volume long);\n"


def build(app, batch_size=4):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    rt.start()
    return rt


def collect(rt, name="q"):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.extend(
        tuple(e.data) for e in i or []))
    return got


class TestSlidingMin:
    def test_length_window_min_recovers_after_eviction(self):
        rt = build(S + "@info(name='q') from S#window.length(3) "
                   "select min(price) as mn insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([5.0, 1.0, 7.0, 9.0, 8.0, 2.0]):
            h.send(("s", p, i), timestamp=i)
        rt.flush()
        # windows: [5] [5,1] [5,1,7] [1,7,9] [7,9,8] [9,8,2]
        assert [r[0] for r in got] == [5.0, 1.0, 1.0, 1.0, 7.0, 2.0]

    def test_length_window_max(self):
        rt = build(S + "@info(name='q') from S#window.length(2) "
                   "select max(price) as mx insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([5.0, 9.0, 1.0, 3.0, 2.0]):
            h.send(("s", p, i), timestamp=i)
        rt.flush()
        # windows: [5] [5,9] [9,1] [1,3] [3,2]
        assert [r[0] for r in got] == [5.0, 9.0, 9.0, 3.0, 3.0]

    def test_time_window_min_expiry_via_heartbeat(self):
        rt = build(S + "@info(name='q') from S#window.time(5 sec) "
                   "select min(price) as mn insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("s", 1.0, 0), timestamp=1_000)
        h.send(("s", 4.0, 1), timestamp=2_000)
        rt.flush()
        assert [r[0] for r in got] == [1.0, 1.0]
        # ts 1000 expires at 6000 (before the 6500 arrival processes), so
        # the arrival lane sees min{4.0, 9.0} — the removal took effect
        h.send(("s", 9.0, 2), timestamp=6_500)
        rt.flush()
        assert [r[0] for r in got][-1] == 4.0

    def test_min_carries_across_batches(self):
        rt = build(S + "@info(name='q') from S#window.length(4) "
                   "select min(volume) as mn insert into Out;", batch_size=2)
        got = collect(rt)
        h = rt.get_input_handler("S")
        vols = [7, 3, 9, 5, 8, 6]
        for i, v in enumerate(vols):
            h.send(("s", 1.0, v), timestamp=i)
            rt.flush()
        # windows (len 4): [7] [7,3] [7,3,9] [7,3,9,5] [3,9,5,8] [9,5,8,6]
        assert [r[0] for r in got] == [7, 3, 3, 3, 3, 5]

    def test_min_over_batch_window_still_works(self):
        rt = build(S + "@info(name='q') from S#window.lengthBatch(3) "
                   "select min(price) as mn insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([5.0, 1.0, 7.0]):
            h.send(("s", p, i), timestamp=i)
        rt.flush()
        assert [r[0] for r in got] == [5.0, 1.0, 1.0]


class TestExtremaEligibility:
    def test_post_window_filter_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="post-window"):
            build(S + "@info(name='q') from S#window.length(3)[price > 1.0] "
                  "select min(price) as mn insert into Out;")

    def test_delay_window_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="delay"):
            build(S + "@info(name='q') from S#window.delay(1 sec) "
                  "select min(price) as mn insert into Out;")


class TestExpressionWindowExtrema:
    def test_min_over_expression_window(self):
        rt = build(S + "@info(name='q') from S"
                   "#window.expression('count() <= 2') "
                   "select min(price) as mn insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([1.0, 5.0, 7.0, 9.0]):
            h.send(("s", p, i), timestamp=i)
        rt.flush()
        # pop-after-arrival: arrival lane sees pre-pop window, so windows at
        # emission are [1] [1,5] [1,5,7]->pop1 [5,7,9]->pop5
        assert [r[0] for r in got] == [1.0, 1.0, 1.0, 5.0]


class TestGroupedSlidingMinMax:
    """Per-group removal-capable extrema (reference keeps one sorted multiset
    per AggregatorState group key): sorted-run RMQ in ops/extrema.py."""

    def test_grouped_length_window_min(self):
        rt = build(S + "@info(name='q') from S#window.length(4) "
                   "select symbol, min(price) as mn group by symbol "
                   "insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        rows = [("a", 5.0), ("b", 10.0), ("a", 1.0), ("b", 20.0),
                ("a", 7.0), ("b", 2.0), ("a", 9.0)]
        for i, (s, p) in enumerate(rows):
            h.send((s, p, i), timestamp=i)
        rt.flush()
        by = {}
        for sym, mn in [(r[0], r[1]) for r in got]:
            by.setdefault(sym, []).append(mn)
        # window(len 4) evolution per event:
        # a5 | b10 | a1 | b20 | a7(evicts a5) | b2(evicts b10) | a9(evicts a1)
        assert by["a"] == [5.0, 1.0, 1.0, 7.0]
        assert by["b"] == [10.0, 10.0, 2.0]

    def test_grouped_time_window_max(self):
        rt = build(S + "@info(name='q') from S#window.time(10) "
                   "select symbol, max(price) as mx group by symbol "
                   "insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        sends = [(0, "a", 5.0), (1, "b", 30.0), (2, "a", 9.0),
                 (12, "a", 3.0), (13, "b", 4.0)]
        for t, s, p in sends:
            h.send((s, p, 1), timestamp=t)
        rt.flush()
        by = {}
        for r in got:
            by.setdefault(r[0], []).append(r[1])
        # at t=12 the t<=2 events expired: a's max falls back to 3.0
        assert by["a"] == [5.0, 9.0, 3.0]
        assert by["b"] == [30.0, 4.0]

    def test_grouped_min_many_keys_parity_with_host(self):
        import numpy as np
        rt = build(S + "@info(name='q') from S#window.length(8) "
                   "select symbol, min(price) as mn group by symbol "
                   "insert into Out;", batch_size=16)
        got = collect(rt)
        rng = np.random.default_rng(5)
        rows = [(f"k{int(k)}", float(round(p, 1)))
                for k, p in zip(rng.integers(0, 5, 64),
                                rng.uniform(1, 100, 64))]
        h = rt.get_input_handler("S")
        for i, (s, p) in enumerate(rows):
            h.send((s, p, i), timestamp=i)
        rt.flush()
        # host reference: per event, min over the group's rows within the
        # last-8 window
        expect = []
        window = []
        for s, p in rows:
            window.append((s, p))
            window = window[-8:]
            expect.append((s, min(pp for ss, pp in window if ss == s)))
        assert [(r[0], pytest.approx(r[1])) for r in got] == expect
