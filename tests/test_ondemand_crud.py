"""Write-form on-demand query tests (reference:
store/OnDemandQueryTableTestCase — delete/update/update-or-insert/insert)."""

import pytest

from siddhi_tpu import SiddhiManager

APP = (
    "define stream S (symbol string, price float, volume long);\n"
    "define table T (symbol string, price float, volume long);\n"
    "from S insert into T;\n")


def build():
    rt = SiddhiManager().create_siddhi_app_runtime(APP)
    rt.start()
    h = rt.get_input_handler("S")
    for row in [("IBM", 75.0, 100), ("WSO2", 57.0, 10), ("GOOG", 120.0, 5)]:
        h.send(row)
    rt.flush()
    return rt


class TestOnDemandDelete:
    def test_delete_on_condition(self):
        rt = build()
        rt.query("delete T on T.symbol == 'IBM'")
        rows = sorted(r[0] for r in rt.tables["T"].all_rows())
        assert rows == ["GOOG", "WSO2"]

    def test_delete_numeric_condition(self):
        rt = build()
        rt.query("delete T on T.price < 100.0")
        assert [r[0] for r in rt.tables["T"].all_rows()] == ["GOOG"]


class TestOnDemandUpdate:
    def test_update_with_condition(self):
        rt = build()
        rt.query("update T set T.price = 99.5 on T.symbol == 'WSO2'")
        rows = {r[0]: r[1] for r in rt.tables["T"].all_rows()}
        assert rows["WSO2"] == pytest.approx(99.5)
        assert rows["IBM"] == pytest.approx(75.0)

    def test_update_all_rows(self):
        rt = build()
        rt.query("update T set T.volume = 0l")
        assert all(r[2] == 0 for r in rt.tables["T"].all_rows())

    def test_update_expression_of_table_attr(self):
        rt = build()
        rt.query("update T set T.price = T.price * 2.0 on T.symbol == 'IBM'")
        rows = {r[0]: r[1] for r in rt.tables["T"].all_rows()}
        assert rows["IBM"] == pytest.approx(150.0)


class TestOnDemandUpdateOrInsert:
    def test_updates_existing(self):
        rt = build()
        rt.query("select 'IBM' as symbol, 11.0 as price, 1l as volume "
                 "update or insert into T set T.price = 11.0 "
                 "on T.symbol == 'IBM'")
        rows = {r[0]: r[1] for r in rt.tables["T"].all_rows()}
        assert rows["IBM"] == pytest.approx(11.0)
        assert len(rows) == 3

    def test_inserts_when_missing(self):
        rt = build()
        rt.query("select 'MSFT' as symbol, 300.0 as price, 7l as volume "
                 "update or insert into T set T.price = 300.0 "
                 "on T.symbol == 'MSFT'")
        rows = {r[0]: (r[1], r[2]) for r in rt.tables["T"].all_rows()}
        assert rows["MSFT"] == (pytest.approx(300.0), 7)
        assert len(rows) == 4


class TestEmptySourceInsert:
    def test_insert_from_empty_table(self):
        rt = SiddhiManager().create_siddhi_app_runtime(
            "define table Src (a long);\n"
            "define table T (a long);\n")
        rt.start()
        events = rt.query("from Src select a insert into T")
        assert events == []
        assert rt.tables["T"].all_rows() == []


class TestOnDemandInsertFromSelect:
    def test_select_insert_into(self):
        rt = SiddhiManager().create_siddhi_app_runtime(
            APP + "define table Archive (symbol string, price float, volume long);\n")
        rt.start()
        h = rt.get_input_handler("S")
        for row in [("IBM", 75.0, 100), ("WSO2", 57.0, 10)]:
            h.send(row)
        rt.flush()
        events = rt.query("from T select symbol, price, volume insert into Archive")
        assert len(events) == 2
        assert sorted(r[0] for r in rt.tables["Archive"].all_rows()) == ["IBM", "WSO2"]
