"""Doctor CLI tests (siddhi_tpu/doctor.py).

The flagship case is the ISSUE 10 acceptance path run in-process: a real
runtime with a declared p99 SLO is degraded through the fault-injection
harness ($SIDDHI_FAULT_SPEC seeding a slow sink), the breach freezes a
diagnostic bundle, and the doctor must (a) name the INJECTED stage —
sink, not the device stage the sink publish is nested inside — as
dominant and (b) exit 3. The synthetic-bundle cases pin the rest of the
diagnosis matrix (breakers, compile storms, baseline regressions) and
the CI-stable exit codes 0/1/3.
"""

import json
import os

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu import doctor
from siddhi_tpu.telemetry.recorder import SCHEMA_VERSION
from siddhi_tpu.util.faults import apply_fault_spec

pytestmark = pytest.mark.smoke

FAULT_APP = """
@app:name('FaultApp')
@app:slo(stream='S', p99.ms='50', min.samples='3')
define stream S (symbol string, price double);
@sink(type='log', prefix='doctor-test')
define stream Out (symbol string, price double);
@info(name='q1')
from S[price > 0.0] select symbol, price insert into Out;
"""


@pytest.fixture(scope="class")
def degraded_bundle(request, tmp_path_factory):
    """Run the acceptance scenario once per class: healthy warm-up, then
    the env-seeded slow-sink fault until the p99 objective breaches and
    the recorder freezes exactly one bundle."""
    diag = tmp_path_factory.mktemp("diag")
    os.environ["SIDDHI_DIAG_DIR"] = str(diag)
    os.environ["SIDDHI_FAULT_SPEC"] = "sink:slow=0.05,p=1.0,seed=1"
    try:
        rt = SiddhiManager().create_siddhi_app_runtime(FAULT_APP)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(512):  # healthy warm-up past min.samples
            h.send(("A", float(i + 1)))
        rt.flush()
        rt.slo_engine.tick()
        assert not rt.slo_engine.breaching()
        plans = apply_fault_spec(rt)  # spec comes from the env var
        assert "sink" in plans
        for _ in range(20):
            for j in range(5):
                h.send(("B", float(j + 1)))
            rt.flush()
        rt.slo_engine.tick()
        assert rt.slo_engine.breaching()
        rep = rt.ctx.recorder.report()
        assert rep["bundles_written"] == 1, "expected one rate-limited bundle"
        bundles = os.listdir(os.path.join(diag, "FaultApp"))
        assert len(bundles) == 1
        path = os.path.join(diag, "FaultApp", bundles[0])
        rt.shutdown()
        yield path
    finally:
        os.environ.pop("SIDDHI_DIAG_DIR", None)
        os.environ.pop("SIDDHI_FAULT_SPEC", None)


class TestAcceptancePath:
    def test_doctor_names_injected_sink_stage_dominant(self, degraded_bundle):
        bundle = doctor.load_bundle(degraded_bundle)
        assert bundle["manifest"]["trigger"]["kind"] == "slo_breach"
        findings = doctor.analyze(bundle)
        crit = [f for f in findings if f["severity"] == "critical"]
        assert crit, "breached objective must produce a critical finding"
        top = crit[0]
        assert top["objective"] == "stream:S:p99.ms"
        assert "dominant stage: sink" in top["title"], top["title"]

    def test_cli_exits_degraded(self, degraded_bundle, capsys):
        rc = doctor.main([degraded_bundle])
        assert rc == doctor.EXIT_DEGRADED
        out = capsys.readouterr().out
        assert "dominant stage: sink" in out
        assert "[CRITICAL]" in out

    def test_json_output_is_machine_readable(self, degraded_bundle, capsys):
        rc = doctor.main([degraded_bundle, "--json"])
        assert rc == doctor.EXIT_DEGRADED
        payload = json.loads(capsys.readouterr().out)
        assert payload["app"] == "FaultApp"
        assert payload["degraded"] is True
        assert any(f["severity"] == "critical" for f in payload["findings"])


class TestExitCodes:
    def test_healthy_bundle_exits_zero(self, tmp_path, capsys):
        rt = SiddhiManager().create_siddhi_app_runtime(
            "@app:name('OkApp')\n"
            "define stream S (symbol string, price float);\n"
            "from S select symbol insert into Out;")
        rt.start()
        rt.get_input_handler("S").send(("A", 1.0))
        rt.flush()
        rec = rt.ctx.recorder
        rec.bundle_dir = str(tmp_path / "ok")
        path = rec.trigger("manual", force=True)
        rt.shutdown()
        assert doctor.main([path]) == doctor.EXIT_OK
        assert "healthy" in capsys.readouterr().out

    def test_missing_and_corrupt_bundles_exit_one(self, tmp_path, capsys):
        assert doctor.main([str(tmp_path / "nope")]) == doctor.EXIT_BAD_BUNDLE
        bad = tmp_path / "bad"
        bad.mkdir()
        (bad / "manifest.json").write_text(
            json.dumps({"schema_version": SCHEMA_VERSION + 99}))
        assert doctor.main([str(bad)]) == doctor.EXIT_BAD_BUNDLE
        capsys.readouterr()

    def test_live_scrape_failure_exits_one(self, capsys):
        rc = doctor.main(["--live", "http://127.0.0.1:1", "--app", "X"])
        assert rc == doctor.EXIT_BAD_BUNDLE
        assert "live scrape" in capsys.readouterr().err


def _bundle(stats=None, traces=None):
    return {"manifest": {"schema_version": SCHEMA_VERSION, "app": "t",
                         "trigger": {"kind": "manual", "reason": ""}},
            "stats": stats or {}, "traces": traces or {},
            "logs": [], "plan": None, "config": None}


def _breached_slo(scope="stream:S"):
    return {"objectives": {f"{scope}:p99.ms": {
        "state": "breached", "scope": scope, "breaches": 1, "recoveries": 0,
        "fast": {"burn_rate": 12.0}, "slow": {"burn_rate": 4.0}}}}


class TestSyntheticDiagnosis:
    def test_stream_scope_ranks_stage_p99s(self):
        stats = {"slo": _breached_slo("stream:S"),
                 "latency": {"streams": {"S": {
                     "device": {"p99_ms": 4.0}, "h2d": {"p99_ms": 80.0},
                     "sink": {"p99_ms": 2.0}, "e2e": {"p99_ms": 90.0}}}}}
        (f,) = doctor.analyze(_bundle(stats))
        assert "dominant stage: h2d" in f["title"]
        assert "stream 'S'" in f["evidence"]

    def test_query_scope_falls_back_to_exemplar_shares(self):
        stats = {"slo": _breached_slo("query:q1")}
        traces = {"slow_batches": [
            {"queries": ["q1"], "stages_ms": {"stage": 1.0, "h2d": 1.0,
                                              "device": 30.0, "sink": 2.0}},
            {"queries": ["other"], "stages_ms": {"stage": 99.0, "h2d": 0.0,
                                                 "device": 0.0, "sink": 0.0}},
        ]}
        (f,) = doctor.analyze(_bundle(stats, traces))
        assert "dominant stage: device" in f["title"]
        assert "query 'q1'" in f["evidence"]

    def test_recovered_objective_is_info_only(self):
        stats = {"slo": {"objectives": {"stream:S:p99.ms": {
            "state": "ok", "scope": "stream:S", "breaches": 2,
            "recoveries": 2, "fast": {}, "slow": {}}}}}
        (f,) = doctor.analyze(_bundle(stats))
        assert f["severity"] == "info" and "recovered" in f["title"]

    def test_engine_surfaces_and_ranking(self):
        stats = {
            "breakers": {"q1": {"state": "open", "failures": 5,
                                "diverted_rows": 40}},
            "sink_dead_letters": {"Out": 7},
            "compile_widths": {"q1": list(range(10))},
        }
        findings = doctor.analyze(_bundle(stats))
        sevs = [f["severity"] for f in findings]
        assert sevs == sorted(
            sevs, key=doctor.SEVERITIES.index), "ranked most-severe first"
        titles = " | ".join(f["title"] for f in findings)
        assert "circuit breaker" in titles
        assert "dead-letters" in titles
        assert "recompile storm" in titles

    def test_state_budget_near_exhaustion_names_dominant(self):
        stats = {"cost": {
            "predicted_state_bytes": 900, "live_state_bytes": 850,
            "state_ratio": 850 / 900, "predicted_compiles": 4,
            "live_compiles": 4, "exact": True,
            "dominant": {"element": "q1", "state_bytes": 800,
                         "share": 0.89},
            "budget": {"state_bytes": 1000, "compiles": None,
                       "mode": "error", "source": "annotation"}}}
        (f,) = doctor.analyze(_bundle(stats))
        assert f["severity"] == "warning"
        assert "state budget near exhaustion" in f["title"]
        assert "'q1'" in f["evidence"] and "SL505" in f["evidence"]
        assert "90%" in f["evidence"]

    def test_state_budget_exceeded_is_critical(self):
        stats = {"cost": {
            "predicted_state_bytes": 1500, "live_state_bytes": 1500,
            "state_ratio": 1.0, "predicted_compiles": 1, "live_compiles": 1,
            "exact": True, "dominant": None,
            "budget": {"state_bytes": 1000, "compiles": None,
                       "mode": "queue", "source": "env"}}}
        (f,) = doctor.analyze(_bundle(stats))
        assert f["severity"] == "critical"
        assert "state budget exceeded" in f["title"]

    def test_cost_model_drift_flags_outside_band(self):
        stats = {"cost": {
            "predicted_state_bytes": 100, "live_state_bytes": 500,
            "state_ratio": 5.0, "predicted_compiles": 1, "live_compiles": 1,
            "exact": True, "dominant": None, "budget": None}}
        (f,) = doctor.analyze(_bundle(stats))
        assert f["severity"] == "warning"
        assert "cost-model drift" in f["title"]
        assert "cost_calibrate" in f["evidence"]

    def test_calibrated_cost_yields_no_finding(self):
        stats = {"cost": {
            "predicted_state_bytes": 100, "live_state_bytes": 110,
            "state_ratio": 1.1, "predicted_compiles": 2, "live_compiles": 2,
            "exact": True, "dominant": None, "budget": None}}
        assert doctor.analyze(_bundle(stats)) == []

    def test_baseline_regression_diff(self):
        now = {"latency": {"streams": {"S": {"sink": {"p99_ms": 50.0},
                                             "device": {"p99_ms": 5.0}}}}}
        base = {"latency": {"streams": {"S": {"sink": {"p99_ms": 10.0},
                                              "device": {"p99_ms": 5.0}}}}}
        findings = doctor.analyze(_bundle(now), baseline=_bundle(base),
                                  threshold=2.0)
        (f,) = findings
        assert f["severity"] == "warning"
        assert "'sink' p99 regressed 5.0x" in f["title"]
