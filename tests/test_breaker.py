"""Per-query circuit breaker tests (core/breaker.py): trip on K failures in
a window, divert input while OPEN, HALF_OPEN probe after cooldown, close on
probe success — all without stopping sibling queries or the app."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from siddhi_tpu.state.error_store import InMemoryErrorStore
from siddhi_tpu.util.faults import FaultPlan, InjectedFault, inject

pytestmark = pytest.mark.smoke


class TestCircuitBreakerUnit:
    def test_trip_cooldown_probe_close(self):
        clk = {"t": 0.0}
        br = CircuitBreaker(threshold=2, window_s=60.0, cooldown_s=5.0,
                            clock=lambda: clk["t"])
        assert br.allow() and br.state == CLOSED
        assert br.record_failure() is False
        assert br.record_failure() is True  # threshold hit -> OPEN
        assert br.state == OPEN and br.opens == 1
        assert not br.allow()  # inside cooldown
        clk["t"] = 5.0
        assert br.allow() and br.state == HALF_OPEN  # one probe admitted
        br.record_success()
        assert br.state == CLOSED and br.closes == 1

    def test_failed_probe_reopens(self):
        clk = {"t": 0.0}
        br = CircuitBreaker(threshold=1, cooldown_s=1.0,
                            clock=lambda: clk["t"])
        assert br.record_failure() is True
        clk["t"] = 1.5
        assert br.allow() and br.state == HALF_OPEN
        assert br.record_failure() is True  # probe failed: straight back
        assert br.state == OPEN and br.opens == 2
        assert not br.allow()

    def test_window_prunes_stale_failures(self):
        clk = {"t": 0.0}
        br = CircuitBreaker(threshold=2, window_s=10.0,
                            clock=lambda: clk["t"])
        br.record_failure()
        clk["t"] = 11.0  # first failure ages out of the window
        assert br.record_failure() is False
        assert br.state == CLOSED


def _build(*, breaker_ann, store=None, extra_query=""):
    mgr = SiddhiManager()
    if store is not None:
        mgr.set_error_store(store)
    app = ("@app:name('BrkApp')\n"
           "define stream S (v long);\n"
           f"@info(name='q') {breaker_ann}\n"
           "from S select v insert into Out;\n" + extra_query)
    rt = mgr.create_siddhi_app_runtime(app, batch_size=4)
    got: list = []
    rt.add_callback("Out", lambda evs: got.extend(e.data[0] for e in evs))
    return mgr, rt, got


class TestQueryBreaker:
    def test_lifecycle_trip_divert_halfopen_close(self):
        """The acceptance scenario end-to-end: K failures trip the breaker,
        OPEN diverts input to the ErrorStore (replayable, counted), the
        cooldown admits a probe, and a probe success closes the breaker."""
        store = InMemoryErrorStore()
        _mgr, rt, got = _build(
            breaker_ann="@breaker(threshold='2', window='60 sec', "
                        "cooldown='5 sec')",
            store=store)
        qr = rt.query_runtimes["q"]
        clk = {"t": 0.0}
        qr.breaker.clock = lambda: clk["t"]  # virtual time
        plan = inject(qr, "on_batch", FaultPlan(nth=(1, 2), exc=InjectedFault))
        h = rt.get_input_handler("S")

        for i in range(3):  # rows 0,1 fail the step; row 2 meets OPEN
            h.send((i,))
            rt.flush()
        rep = rt.statistics_report()
        assert got == []
        assert qr.breaker.state == OPEN
        assert rep["breakers"]["q"]["state"] == OPEN
        assert rep["breakers"]["q"]["opens"] == 1
        assert rep["breakers"]["q"]["failures"] == 2
        # every undelivered row was diverted — rows 0,1 on failure, row 2
        # while open — and is replayable from the store
        assert rep["breakers"]["q"]["diverted_rows"] == 3
        diverted = [row[0] for e in store.load("BrkApp", kind="breaker")
                    for _ts, row in e.events]
        assert sorted(diverted) == [0, 1, 2]
        assert plan.calls == 2  # the OPEN divert never dispatched the step

        clk["t"] = 5.0  # cooldown over: next batch is the HALF_OPEN probe
        h.send((3,))
        rt.flush()
        assert qr.breaker.state == CLOSED  # probe (fault plan exhausted) ok
        h.send((4,))
        rt.flush()
        assert got == [3, 4]
        assert rt.health()["state"] == "stopped"  # never started; not degraded

    def test_sibling_queries_survive_a_tripped_query(self):
        """One poisoned query must not take the app down: its breaker opens
        while the sibling on the same junction keeps delivering."""
        store = InMemoryErrorStore()
        _mgr, rt, got = _build(
            breaker_ann="@breaker(threshold='1')",
            store=store,
            extra_query="@info(name='sibling') "
                        "from S select v insert into Out2;")
        got2: list = []
        rt.add_callback("Out2", lambda evs: got2.extend(e.data[0] for e in evs))
        qr = rt.query_runtimes["q"]
        inject(qr, "on_batch", FaultPlan(for_s=1e9, exc=InjectedFault))
        h = rt.get_input_handler("S")
        for i in range(6):
            h.send((i,))
            rt.flush()
        assert qr.breaker.state == OPEN
        assert got == []
        assert got2 == list(range(6))  # sibling untouched
        assert rt.statistics_report()["breakers"]["q"]["diverted_rows"] == 6

    def test_open_breaker_marks_app_degraded(self):
        store = InMemoryErrorStore()
        _mgr, rt, _got = _build(breaker_ann="@breaker(threshold='1')",
                                store=store)
        rt.start()
        try:
            inject(rt.query_runtimes["q"], "on_batch",
                   FaultPlan(nth=(1,), exc=InjectedFault))
            rt.get_input_handler("S").send((1,))
            rt.flush()
            health = rt.health()
            assert health["state"] == "degraded"
            assert health["breakers"]["q"]["state"] == OPEN
        finally:
            rt.shutdown()

    def test_divert_prefers_fault_stream(self):
        """With @OnError(action='STREAM') on the input stream, breaker
        diverts ride the `!stream` fault stream with the error message."""
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('BrkFS')\n"
            "@OnError(action='STREAM')\n"
            "define stream S (v long);\n"
            "@info(name='q') @breaker(threshold='1')\n"
            "from S select v insert into Out;", batch_size=4)
        faulted: list = []
        rt.add_callback("!S", lambda evs: faulted.extend(evs))
        inject(rt.query_runtimes["q"], "on_batch",
               FaultPlan(nth=(1,), exc=InjectedFault))
        rt.get_input_handler("S").send((7,))
        rt.flush()
        assert len(faulted) == 1
        assert faulted[0].data[0] == 7
        assert "injected fault" in faulted[0].data[1]

    def test_no_breaker_preserves_propagation(self):
        """Queries without @breaker keep the pre-existing contract: a step
        failure with no @OnError propagates to the caller."""
        _mgr, rt, _got = _build(breaker_ann="")
        inject(rt.query_runtimes["q"], "on_batch",
               FaultPlan(nth=(1,), exc=InjectedFault))
        h = rt.get_input_handler("S")
        h.send((1,))
        with pytest.raises(InjectedFault):
            rt.flush()

    def test_bad_breaker_annotation_rejected(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError):
            SiddhiManager().create_siddhi_app_runtime(
                "define stream S (v long);\n"
                "@breaker(threshold='0')\n"
                "from S select v insert into Out;")
