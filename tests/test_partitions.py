"""Partition behavioral tests (reference:
modules/siddhi-core/src/test/java/io/siddhi/core/query/partition/ — 8 files:
PartitionTestCase1/2, RangePartitionTestCase: value/range partitions, per-key
window and aggregator state isolation, inner streams)."""

import pytest

from siddhi_tpu import SiddhiManager

STOCK = "define stream StockStream (symbol string, price float, volume long);\n"


def build(app_text, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(app_text, batch_size=batch_size)
    rt.start()
    return rt


def q_callback(rt, name):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.extend(i or []))
    return got


class TestValuePartition:
    def test_per_key_count(self):
        # count() inside a partition is per key (reference PartitionTestCase1)
        rt = build(
            STOCK
            + "partition with (symbol of StockStream) begin\n"
            "@info(name='q') from StockStream select symbol, count() as n "
            "insert into Out;\n"
            "end;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("StockStream")
        for row in [("IBM", 1.0, 1), ("WSO2", 1.0, 1), ("IBM", 1.0, 1),
                    ("IBM", 1.0, 1), ("WSO2", 1.0, 1)]:
            h.send(row)
        rt.flush()
        counts = {}
        for e in got:
            counts[e.data[0]] = e.data[1]
        assert counts == {"IBM": 3, "WSO2": 2}

    def test_per_key_length_window_sum(self):
        # length(2) window keeps last 2 events PER KEY
        rt = build(
            STOCK
            + "partition with (symbol of StockStream) begin\n"
            "@info(name='q') from StockStream#window.length(2) "
            "select symbol, sum(price) as total insert into Out;\n"
            "end;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("StockStream")
        for row in [("A", 10.0, 1), ("B", 100.0, 1), ("A", 20.0, 1),
                    ("A", 30.0, 1), ("B", 200.0, 1)]:
            h.send(row)
            rt.flush()
        finals = {}
        for e in got:
            finals[e.data[0]] = e.data[1]
        # A: window holds 20,30 → 50; B: holds 100,200 → 300
        assert finals["A"] == pytest.approx(50.0)
        assert finals["B"] == pytest.approx(300.0)

    def test_stateless_filter_partition(self):
        rt = build(
            STOCK
            + "partition with (symbol of StockStream) begin\n"
            "@info(name='q') from StockStream[price > 50.0] "
            "select symbol, price insert into Out;\n"
            "end;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("StockStream")
        for row in [("A", 60.0, 1), ("B", 10.0, 1), ("C", 70.0, 1)]:
            h.send(row)
        rt.flush()
        assert sorted(e.data[0] for e in got) == ["A", "C"]

    def test_inner_stream_chaining(self):
        rt = build(
            STOCK
            + "partition with (symbol of StockStream) begin\n"
            "from StockStream select symbol, price, count() as n "
            "insert into #Acc;\n"
            "@info(name='q2') from #Acc[n == 2] select symbol, price "
            "insert into Out;\n"
            "end;")
        got = q_callback(rt, "q2")
        h = rt.get_input_handler("StockStream")
        for row in [("A", 1.0, 1), ("B", 5.0, 1), ("A", 2.0, 1), ("B", 6.0, 1)]:
            h.send(row)
        rt.flush()
        # per key, the 2nd event passes the inner filter
        rows = sorted((e.data[0], e.data[1]) for e in got)
        assert rows == [("A", pytest.approx(2.0)), ("B", pytest.approx(6.0))]


class TestRangePartition:
    def test_range_routing(self):
        rt = build(
            "define stream S (symbol string, price float);\n"
            "partition with (price < 50.0 as 'cheap' or price >= 50.0 as 'rich' of S)\n"
            "begin\n"
            "@info(name='q') from S select symbol, count() as n insert into Out;\n"
            "end;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        for row in [("a", 10.0), ("b", 90.0), ("c", 20.0), ("d", 95.0), ("e", 30.0)]:
            h.send(row)
        rt.flush()
        # counts are per range-key: cheap has 3, rich has 2
        assert max(e.data[1] for e in got) == 3


class TestRangePartitionDrop:
    def test_stateless_range_drops_unmatched(self):
        # events matching no range route nowhere, even on the stateless path
        rt = build(
            "define stream S (symbol string, price float);\n"
            "partition with (price < 50.0 as 'cheap' of S) begin\n"
            "@info(name='q') from S select symbol, price insert into Out;\n"
            "end;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        h.send(("a", 10.0))
        h.send(("b", 90.0))
        rt.flush()
        assert [e.data[0] for e in got] == ["a"]


class TestPartitionPersistence:
    def test_snapshot_restore_per_key_state(self):
        app = (STOCK
               + "partition with (symbol of StockStream) begin\n"
               "@info(name='q') from StockStream select symbol, count() as n "
               "insert into Out;\n"
               "end;")
        rt = build(app)
        h = rt.get_input_handler("StockStream")
        for row in [("A", 1.0, 1), ("A", 1.0, 1), ("B", 1.0, 1)]:
            h.send(row)
        rt.flush()
        blob = rt.snapshot()

        rt2 = build(app)
        rt2.restore(blob)
        got = q_callback(rt2, "q")
        rt2.get_input_handler("StockStream").send(("A", 1.0, 1))
        rt2.flush()
        assert [(e.data[0], e.data[1]) for e in got] == [("A", 3)]


class TestPartitionedWindowJoins:
    """Window joins inside a partition block run PER KEY — each key's inner
    query owns isolated window rings on both sides (reference:
    GroupingFindableWindowProcessor.java:40 — findable window contents are
    keyed by the partition flow id). VERDICT r3 item 4."""

    JOIN_APP = (
        "define stream A (sym string, x int);\n"
        "define stream B (sym string, y int);\n"
        "partition with (sym of A, sym of B) begin\n"
        "@info(name='pj') from A#window.length(5) join B#window.length(5) "
        "on A.x == B.y "
        "select A.sym as sym, A.x as x, B.y as y insert into Out;\n"
        "end;")

    def test_equi_join_is_per_key(self):
        rt = build(self.JOIN_APP)
        got = q_callback(rt, "pj")
        ha, hb = rt.get_input_handler("A"), rt.get_input_handler("B")
        # same x/y values under DIFFERENT keys must not join
        ha.send(("k1", 7))
        ha.send(("k2", 7))
        rt.flush()
        hb.send(("k2", 7))
        rt.flush()
        assert [tuple(e.data) for e in got] == [("k2", 7, 7)]

    def test_cross_join_windows_isolated(self):
        app = (
            "define stream A (sym string, x int);\n"
            "define stream B (sym string, y int);\n"
            "partition with (sym of A, sym of B) begin\n"
            "@info(name='pj') from A#window.length(5) join B#window.length(5) "
            "on A.x < B.y "
            "select A.sym as sym, A.x as x, B.y as y insert into Out;\n"
            "end;")
        rt = build(app)
        got = q_callback(rt, "pj")
        ha, hb = rt.get_input_handler("A"), rt.get_input_handler("B")
        ha.send(("k1", 1))
        ha.send(("k2", 10))
        rt.flush()
        hb.send(("k1", 5))   # joins k1's window only: 1 < 5
        hb.send(("k2", 5))   # k2: 10 < 5 fails
        rt.flush()
        assert sorted(tuple(e.data) for e in got) == [("k1", 1, 5)]

    def test_broadcast_side_joins_every_key(self):
        # B is NOT partitioned: its events broadcast into every live key's
        # inner join (reference PartitionStreamReceiver broadcast path)
        app = (
            "define stream A (sym string, x int);\n"
            "define stream B (y int);\n"
            "partition with (sym of A) begin\n"
            "@info(name='pj') from A#window.length(5) join B#window.length(5) "
            "on A.x == B.y "
            "select A.sym as sym, B.y as y insert into Out;\n"
            "end;")
        rt = build(app)
        got = q_callback(rt, "pj")
        ha, hb = rt.get_input_handler("A"), rt.get_input_handler("B")
        ha.send(("k1", 3))
        ha.send(("k2", 3))
        rt.flush()
        hb.send((3,))
        rt.flush()
        assert sorted(tuple(e.data) for e in got) == [("k1", 3), ("k2", 3)]

    def test_per_key_state_survives_snapshot(self):
        rt = build(self.JOIN_APP)
        ha, hb = rt.get_input_handler("A"), rt.get_input_handler("B")
        ha.send(("k1", 42))
        rt.flush()
        blob = rt.snapshot()
        rt2 = build(self.JOIN_APP)
        rt2.restore(blob)
        got = q_callback(rt2, "pj")
        rt2.get_input_handler("B").send(("k1", 42))
        rt2.flush()
        assert [tuple(e.data) for e in got] == [("k1", 42, 42)]

    def test_outer_join_per_key(self):
        app = (
            "define stream A (sym string, x int);\n"
            "define stream B (sym string, y int);\n"
            "partition with (sym of A, sym of B) begin\n"
            "@info(name='pj') from A#window.length(5) "
            "left outer join B#window.length(5) on A.x == B.y "
            "select A.sym as sym, A.x as x, B.y as y insert into Out;\n"
            "end;")
        rt = build(app)
        got = q_callback(rt, "pj")
        ha, hb = rt.get_input_handler("A"), rt.get_input_handler("B")
        hb.send(("k1", 8))
        rt.flush()
        ha.send(("k1", 8))   # matches k1's B window
        ha.send(("k2", 8))   # k2 has no B rows: null row (numeric null -> 0)
        rt.flush()
        assert sorted(tuple(e.data) for e in got) == [
            ("k1", 8, 8), ("k2", 8, 0)]


class TestPlaybackPartitionWindows:
    """Playback virtual time × per-key windows inside partitions
    (VERDICT r3 item 8: partition+window interactions; reference:
    PartitionTestCase window cases + playback TimestampGenerator)."""

    def test_per_key_time_window_expires_on_heartbeat(self):
        rt = build(
            "@app:playback\n" + STOCK
            + "partition with (symbol of StockStream) begin\n"
            "@info(name='q') from StockStream#window.time(1 sec) "
            "select symbol, sum(volume) as v insert into Out;\n"
            "end;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("StockStream")
        h.send(("A", 1.0, 10), timestamp=100)
        h.send(("B", 1.0, 20), timestamp=200)
        rt.flush()
        assert sorted((e.data[0], e.data[1]) for e in got) == [
            ("A", 10), ("B", 20)]
        del got[:]
        # the heartbeat drives EVERY key instance's clock: both windows
        # drain, and new arrivals aggregate from zero per key
        rt.heartbeat(now=2_000)
        h.send(("A", 1.0, 5), timestamp=2_100)
        rt.flush()
        assert [(e.data[0], e.data[1]) for e in got if e.data[0] == "A"] \
            == [("A", 5)]

    def test_per_key_time_batch_flush(self):
        rt = build(
            "@app:playback\n" + STOCK
            + "partition with (symbol of StockStream) begin\n"
            "@info(name='q') from StockStream#window.timeBatch(1 sec) "
            "select symbol, sum(volume) as v insert into Out;\n"
            "end;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("StockStream")
        h.send(("A", 1.0, 1), timestamp=100)
        h.send(("A", 1.0, 2), timestamp=200)
        h.send(("B", 1.0, 7), timestamp=300)
        rt.flush()
        rt.heartbeat(now=1_500)  # bucket [0,1000) closes for both keys
        flushed = sorted((e.data[0], e.data[1]) for e in got)
        assert ("A", 3) in flushed and ("B", 7) in flushed

    def test_purge_drops_idle_keys_under_playback(self):
        rt = build(
            "@app:playback\n" + STOCK
            + "@purge(idle.period='1 sec')\n"
            "partition with (symbol of StockStream) begin\n"
            "@info(name='q') from StockStream select symbol, count() as n "
            "insert into Out;\n"
            "end;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("StockStream")
        h.send(("A", 1.0, 1), timestamp=100)
        rt.flush()
        rt.heartbeat(now=5_000)  # A idle > 1 sec: instance purged
        pr = next(iter(rt.partitions.values()))
        assert pr.instances == {}
        h.send(("A", 1.0, 1), timestamp=5_100)  # fresh instance: count resets
        rt.flush()
        assert [e.data[1] for e in got] == [1, 1]
