"""Batched public ingestion: send_batch / send_columns / async callbacks.

Reference parity: InputHandler.java:50 offers send(Event[]) — a batch
overload of the public ingestion API. Here the batched paths are also the
performance surface (VERDICT r3 item 1): per-event Python overhead is paid
once per batch, string interning is vectorized per distinct value, and
callback decode can run on a background worker (async_callbacks=True).
Every path must produce byte-identical results to per-row send().
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager


pytestmark = pytest.mark.smoke

FILTER_APP = """
define stream TradeStream (symbol string, price double, volume long);
@info(name = 'q')
from TradeStream[price > 50.0]
select symbol, price, volume
insert into OutStream;
"""

GROUP_APP = """
define stream TradeStream (symbol string, price double, volume long);
@info(name = 'q')
from TradeStream#window.lengthBatch(8)
select symbol, sum(price) as total
group by symbol
insert into OutStream;
"""


def _rows(n, seed=3):
    rng = np.random.default_rng(seed)
    syms = rng.integers(1, 6, n)
    # prices quantized through float32 so expected-value comparisons are
    # exact (double columns store float32 on device)
    ps = rng.uniform(1.0, 100.0, n).astype(np.float32)
    vs = rng.integers(1, 50, n)
    return [(f"S{int(k)}", float(p), int(v))
            for k, p, v in zip(syms, ps, vs)]


def _collect(app, feed, *, batch_size=16, **kw):
    rt = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=batch_size, **kw)
    got = []
    rt.add_callback("OutStream", lambda evs: got.extend(
        (e.timestamp, e.data) for e in evs))
    rt.start()
    feed(rt)
    rt.drain()
    rt.shutdown()
    return got


class TestSendBatch:
    def test_matches_per_row_send(self):
        rows = _rows(40)

        def per_row(rt):
            h = rt.get_input_handler("TradeStream")
            for i, r in enumerate(rows):
                h.send(r, timestamp=i + 1)
            rt.flush()

        def batched(rt):
            h = rt.get_input_handler("TradeStream")
            h.send_batch(rows, timestamps=list(range(1, len(rows) + 1)))
            rt.flush()

        assert _collect(FILTER_APP, per_row) == _collect(FILTER_APP, batched)

    def test_single_timestamp_broadcast(self):
        rows = _rows(10)

        def batched(rt):
            rt.get_input_handler("TradeStream").send_batch(rows, timestamps=7)
            rt.flush()

        got = _collect(FILTER_APP, batched)
        assert got and all(ts == 7 for ts, _ in got)

    def test_groupby_equivalence(self):
        rows = _rows(32)

        def per_row(rt):
            h = rt.get_input_handler("TradeStream")
            for r in rows:
                h.send(r, timestamp=5)
            rt.flush()

        def batched(rt):
            rt.get_input_handler("TradeStream").send_batch(rows, timestamps=5)
            rt.flush()

        a, b = _collect(GROUP_APP, per_row), _collect(GROUP_APP, batched)
        assert a == b and len(a) > 0

    def test_timestamp_arity_mismatch_raises(self):
        rt = SiddhiManager().create_siddhi_app_runtime(FILTER_APP)
        h = rt.get_input_handler("TradeStream")
        with pytest.raises(ValueError, match="timestamps"):
            h.send_batch(_rows(4), timestamps=[1, 2])

    def test_async_ring_path(self):
        """@Async stream: send_batch pushes through the native staging ring."""
        app = FILTER_APP.replace("define stream TradeStream",
                                 "@async(buffer.size='16')\n"
                                 "define stream TradeStream")
        rows = _rows(40)

        def batched(rt):
            rt.get_input_handler("TradeStream").send_batch(
                rows, timestamps=list(range(1, len(rows) + 1)))
            import time
            time.sleep(0.05)  # let the feeder drain
            rt.flush()

        got = _collect(app, batched)
        expect = sorted((i + 1, r) for i, r in enumerate(rows) if r[1] > 50.0)
        assert sorted(got) == expect


class TestSendColumns:
    def _cols(self, n, seed=3):
        rows = _rows(n, seed)
        return {
            "symbol": np.array([r[0] for r in rows], dtype=object),
            "price": np.array([r[1] for r in rows]),
            "volume": np.array([r[2] for r in rows]),
        }, rows

    def test_matches_row_send(self):
        cols, rows = self._cols(40)
        tss = list(range(1, 41))

        def per_row(rt):
            h = rt.get_input_handler("TradeStream")
            for i, r in enumerate(rows):
                h.send(r, timestamp=tss[i])
            rt.flush()

        def columnar(rt):
            rt.get_input_handler("TradeStream").send_columns(
                cols, timestamps=tss)
            rt.flush()

        assert _collect(FILTER_APP, per_row) == _collect(FILTER_APP, columnar)

    def test_chunking_across_batch_capacity(self):
        """73 rows through capacity-16 junction: 4 full chunks + padded tail."""
        cols, rows = self._cols(73)

        def columnar(rt):
            rt.get_input_handler("TradeStream").send_columns(
                cols, timestamps=list(range(1, 74)))
            rt.flush()

        got = _collect(FILTER_APP, columnar, batch_size=16)
        expect = sorted((i + 1, r) for i, r in enumerate(rows) if r[1] > 50.0)
        assert sorted(got) == expect

    def test_missing_column_raises(self):
        rt = SiddhiManager().create_siddhi_app_runtime(FILTER_APP)
        with pytest.raises(ValueError, match="missing column"):
            rt.get_input_handler("TradeStream").send_columns(
                {"symbol": np.array(["A"], dtype=object)})

    def test_groupby_string_interning(self):
        """Vectorized interning must produce codes consistent with per-row
        interning (group keys decode back to the right symbols)."""
        cols, rows = self._cols(32)

        def per_row(rt):
            h = rt.get_input_handler("TradeStream")
            for r in rows:
                h.send(r, timestamp=5)
            rt.flush()

        def columnar(rt):
            rt.get_input_handler("TradeStream").send_columns(
                cols, timestamps=5)
            rt.flush()

        a = _collect(GROUP_APP, per_row)
        b = _collect(GROUP_APP, columnar)
        # vectorized interning assigns codes in sorted-unique order (per-row
        # assigns first-seen), so groups occupy different key-table slots and
        # float32 segment sums round differently at ~1e-6 relative — compare
        # with tolerance, exact on symbols
        assert len(a) == len(b) > 0
        for (ta, da), (tb, db) in zip(a, b):
            assert ta == tb and da[0] == db[0]
            assert da[1] == pytest.approx(db[1], rel=1e-5)


class TestVectorizedInterning:
    def test_transient_codes_round_trip(self):
        """A live transient (UUID-ring) string must encode back to its
        transient code through EVERY encode path — permanent re-interning
        would break device equality against stored uuid columns and shadow
        the transient code for later encodes."""
        from siddhi_tpu.core.event import StringTable
        tbl = StringTable()
        t_code = tbl.encode_transient("uuid-abc")
        assert t_code >= StringTable.TRANSIENT_BASE
        codes = tbl.encode_array(
            np.array(["uuid-abc", "plain"], dtype=object))
        assert codes[0] == t_code
        assert 0 < codes[1] < StringTable.TRANSIENT_BASE
        # encode() still sees the transient, not a permanent shadow
        assert tbl.encode("uuid-abc") == t_code

    def test_ring_detach_does_not_duplicate(self):
        """send_batch on an @Async stream racing shutdown: rows pushed to
        the ring before detach must not ALSO be re-staged synchronously."""
        app = FILTER_APP.replace("define stream TradeStream",
                                 "@async(buffer.size='8')\n"
                                 "define stream TradeStream")
        rows = _rows(64)
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)
        n = [0]
        rt.add_callback("OutStream", lambda evs: n.__setitem__(0, n[0] + len(evs)))
        rt.start()
        rt.get_input_handler("TradeStream").send_batch(
            rows, timestamps=list(range(1, 65)))
        rt.shutdown()  # drains the ring + staged rows exactly once
        assert n[0] == sum(1 for r in rows if r[1] > 50.0)


class TestAsyncCallbacks:
    def test_results_match_sync(self):
        rows = _rows(64)

        def feed(rt):
            rt.get_input_handler("TradeStream").send_batch(
                rows, timestamps=list(range(1, 65)))
            rt.flush()

        sync = _collect(FILTER_APP, feed)
        async_ = _collect(FILTER_APP, feed, async_callbacks=True)
        assert sync == async_ and len(sync) > 0

    def test_drain_is_barrier(self):
        rows = _rows(256)
        rt = SiddhiManager().create_siddhi_app_runtime(
            FILTER_APP, batch_size=32, async_callbacks=True)
        n = [0]
        rt.add_callback("OutStream", lambda evs: n.__setitem__(0, n[0] + len(evs)))
        rt.start()
        rt.get_input_handler("TradeStream").send_batch(
            rows, timestamps=list(range(1, 257)))
        rt.drain()
        expect = sum(1 for r in rows if r[1] > 50.0)
        assert n[0] == expect
        rt.shutdown()

    def test_shutdown_flushes_decoder(self):
        rows = _rows(32)
        rt = SiddhiManager().create_siddhi_app_runtime(
            FILTER_APP, batch_size=32, async_callbacks=True)
        n = [0]
        rt.add_callback("OutStream", lambda evs: n.__setitem__(0, n[0] + len(evs)))
        rt.start()
        rt.get_input_handler("TradeStream").send_batch(rows, timestamps=1)
        rt.flush()
        rt.shutdown()  # stop() waits for the queue to empty
        assert n[0] == sum(1 for r in rows if r[1] > 50.0)


class TestColumnarCallbacks:
    """ColumnarBlock delivery — the batch-level Event[] analogue
    (reference: StreamCallback.java:38 receives Event[] per chunk)."""

    def _run(self, async_cb: bool):
        rt = SiddhiManager().create_siddhi_app_runtime(
            FILTER_APP, batch_size=32, async_callbacks=async_cb)
        blocks = []
        rt.add_callback("OutStream", blocks.append, columnar=True)
        rt.start()
        rows = _rows(64)
        rt.get_input_handler("TradeStream").send_batch(
            rows, timestamps=list(range(1, 65)))
        rt.flush()
        rt.drain()
        rt.shutdown()
        return rows, blocks

    @pytest.mark.parametrize("async_cb", [False, True])
    def test_block_contents_match_rows(self, async_cb):
        rows, blocks = self._run(async_cb)
        expect = [r for r in rows if r[1] > 50.0]
        got_n = sum(b.count for b in blocks)
        assert got_n == len(expect)
        syms = [s for b in blocks for s in b.strings("symbol")]
        assert syms == [r[0] for r in expect]
        prices = np.concatenate([b.column("price") for b in blocks])
        assert np.allclose(prices, [r[1] for r in expect], rtol=1e-6)

    def test_to_events_matches_event_callback(self):
        rows, blocks = self._run(False)
        evs = [e for b in blocks for e in b.to_events()]
        rt = SiddhiManager().create_siddhi_app_runtime(
            FILTER_APP, batch_size=32)
        got = []
        rt.add_callback("OutStream", got.extend)
        rt.start()
        rt.get_input_handler("TradeStream").send_batch(
            rows, timestamps=list(range(1, 65)))
        rt.flush()
        rt.shutdown()
        assert [(e.timestamp, e.data) for e in evs] == \
            [(e.timestamp, e.data) for e in got]

    def test_send_columns_roundtrip_groupby(self):
        rt = SiddhiManager().create_siddhi_app_runtime(
            GROUP_APP, batch_size=16, async_callbacks=True)
        blocks = []
        rt.add_callback("OutStream", blocks.append, columnar=True)
        rt.start()
        pool = np.array(["A", "B"], dtype=object)
        rt.get_input_handler("TradeStream").send_columns({
            "symbol": pool[np.array([0, 1] * 8)],
            "price": np.arange(1.0, 17.0),
            "volume": np.ones(16, np.int64),
        }, timestamps=np.arange(1, 17, dtype=np.int64))
        rt.flush()
        rt.drain()
        rt.shutdown()
        # lengthBatch(8) flushed twice; last CURRENT lane of each flush per
        # group carries the group's running sum
        assert sum(b.count for b in blocks) > 0
        syms = [s for b in blocks for s in b.strings("symbol")]
        assert set(syms) <= {"A", "B"}
