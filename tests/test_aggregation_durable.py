"""Durable aggregation stores + restart rebuild (reference:
core/aggregation/IncrementalExecutorsInitialiser.java — on restart,
in-memory buckets rebuild from the per-duration tables the aggregation
persisted; VERDICT r02 missing item 6)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.io.record_table import InMemoryRecordStore

APP = """
define stream TradeStream (symbol string, price double, ts long);
@store(type='durable')
define aggregation TradeAgg
from TradeStream
select symbol, sum(price) as total, count() as n
group by symbol
aggregate by ts every sec, min;
"""


class DurableStore(InMemoryRecordStore):
    """Class-level row persistence so a NEW app instance (a 'restart')
    sees what the previous one wrote — the role an RDBMS plays for the
    reference."""

    _tables: dict = {}

    def init(self, definition, properties, config_reader=None):
        super().init(definition, properties, config_reader)
        self.rows = list(DurableStore._tables.get(definition.id, []))

    def _sync(self):
        DurableStore._tables[self.definition.id] = list(self.rows)

    def add(self, rows):
        super().add(rows)
        self._sync()

    def delete(self, compiled):
        n = super().delete(compiled)
        self._sync()
        return n


def make_runtime():
    mgr = SiddhiManager()
    mgr.set_extension("durable", DurableStore)
    rt = mgr.create_siddhi_app_runtime(APP, batch_size=16)
    rt.start()
    return rt


class TestDurableAggregation:
    def setup_method(self):
        DurableStore._tables.clear()

    def test_flush_and_rebuild_across_restart(self):
        rt = make_runtime()
        h = rt.get_input_handler("TradeStream")
        for sym, p, t in [("A", 10.0, 100), ("B", 5.0, 200),
                          ("A", 7.0, 1500)]:
            h.send((sym, p, t))
        rt.flush()
        before = sorted(
            tuple(e.data) for e in rt.query(
                "from TradeAgg within 0, 10000 per 'sec' "
                "select symbol, total, n"))
        rt.shutdown()  # flushes the durable duration tables

        # durable tables hold the buckets
        assert len(DurableStore._tables["TradeAgg_sec"]) == 3

        # a fresh app instance rebuilds its device buckets from them
        rt2 = make_runtime()
        after = sorted(
            tuple(e.data) for e in rt2.query(
                "from TradeAgg within 0, 10000 per 'sec' "
                "select symbol, total, n"))
        assert after == before
        assert len(after) == 3
        rt2.shutdown()

    def test_rebuilt_buckets_keep_accumulating(self):
        rt = make_runtime()
        h = rt.get_input_handler("TradeStream")
        h.send(("A", 10.0, 100))
        rt.flush()
        rt.shutdown()

        rt2 = make_runtime()
        h2 = rt2.get_input_handler("TradeStream")
        h2.send(("A", 2.0, 300))  # same second bucket as the restored row
        rt2.flush()
        rows = rt2.query("from TradeAgg within 0, 10000 per 'sec' "
                         "select symbol, total, n")
        assert [tuple(e.data) for e in rows] == [
            ("A", pytest.approx(12.0), 2)]
        rt2.shutdown()

    def test_no_store_annotation_keeps_snapshot_only_path(self):
        app = APP.replace("@store(type='durable')\n", "")
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(app, batch_size=16)
        rt.start()
        rt.get_input_handler("TradeStream").send(("A", 1.0, 100))
        rt.flush()
        rt.shutdown()  # no durable store: nothing written, no error
        assert DurableStore._tables == {}
