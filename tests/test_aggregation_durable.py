"""Durable aggregation stores + restart rebuild (reference:
core/aggregation/IncrementalExecutorsInitialiser.java — on restart,
in-memory buckets rebuild from the per-duration tables the aggregation
persisted; VERDICT r02 missing item 6)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.io.record_table import InMemoryRecordStore

APP = """
define stream TradeStream (symbol string, price double, ts long);
@store(type='durable')
define aggregation TradeAgg
from TradeStream
select symbol, sum(price) as total, count() as n
group by symbol
aggregate by ts every sec, min;
"""


class DurableStore(InMemoryRecordStore):
    """Class-level row persistence so a NEW app instance (a 'restart')
    sees what the previous one wrote — the role an RDBMS plays for the
    reference."""

    _tables: dict = {}

    def init(self, definition, properties, config_reader=None):
        super().init(definition, properties, config_reader)
        self.rows = list(DurableStore._tables.get(definition.id, []))

    def _sync(self):
        DurableStore._tables[self.definition.id] = list(self.rows)

    def add(self, rows):
        super().add(rows)
        self._sync()

    def delete(self, compiled):
        n = super().delete(compiled)
        self._sync()
        return n


def make_runtime():
    mgr = SiddhiManager()
    mgr.set_extension("durable", DurableStore)
    rt = mgr.create_siddhi_app_runtime(APP, batch_size=16)
    rt.start()
    return rt


class TestDurableAggregation:
    def setup_method(self):
        DurableStore._tables.clear()

    def test_flush_and_rebuild_across_restart(self):
        rt = make_runtime()
        h = rt.get_input_handler("TradeStream")
        for sym, p, t in [("A", 10.0, 100), ("B", 5.0, 200),
                          ("A", 7.0, 1500)]:
            h.send((sym, p, t))
        rt.flush()
        before = sorted(
            tuple(e.data) for e in rt.query(
                "from TradeAgg within 0, 10000 per 'sec' "
                "select symbol, total, n"))
        rt.shutdown()  # flushes the durable duration tables

        # durable tables hold the buckets
        assert len(DurableStore._tables["TradeAgg_sec"]) == 3

        # a fresh app instance rebuilds its device buckets from them
        rt2 = make_runtime()
        after = sorted(
            tuple(e.data) for e in rt2.query(
                "from TradeAgg within 0, 10000 per 'sec' "
                "select symbol, total, n"))
        assert after == before
        assert len(after) == 3
        rt2.shutdown()

    def test_rebuilt_buckets_keep_accumulating(self):
        rt = make_runtime()
        h = rt.get_input_handler("TradeStream")
        h.send(("A", 10.0, 100))
        rt.flush()
        rt.shutdown()

        rt2 = make_runtime()
        h2 = rt2.get_input_handler("TradeStream")
        h2.send(("A", 2.0, 300))  # same second bucket as the restored row
        rt2.flush()
        rows = rt2.query("from TradeAgg within 0, 10000 per 'sec' "
                         "select symbol, total, n")
        assert [tuple(e.data) for e in rows] == [
            ("A", pytest.approx(12.0), 2)]
        rt2.shutdown()

    def test_no_store_annotation_keeps_snapshot_only_path(self):
        app = APP.replace("@store(type='durable')\n", "")
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(app, batch_size=16)
        rt.start()
        rt.get_input_handler("TradeStream").send(("A", 1.0, 100))
        rt.flush()
        rt.shutdown()  # no durable store: nothing written, no error
        assert DurableStore._tables == {}


class TestShardedDurableRebuild:
    """VERDICT r3 item 7 (second half): durable rebuild on a mesh must
    RE-SCATTER restored rows by group hash (the sharded ingest's ownership
    rule), not land everything on shard 0."""

    def setup_method(self):
        DurableStore._tables = {}

    def _mesh(self, n=8):
        import jax
        import numpy as np
        from jax.sharding import Mesh
        return Mesh(np.asarray(jax.devices()[:n]), ("part",))

    def _make(self, mesh):
        mgr = SiddhiManager()
        mgr.set_extension("durable", DurableStore)
        rt = mgr.create_siddhi_app_runtime(
            APP, batch_size=16, group_capacity=64, mesh=mesh)
        rt.start()
        return rt

    def test_rebuild_balances_shards_and_reads_exact(self):
        import numpy as np
        rt = self._make(self._mesh())
        h = rt.get_input_handler("TradeStream")
        rng = np.random.default_rng(5)
        for k, p, t in zip(rng.integers(0, 16, 64),
                           rng.uniform(1, 100, 64),
                           rng.integers(0, 5000, 64)):
            h.send((f"S{int(k)}", float(round(p, 2)), int(t)))
        rt.flush()
        q = "from TradeAgg within 0, 10000 per 'sec' select symbol, total, n"
        want = sorted(tuple(e.data) for e in rt.query(q))
        rt.shutdown()  # flushes durable duration tables

        rt2 = self._make(self._mesh())  # restart: rebuild from durable rows
        got = sorted(tuple(e.data) for e in rt2.query(q))
        assert len(got) == len(want) > 0
        for g, w in zip(got, want):
            assert g[0] == w[0] and g[2] == w[2]
            assert g[1] == pytest.approx(w[1], rel=1e-5)
        # balance: restored rows spread over multiple shards by group hash
        agg = rt2.aggregations["TradeAgg"]
        S = agg.n_shards
        alive = np.asarray(agg.state[0].alive).reshape(S, -1)
        per_shard = alive.sum(axis=1)
        assert (per_shard > 0).sum() >= 2, per_shard.tolist()
        assert per_shard[0] < per_shard.sum(), "all rows on shard 0"
        rt2.shutdown()
