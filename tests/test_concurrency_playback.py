"""Multi-threaded junction semantics + playback edge cases (VERDICT r3
weak #7: unmirrored reference families — multi-threaded junction tests
(core/stream/ junction suites) and playback TimestampGenerator cases)."""

import threading
import time

import pytest

from siddhi_tpu import SiddhiManager

S = "define stream S (k string, v long);\n"


class TestConcurrentProducers:
    def test_concurrent_send_batch_conserves_events(self):
        """N threads push batches through the @Async MPSC ring; every event
        is delivered exactly once (no loss, no duplication)."""
        app = ("@async(buffer.size='64')\n" + S +
               "@info(name='q') from S select k, v insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=32)
        got = []
        lock = threading.Lock()

        def cb(evs):
            with lock:
                got.extend(e.data for e in evs)

        rt.add_callback("Out", cb)
        rt.start()
        N_THREADS, PER = 4, 300

        def produce(t):
            h = rt.get_input_handler("S")
            for base in range(0, PER, 50):
                h.send_batch([(f"t{t}", t * PER + base + i)
                              for i in range(50)])

        threads = [threading.Thread(target=produce, args=(t,))
                   for t in range(N_THREADS)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        deadline = time.time() + 30
        while time.time() < deadline:
            rt.flush()
            with lock:
                if len(got) >= N_THREADS * PER:
                    break
            time.sleep(0.05)
        rt.shutdown()
        assert len(got) == N_THREADS * PER
        assert len({(k, v) for k, v in got}) == N_THREADS * PER  # no dupes

    def test_concurrent_producers_with_aggregation(self):
        """Per-key counts survive concurrent interleaving: the controller
        lock serializes device steps, so each thread's events all land."""
        app = ("@async(buffer.size='64')\n" + S +
               "@info(name='q') from S select k, count() as n group by k "
               "insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=32, group_capacity=64)
        latest = {}
        lock = threading.Lock()
        rt.add_callback("Out", lambda evs: [
            latest.__setitem__(e.data[0], e.data[1]) for e in evs])
        rt.start()

        def produce(t):
            h = rt.get_input_handler("S")
            for i in range(200):
                h.send((f"t{t}", i))

        threads = [threading.Thread(target=produce, args=(t,))
                   for t in range(3)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        deadline = time.time() + 30
        while time.time() < deadline:
            rt.flush()
            with lock:
                if all(latest.get(f"t{t}") == 200 for t in range(3)):
                    break
            time.sleep(0.05)
        rt.shutdown()
        assert {k: latest[k] for k in sorted(latest)} == {
            "t0": 200, "t1": 200, "t2": 200}

    def test_async_callbacks_with_concurrent_producers(self):
        """@Async ingestion + async decode pipeline together: drain() is a
        complete barrier across both."""
        app = ("@async(buffer.size='64')\n" + S +
               "@info(name='q') from S select k, v insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=32, async_callbacks=True)
        n = [0]
        lock = threading.Lock()

        def cb(evs):
            with lock:
                n[0] += len(evs)

        rt.add_callback("Out", cb)
        rt.start()

        def produce(t):
            rt.get_input_handler("S").send_batch(
                [(f"t{t}", i) for i in range(250)])

        threads = [threading.Thread(target=produce, args=(t,))
                   for t in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        deadline = time.time() + 30
        while time.time() < deadline:
            rt.drain()
            with lock:
                if n[0] >= 1000:
                    break
            time.sleep(0.05)
        rt.shutdown()
        assert n[0] == 1000


class TestPlaybackEdgeCases:
    def test_idle_time_increment_advances_windows(self):
        """@app:playback(idle.time, increment): a bare heartbeat() bumps the
        virtual clock by the increment (reference:
        TimestampGeneratorImpl.java:92-131), draining time windows."""
        app = ("@app:playback(idle.time='100 millisecond', "
               "increment='2 sec')\n" + S +
               "@info(name='q') from S#window.time(1 sec) "
               "select k, sum(v) as total insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(
            tuple(e.data) for e in i or []))
        rt.start()
        rt.get_input_handler("S").send(("a", 5), timestamp=1_000)
        rt.flush()
        assert got == [("a", 5)]
        del got[:]
        rt.heartbeat()  # virtual clock 1000 -> 3000: window drains
        rt.get_input_handler("S").send(("a", 7), timestamp=3_100)
        rt.flush()
        # the old 5 expired with the idle bump: sum restarts
        assert got == [("a", 7)]
        rt.shutdown()

    def test_watermark_never_regresses_on_late_events(self):
        """A late (out-of-order) timestamp must not rewind the virtual clock
        or re-open expired windows."""
        app = ("@app:playback\n" + S +
               "@info(name='q') from S#window.time(1 sec) "
               "select k, sum(v) as total insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(
            tuple(e.data) for e in i or []))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("a", 1), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=5_000)  # first event expired
        del got[:]
        h.send(("late", 2), timestamp=2_000)  # older than the watermark
        rt.flush()
        # the late event aggregates alone — the expired 1 must not return
        # (the device watermark holds even though the virtual clock follows
        # observed events only; explicit-now heartbeats are test plumbing)
        assert got == [("late", 2)]
        rt.shutdown()

    def test_virtual_clock_survives_snapshot_restore(self):
        app = ("@app:playback\n" + S +
               "@info(name='q') from S select k, v insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)
        rt.start()
        rt.get_input_handler("S").send(("a", 1), timestamp=7_777)
        rt.flush()
        blob = rt.snapshot()
        rt2 = SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)
        rt2.restore(blob)
        assert rt2.ctx.timestamp_generator.current_time() == 7_777
