"""Record-table SPI + @cache tests (reference: the store/ and
query/table cache test blocks — AbstractRecordTable extension contract,
CacheTableFIFO/LRU/LFU policies)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError
from siddhi_tpu.extension.registry import ExtensionKind
from siddhi_tpu.io.record_table import InMemoryRecordStore, RecordStore


pytestmark = pytest.mark.smoke

APP = """
define stream S (sym string, price double);
@store(type='inMemory')
define table T (sym string, price double);
from S select sym, price insert into T;
"""


def build(app, **kw):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(app, **kw)
    rt.start()
    return rt


class TestRecordStoreSPI:
    def test_insert_and_on_demand_find(self):
        rt = build(APP)
        h = rt.get_input_handler("S")
        h.send(("IBM", 75.0))
        h.send(("WSO2", 57.0))
        rt.flush()
        rows = rt.query("from T on price > 60.0 select sym, price")
        assert [r.data for r in rows] == [("IBM", 75.0)]
        # the store is the authority
        store = rt.tables["T"].store
        assert len(store.rows) == 2

    def test_on_demand_crud(self):
        rt = build("@store(type='inMemory')\n"
                   "define table T (sym string, price double);")
        rt.query("select 'a' as sym, 1.0 as price update or insert into T "
                 "on T.sym == 'a'")
        rt.query("select 'b' as sym, 2.0 as price update or insert into T "
                 "on T.sym == 'b'")
        assert sorted(rt.tables["T"].all_rows()) == [("a", 1.0), ("b", 2.0)]
        rt.query("update T set T.price = T.price * 10.0 on T.sym == 'a'")
        assert ("a", 10.0) in rt.tables["T"].all_rows()
        rt.query("delete T on T.sym == 'b'")
        assert rt.tables["T"].all_rows() == [("a", 10.0)]

    def test_query_output_crud(self):
        rt = build("define stream S (sym string, price double);\n"
                   "@store(type='inMemory')\n"
                   "define table T (sym string, price double);\n"
                   "from S select sym, price update or insert into T "
                   "on T.sym == sym;")
        h = rt.get_input_handler("S")
        h.send(("a", 1.0))
        rt.flush()
        h.send(("a", 5.0))  # updates, not duplicates
        h.send(("b", 2.0))
        rt.flush()
        assert sorted(rt.tables["T"].all_rows()) == [("a", 5.0), ("b", 2.0)]

    def test_custom_store_via_set_extension(self):
        calls = []

        class AuditedStore(InMemoryRecordStore):
            def add(self, rows):
                calls.append(("add", len(rows)))
                super().add(rows)

            def find(self, compiled):
                calls.append(("find", None))
                return super().find(compiled)

        mgr = SiddhiManager()
        mgr.set_extension("audited", AuditedStore)
        rt = mgr.create_siddhi_app_runtime(
            "define stream S (k int);\n"
            "@store(type='audited')\n"
            "define table T (k int);\n"
            "from S select k insert into T;")
        rt.start()
        rt.get_input_handler("S").send((7,))
        rt.flush()
        rows = rt.query("from T select k")
        assert [r.data for r in rows] == [(7,)]
        assert ("add", 1) in calls and ("find", None) in calls

    def test_store_properties_passed(self):
        seen = {}

        class PropStore(InMemoryRecordStore):
            def init(self, definition, properties, config_reader=None):
                seen.update(properties)
                super().init(definition, properties, config_reader)

        mgr = SiddhiManager()
        mgr.set_extension("propStore", PropStore)
        rt = mgr.create_siddhi_app_runtime(
            "@store(type='propStore', uri='fake://host', mode='rw')\n"
            "define table T (k int);")
        rt.start()
        assert seen == {"uri": "fake://host", "mode": "rw"}


class TestRecordTableCache:
    CACHED = """
    define stream S (sym string, price double);
    define stream Q (sym string);
    @store(type='inMemory')
    @cache(size='2', policy='{policy}')
    @PrimaryKey('sym')
    define table T (sym string, price double);
    from S select sym, price insert into T;
    @info(name='j') from Q join T on Q.sym == T.sym
    select Q.sym as sym, T.price as price insert into Out;
    """

    def _joined(self, rt, sym):
        got = []
        rt.add_query_callback("j", lambda ts, i, r: got.extend(
            tuple(e.data) for e in i or []))
        rt.get_input_handler("Q").send((sym,))
        rt.flush()
        return got

    def test_join_reads_cache_at_device_speed(self):
        rt = build(self.CACHED.format(policy="FIFO"))
        h = rt.get_input_handler("S")
        h.send(("a", 1.0))
        h.send(("b", 2.0))
        rt.flush()
        assert self._joined(rt, "b") == [("b", 2.0)]

    def test_fifo_eviction(self):
        rt = build(self.CACHED.format(policy="FIFO"))
        h = rt.get_input_handler("S")
        for i, sym in enumerate(["a", "b", "c"]):  # size 2: 'a' evicted
            h.send((sym, float(i)))
            rt.flush()
        cp = rt.tables["T"].cache_policy
        assert [k[0] for k in cp.rows] == ["b", "c"]
        # the store still has all three (cache is a view, not the authority)
        assert len(rt.tables["T"].store.rows) == 3
        # a miss served by the store read-through re-populates the cache
        rows = rt.query("from T on sym == 'a' select sym, price")
        assert [r.data for r in rows] == [("a", 0.0)]
        assert ("a",) in cp.rows

    def test_lru_eviction_prefers_recently_read(self):
        rt = build(self.CACHED.format(policy="LRU"))
        h = rt.get_input_handler("S")
        h.send(("a", 1.0))
        h.send(("b", 2.0))
        rt.flush()
        # touch 'a' via a read-through find, then insert 'c': 'b' evicts
        rt.query("from T on sym == 'a' select sym")
        h.send(("c", 3.0))
        rt.flush()
        cp = rt.tables["T"].cache_policy
        assert sorted(k[0] for k in cp.rows) == ["a", "c"]

    def test_lfu_eviction_prefers_frequent(self):
        rt = build(self.CACHED.format(policy="LFU"))
        h = rt.get_input_handler("S")
        h.send(("a", 1.0))
        h.send(("b", 2.0))
        rt.flush()
        for _ in range(3):
            rt.query("from T on sym == 'a' select sym")
        h.send(("c", 3.0))
        rt.flush()
        cp = rt.tables["T"].cache_policy
        assert sorted(k[0] for k in cp.rows) == ["a", "c"]

    def test_uncached_join_rejected_with_guidance(self):
        with pytest.raises(SiddhiAppCreationError, match="@cache"):
            build("define stream Q (sym string);\n"
                  "@store(type='inMemory')\n"
                  "define table T (sym string, price double);\n"
                  "from Q join T on Q.sym == T.sym "
                  "select Q.sym as s insert into Out;")


class TestStoreFallbackOnEviction:
    """Probes against a cached @store table stay CORRECT when the store
    outgrows the cache (VERDICT r3 item 2; reference:
    AbstractQueryableRecordTable.java:109,207-238 — cache misses fall back
    to the backing store). The runtimes pre-warm the cache with each batch's
    probe keys via RecordTableRuntime.ensure_cached_for_keys."""

    CACHED = TestRecordTableCache.CACHED

    def _fill_abc(self, rt):
        h = rt.get_input_handler("S")
        for i, sym in enumerate(["a", "b", "c"]):  # size 2: 'a' evicted
            h.send((sym, float(i)))
            rt.flush()
        assert [k[0] for k in rt.tables["T"].cache_policy.rows] == ["b", "c"]

    def test_join_correct_past_eviction(self):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(self.CACHED.format(policy="FIFO"))
            self._fill_abc(rt)
            got = []
            rt.add_query_callback("j", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            # 'a' was evicted from the device cache: the pre-step read-through
            # must reload it from the store so the join matches
            rt.get_input_handler("Q").send(("a",))
            rt.flush()
        assert got == [("a", 0.0)]

    def test_join_probe_mixes_cached_and_evicted_keys(self):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(self.CACHED.format(policy="FIFO"))
            self._fill_abc(rt)
            got = []
            rt.add_query_callback("j", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            q = rt.get_input_handler("Q")
            for sym in ("a", "c", "zz"):  # evicted + cached + absent
                q.send((sym,))
            rt.flush()
        assert sorted(got) == [("a", 0.0), ("c", 2.0)]

    def test_warm_does_not_evict_same_batch_probe_key(self):
        # size-2 FIFO cache holds {b, c} (head = b); one batch probes
        # {a, b}. Warming 'a' from the store must NOT evict 'b' — the
        # working set of the probing batch is protected during the warm
        # (advisor round-4 high finding)
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(self.CACHED.format(policy="FIFO"))
            self._fill_abc(rt)
            cp = rt.tables["T"].cache_policy
            assert [k[0] for k in cp.rows] == ["b", "c"]
            got = []
            rt.add_query_callback("j", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            q = rt.get_input_handler("Q")
            q.send(("a",))
            q.send(("b",))
            rt.flush()
        assert sorted(got) == [("a", 0.0), ("b", 1.0)]
        # 'c' (not probed) was the eviction victim, not 'b'
        assert set(k[0] for k in cp.rows) == {"a", "b"}

    def test_non_equi_probe_exact_past_eviction(self):
        # S.k > T.k (non-equi): the condition-based store fallback
        # (ensure_cached_for_condition) must reload the EVICTED matching
        # row before the device probe (reference:
        # AbstractQueryableRecordTable.java:207-238 queries the store with
        # streamVariable parameters on every cache miss)
        import warnings as _w
        app = """
        define stream S (v int);
        define stream Q (v int);
        @store(type='inMemory') @cache(size='2', policy='FIFO')
        @PrimaryKey('k')
        define table T (k int, w double);
        from S select v as k, 1.0 as w insert into T;
        @info(name='j') from Q join T on Q.v > T.k
        select Q.v as qv, T.k as tk insert into Out;
        """
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(app)
            h = rt.get_input_handler("S")
            for k in (10, 20, 30):  # size-2 cache: 10 evicted
                h.send((k,))
                rt.flush()
            got = []
            rt.add_query_callback("j", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            rt.get_input_handler("Q").send((15,))  # matches ONLY evicted 10
            rt.flush()
            assert sorted(got) == [(15, 10)], got
            got.clear()
            rt.get_input_handler("Q").send((25,))  # matches 10 and 20
            rt.flush()
            assert sorted(got) == [(25, 10), (25, 20)], got

    def test_outer_join_null_only_for_true_non_matches(self):
        app = """
        define stream S (sym string, price double);
        define stream Q (sym string);
        @store(type='inMemory')
        @cache(size='2', policy='FIFO')
        @PrimaryKey('sym')
        define table T (sym string, price double);
        from S select sym, price insert into T;
        @info(name='j') from Q left outer join T on Q.sym == T.sym
        select Q.sym as sym, T.price as price insert into Out;
        """
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(app)
            self._fill_abc(rt)
            got = []
            rt.add_query_callback("j", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            q = rt.get_input_handler("Q")
            q.send(("a",))   # evicted: must match via fallback, NOT null
            q.send(("zz",))  # absent: genuine null row (numeric null -> 0)
            rt.flush()
        assert sorted(got, key=str) == [("a", 0.0), ("zz", 0.0)]
        # distinguishability check: 'a' matched via the store (price 0.0 is
        # its REAL value), 'zz' is the null row — prove the fallback matched
        # by probing a non-zero evicted price
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt.get_input_handler("S").send(("d", 7.0))  # evicts 'b'
            rt.flush()
            assert "b" not in [k[0]
                               for k in rt.tables["T"].cache_policy.rows]
            got.clear()
            rt.get_input_handler("Q").send(("b",))
            rt.flush()
        assert got == [("b", 1.0)]

    def test_in_probe_correct_past_eviction(self):
        app = """
        define stream S (sym string, price double);
        define stream C (sym string);
        @store(type='inMemory')
        @cache(size='2', policy='FIFO')
        @PrimaryKey('sym')
        define table T (sym string, price double);
        from S select sym, price insert into T;
        @info(name='chk') from C[C.sym == T.sym in T]
        select sym insert into Out;
        """
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(app)
            self._fill_abc(rt)
            got = []
            rt.add_callback("Out", lambda evs: got.extend(
                e.data[0] for e in evs))
            c = rt.get_input_handler("C")
            for sym in ("a", "zz", "c"):
                c.send((sym,))
            rt.flush()
        assert got == ["a", "c"]

    def test_absent_key_memo_invalidated_by_store_write(self):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(self.CACHED.format(policy="FIFO"))
            self._fill_abc(rt)
            got = []
            rt.add_query_callback("j", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            q = rt.get_input_handler("Q")
            q.send(("zz",))  # absent: memoized as not-in-store
            rt.flush()
            assert got == []
            rt.get_input_handler("S").send(("zz", 9.0))  # store write
            rt.flush()
            q.send(("zz",))
            rt.flush()
        assert got == [("zz", 9.0)]

    def test_float_key_fallback_matches_past_eviction(self):
        """FLOAT join keys round-trip through the device as f32; the store
        read-through must compare in device space or evicted float-keyed
        rows would silently miss (and be memoized absent)."""
        app = """
        define stream S (sym string, price double);
        define stream Q (price double);
        @store(type='inMemory')
        @cache(size='2', policy='FIFO')
        @PrimaryKey('sym')
        define table T (sym string, price double);
        from S select sym, price insert into T;
        @info(name='j') from Q join T on Q.price == T.price
        select T.sym as sym insert into Out;
        """
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(app)
            h = rt.get_input_handler("S")
            # 0.1 is inexact in binary: full-precision store value vs f32
            # probe value differ unless normalized
            for sym, p in [("a", 0.1), ("b", 0.2), ("c", 0.3)]:
                h.send((sym, p))
                rt.flush()
            assert [k[0] for k in rt.tables["T"].cache_policy.rows] == \
                ["b", "c"]
            got = []
            rt.add_query_callback("j", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            rt.get_input_handler("Q").send((0.1,))  # 'a' evicted
            rt.flush()
        assert got == [("a",)]

    def test_overflow_warning_mentions_read_through(self):
        import warnings as _w
        rt = build(self.CACHED.format(policy="FIFO"))
        with _w.catch_warnings(record=True) as caught:
            _w.simplefilter("always")
            self._fill_abc(rt)
        texts = [str(w.message) for w in caught]
        assert any("read-through" in t for t in texts)


class TestRecordTablePersistence:
    def test_persist_restore_skips_external_store(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "define stream S (k int);\n"
            "@store(type='inMemory')\n"
            "define table T (k int);\n"
            "from S select k insert into T;")
        rt.start()
        rt.get_input_handler("S").send((1,))
        rt.flush()
        blob = rt.snapshot()
        rt.restore(blob)
        # store rows survive independently of engine snapshots
        assert rt.tables["T"].all_rows() == [(1,)]


class TestRecordStoreOnDemandQueries:
    """Deeper store-query block over record tables (reference:
    store/OnDemandQueryTableTestCase shapes run against @store tables):
    aggregation, group-by, having, order-by/limit, and pushdown counting."""

    def _loaded(self):
        rt = build(APP)
        h = rt.get_input_handler("S")
        for sym, p in [("IBM", 75.0), ("WSO2", 57.0), ("IBM", 25.0),
                       ("GOOG", 90.0), ("WSO2", 63.0)]:
            h.send((sym, p))
        rt.flush()
        return rt

    def test_aggregate_over_store(self):
        rt = self._loaded()
        rows = rt.query("from T select count() as n, sum(price) as total")
        assert [r.data for r in rows] == [(5, pytest.approx(310.0))]

    def test_group_by_having(self):
        rt = self._loaded()
        rows = rt.query("from T select sym, sum(price) as total "
                        "group by sym having total > 100.0")
        # IBM: 100.0 (excluded by >), WSO2: 120.0, GOOG: 90.0
        assert [r.data for r in rows] == [("WSO2", pytest.approx(120.0))]

    def test_order_by_limit(self):
        rt = self._loaded()
        rows = rt.query("from T select sym, price "
                        "order by price desc limit 2")
        assert [r.data for r in rows] == [("GOOG", 90.0), ("IBM", 75.0)]

    def test_condition_pushdown_reaches_store(self):
        rt = self._loaded()
        store = rt.tables["T"].store
        before = len(getattr(store, "find_calls", []))
        rows = rt.query("from T on sym == 'IBM' select sym, price")
        assert sorted(r.data for r in rows) == [("IBM", 25.0), ("IBM", 75.0)]
        calls = getattr(store, "find_calls", None)
        if calls is not None:  # SPI records pushdown visits
            assert len(calls) > before

    def test_on_demand_insert_into_store(self):
        rt = build(APP)
        rt.query("select 'NEW' as sym, 5.0 as price insert into T")
        assert ("NEW", 5.0) in rt.tables["T"].all_rows()

    def test_within_like_range_condition(self):
        rt = self._loaded()
        rows = rt.query("from T on price >= 57.0 and price <= 75.0 "
                        "select sym, price")
        assert sorted(r.data for r in rows) == [
            ("IBM", 75.0), ("WSO2", 57.0), ("WSO2", 63.0)]


class TestCachePolicyMatrix:
    """FIFO/LRU/LFU x join / `in` / on-demand probes past eviction
    (reference: the query/table cache suite's policy matrix)."""

    APP = """
    define stream S (sym string, price double);
    define stream Q (sym string);
    @store(type='inMemory')
    @cache(size='2', policy='{policy}')
    @PrimaryKey('sym')
    define table T (sym string, price double);
    from S select sym, price insert into T;
    @info(name='j') from Q join T on Q.sym == T.sym
    select Q.sym as sym, T.price as price insert into OutJ;
    @info(name='i') from Q[Q.sym in T] select sym insert into OutI;
    """

    def _fill(self, rt):
        h = rt.get_input_handler("S")
        for i, sym in enumerate(["a", "b", "c"]):  # size-2: one evicted
            h.send((sym, float(i)))
            rt.flush()

    @pytest.mark.parametrize("policy", ["FIFO", "LRU", "LFU"])
    def test_join_probe_exact_past_eviction(self, policy):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(self.APP.format(policy=policy))
            self._fill(rt)
            got = []
            rt.add_query_callback("j", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            q = rt.get_input_handler("Q")
            # per-batch working set stays within the cache size (the
            # documented guarantee); each batch's probe keys re-warm
            for sym in ("a", "b", "c"):
                q.send((sym,))
                rt.flush()
        assert sorted(got) == [("a", 0.0), ("b", 1.0), ("c", 2.0)]

    @pytest.mark.parametrize("policy", ["FIFO", "LRU", "LFU"])
    def test_in_probe_exact_past_eviction(self, policy):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(self.APP.format(policy=policy))
            self._fill(rt)
            got = []
            rt.add_query_callback("i", lambda ts, i, r: got.extend(
                tuple(e.data) for e in i or []))
            q = rt.get_input_handler("Q")
            for sym in ("a", "zz", "c"):
                q.send((sym,))
                rt.flush()
        assert sorted(got) == [("a",), ("c",)]

    @pytest.mark.parametrize("policy", ["FIFO", "LRU", "LFU"])
    def test_ondemand_reads_store_past_eviction(self, policy):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(self.APP.format(policy=policy))
            self._fill(rt)
        rows = rt.query("from T select sym, price")
        assert sorted(r.data for r in rows) == [
            ("a", 0.0), ("b", 1.0), ("c", 2.0)]
