"""Shard-plane crash proof: SIGKILL the whole plane process mid-stream,
restart on the same per-shard WAL layout, recover, keep streaming — the
final per-key aggregates must match a no-crash oracle exactly.

The worker (tests/shard_crash_worker.py) acknowledges every command before
blocking on stdin, so SIGKILL lands while the plane is idle with a known
journaled set (the tests/crash_worker.py discipline). The oracle is the
LAST emitted row per key, not the output multiset: recovery replays each
shard's journal at-least-once, so rows re-emit — but a running per-key
aggregate is monotone in its input prefix, so the last row per key is the
final state, and THAT must be exact.

Covers the recovery shapes the plane adds over single-runtime recovery:
whole-fleet restart from per-shard WAL dirs, a single shard dying and
recovering in-process while the rest of the fleet keeps serving, and a
post-recovery forced rebalance (epoch bump re-routing the replayed
journal) that must not lose or double-count state.
"""

import os
import signal
import subprocess
import sys
import threading

import pytest

pytestmark = pytest.mark.smoke

WORKER = os.path.join(os.path.dirname(__file__), "shard_crash_worker.py")


class _Worker:
    """One plane subprocess with a watchdog so a wedged child fails the
    test instead of hanging the suite."""

    def __init__(self, base: str, timeout_s: float = 300.0):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": repo + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        self.proc = subprocess.Popen(
            [sys.executable, WORKER, base],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, bufsize=1, env=env)
        self._watchdog = threading.Timer(timeout_s, self.proc.kill)
        self._watchdog.daemon = True
        self._watchdog.start()
        self.expect("READY")

    def expect(self, prefix: str) -> str:
        while True:
            line = self.proc.stdout.readline()
            if not line:
                raise AssertionError(
                    f"worker died waiting for {prefix!r} "
                    f"(rc={self.proc.poll()})")
            if line.startswith(prefix):
                return line.strip()

    def cmd(self, line: str, reply_prefix: str) -> str:
        self.proc.stdin.write(line + "\n")
        self.proc.stdin.flush()
        return self.expect(reply_prefix)

    def result(self) -> dict:
        import json
        return json.loads(self.cmd("result", "RESULT")[len("RESULT "):])

    def sigkill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait()
        self._watchdog.cancel()

    def close(self) -> None:
        try:
            self.cmd("exit", "BYE")
        finally:
            self.proc.wait()
            self._watchdog.cancel()


def _oracle(tmp_path, sends) -> dict:
    w = _Worker(str(tmp_path / "oracle"))
    for lo, hi in sends:
        w.cmd(f"send {lo} {hi}", f"OK {hi}")
    out = w.result()
    w.close()
    return out


def test_sigkill_whole_plane_then_recover(tmp_path):
    want = _oracle(tmp_path, [(0, 40), (40, 80)])

    base = str(tmp_path / "crash")
    w = _Worker(base)
    w.cmd("send 0 40", "OK 40")
    w.sigkill()  # idle kill: rows 0..39 are journaled, nothing in flight

    w2 = _Worker(base)  # fresh process, same per-shard WAL layout
    rec = w2.cmd("recover", "RECOVERED")
    assert int(rec.split()[1]) == 40  # every accepted row replays
    w2.cmd("send 40 80", "OK 80")
    got = w2.result()
    w2.close()
    assert got == want


def test_sigkill_one_shard_recovers_against_oracle(tmp_path):
    """One replica dies without shutdown while the fleet keeps serving:
    recover_shard rebuilds it from its OWN journal directory, and the
    merged final state matches the no-crash oracle."""
    want = _oracle(tmp_path, [(0, 40), (40, 60), (60, 80)])

    base = str(tmp_path / "chaos")
    w = _Worker(base)
    w.cmd("send 0 40", "OK 40")
    w.cmd("kill 1", "KILLED 1")
    rec = w.cmd("recover_shard 1", "SHARD-RECOVERED 1")
    replayed = int(rec.split()[2])
    assert replayed > 0  # the dead shard owned SOME of rows 0..39
    w.cmd("send 40 60", "OK 60")
    w.cmd("send 60 80", "OK 80")
    got = w.result()
    w.close()
    assert got == want


def test_recover_then_rebalance_then_stream(tmp_path):
    """Crash, recover, force an epoch-bumping rebalance (the replayed
    journal re-routes through the new assignment), keep streaming — state
    must survive BOTH transitions."""
    want = _oracle(tmp_path, [(0, 50), (50, 70), (70, 90)])

    base = str(tmp_path / "reb")
    w = _Worker(base)
    w.cmd("send 0 50", "OK 50")
    w.sigkill()

    w2 = _Worker(base)
    w2.cmd("recover", "RECOVERED")
    # the restarted router's skew counters start empty — the LPT proposal
    # only moves slots it has SEEN load on, so stream first, then rebalance
    w2.cmd("send 50 70", "OK 70")
    reb = w2.cmd("rebalance", "REBALANCED")
    assert int(reb.split()[1]) == 1  # epoch bumped
    w2.cmd("send 70 90", "OK 90")
    got = w2.result()
    w2.close()
    assert got == want
