"""End-to-end filter/projection query tests (model: reference
query/FilterTestCase1/2.java, PassThroughTestCase.java — black-box through the
public API: build SiddhiQL, send events, assert callback outputs)."""

import pytest

from siddhi_tpu import SiddhiManager



pytestmark = pytest.mark.smoke

@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run_app(mgr, app_text, stream, rows, out_stream="OutStream", batch_size=0):
    rt = mgr.create_siddhi_app_runtime(app_text, batch_size=batch_size)
    got = []
    rt.add_callback(out_stream, lambda events: got.extend(events))
    rt.start()
    h = rt.get_input_handler(stream)
    for i, row in enumerate(rows):
        h.send(row, timestamp=1000 + i)
    rt.flush()
    return [e.data for e in got]


STOCK = "define stream StockStream (symbol string, price float, volume long);\n"


class TestFilter:
    def test_greater_than(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream[price > 50.0] select symbol, price insert into OutStream;",
                      "StockStream",
                      [("IBM", 75.6, 100), ("WSO2", 10.0, 200), ("GOOG", 55.5, 300)])
        assert [r[0] for r in out] == ["IBM", "GOOG"]

    def test_compound_condition(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream[price > 20.0 and volume < 250] "
                      "select symbol insert into OutStream;",
                      "StockStream",
                      [("IBM", 75.6, 100), ("WSO2", 25.0, 500), ("GOOG", 21.0, 200)])
        assert [r[0] for r in out] == ["IBM", "GOOG"]

    def test_string_equality(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream[symbol == 'IBM'] select symbol, volume insert into OutStream;",
                      "StockStream",
                      [("IBM", 75.6, 100), ("WSO2", 10.0, 200), ("IBM", 30.0, 300)])
        assert out == [("IBM", 100), ("IBM", 300)]

    def test_string_inequality(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream[symbol != 'IBM'] select symbol insert into OutStream;",
                      "StockStream",
                      [("IBM", 75.6, 100), ("WSO2", 10.0, 200)])
        assert [r[0] for r in out] == ["WSO2"]

    def test_math_in_filter(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream[price * 2.0 >= 100.0] select symbol insert into OutStream;",
                      "StockStream",
                      [("A", 49.0, 1), ("B", 50.0, 2), ("C", 51.0, 3)])
        assert [r[0] for r in out] == ["B", "C"]

    def test_not_and_or(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream[not (price < 20.0) or volume == 999] "
                      "select symbol insert into OutStream;",
                      "StockStream",
                      [("A", 10.0, 999), ("B", 10.0, 1), ("C", 30.0, 1)])
        assert [r[0] for r in out] == ["A", "C"]

    def test_no_filter_passthrough(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream select symbol, price, volume insert into OutStream;",
                      "StockStream",
                      [("A", 1.0, 1), ("B", 2.0, 2)])
        assert len(out) == 2

    def test_mod_and_int_division(self, mgr):
        out = run_app(mgr,
                      "define stream S (a int, b int);\n"
                      "from S[a % b == 1] select a / b as q insert into OutStream;",
                      "S", [(7, 2), (8, 2), (9, 4)])
        assert out == [(3,), (2,)]


class TestProjection:
    def test_arithmetic_projection(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream select symbol, price * 2.0 as doubled, "
                      "volume + 10 as vol insert into OutStream;",
                      "StockStream", [("IBM", 75.5, 100)])
        assert out[0][0] == "IBM"
        assert out[0][1] == pytest.approx(151.0)
        assert out[0][2] == 110

    def test_select_star(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream select * insert into OutStream;",
                      "StockStream", [("IBM", 75.5, 100)])
        assert out[0][0] == "IBM" and out[0][2] == 100

    def test_type_promotion(self, mgr):
        out = run_app(mgr,
                      "define stream S (a int, b long, c float, d double);\n"
                      "from S select a + b as ab, c * d as cd insert into OutStream;",
                      "S", [(1, 2, 1.5, 2.0)])
        assert out[0][0] == 3
        assert out[0][1] == pytest.approx(3.0)

    def test_function_call(self, mgr):
        out = run_app(mgr,
                      "define stream S (a double);\n"
                      "from S select math:abs(a) as aa, ifThenElse(a > 0.0, 1, 0) as pos "
                      "insert into OutStream;",
                      "S", [(-2.5,), (3.5,)])
        assert out == [(2.5, 0), (3.5, 1)]

    def test_chained_queries_stay_on_device(self, mgr):
        out = run_app(mgr, STOCK +
                      "from StockStream[price > 10.0] select symbol, price insert into Mid;\n"
                      "from Mid[price > 50.0] select symbol insert into OutStream;",
                      "StockStream",
                      [("A", 5.0, 1), ("B", 20.0, 2), ("C", 60.0, 3)])
        assert [r[0] for r in out] == ["C"]

    def test_event_order_preserved_across_batches(self, mgr):
        rows = [("S%d" % i, float(i), i) for i in range(100)]
        out = run_app(mgr, STOCK +
                      "from StockStream select symbol, volume insert into OutStream;",
                      "StockStream", rows, batch_size=16)
        assert [r[1] for r in out] == list(range(100))
