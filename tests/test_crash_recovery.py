"""Crash-injection proof: SIGKILL a running app at seeded points mid-stream,
recover (restore last revision + WAL replay), and the output of a windowed
counting query must match a no-crash oracle exactly.

The worker (tests/crash_worker.py) is driven over stdin so the accepted-event
set at each kill point is deterministic: it acknowledges every command and
blocks on the next read, so SIGKILL lands while the engine is idle with a
known set of accepted (journaled) events. Three seeded kill points cover the
interesting recovery shapes:

  kill #1  after a persist + more sends      → restore + WAL suffix replay
  kill #2  after a recovery with NO persist  → replay relies on the WAL
                                               re-journaling its own replay
  kill #3  after another persist + sends     → rotation pruned the journal;
                                               restore + short suffix

Exactness (not just at-least-once) holds because persist() flushes staged
rows into the snapshot BEFORE rotating the journal, and no kill lands inside
persist() itself — so the replayed set is exactly the post-snapshot suffix.
"""

import os
import subprocess
import sys
import threading

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.state.wal import WriteAheadLog

pytestmark = pytest.mark.smoke

WORKER = os.path.join(os.path.dirname(__file__), "crash_worker.py")
EVENTS = 40


def _value(i: int) -> int:
    return (i * 7 + 3) % 101


class _Worker:
    """One engine subprocess with a watchdog so a wedged child fails the
    test instead of hanging the suite."""

    def __init__(self, base: str, timeout_s: float = 240.0,
                 extra_env: dict = None):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               **(extra_env or {})}
        self.proc = subprocess.Popen(
            [sys.executable, WORKER, base],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, bufsize=1, env=env)
        self._watchdog = threading.Timer(timeout_s, self.proc.kill)
        self._watchdog.daemon = True
        self._watchdog.start()
        self.expect("READY")

    def expect(self, prefix: str) -> str:
        while True:
            line = self.proc.stdout.readline()
            if not line:
                raise AssertionError(
                    f"worker died waiting for {prefix!r} "
                    f"(rc={self.proc.poll()})")
            if line.startswith(prefix):
                return line.strip()

    def cmd(self, line: str, reply_prefix: str) -> str:
        self.proc.stdin.write(line + "\n")
        self.proc.stdin.flush()
        return self.expect(reply_prefix)

    def send_range(self, lo: int, hi: int) -> None:
        for i in range(lo, hi):
            self.cmd(f"send {i}", f"OK {i}")

    def kill9(self) -> None:
        self._watchdog.cancel()
        self.proc.kill()  # SIGKILL — no atexit, no flush, no disconnect
        self.proc.wait()

    def close(self) -> None:
        self._watchdog.cancel()
        try:
            self.cmd("exit", "BYE")
        finally:
            self.proc.wait(timeout=30)


def test_sigkill_recovery_matches_no_crash_oracle(tmp_path):
    # ---- no-crash oracle: same engine, same events, zero faults
    w = _Worker(str(tmp_path / "oracle"))
    w.send_range(0, EVENTS)
    oracle = w.cmd("result", "RESULT")
    w.close()

    # the engine's sliding-window answer must itself be arithmetically right,
    # or the crash/no-crash comparison could pass on a shared wrong answer
    vals = [_value(i) for i in range(EVENTS)]
    assert oracle == f"RESULT 8 {sum(vals[-8:])}"

    base = str(tmp_path / "crash")
    # ---- phase 1: persist mid-stream, keep sending, then SIGKILL
    w = _Worker(base)
    w.send_range(0, 10)
    w.cmd("persist", "PERSISTED")
    w.send_range(10, 15)
    w.kill9()

    # ---- phase 2: recover (restore + replay 10..14), send, SIGKILL again
    # with NO persist in between — recovery #3 then leans on the WAL having
    # re-journaled its own replay
    w = _Worker(base)
    rec = w.cmd("recover", "RECOVERED").split()
    assert rec[1] != "None", "phase-2 recover should restore a revision"
    assert int(rec[2]) == 5  # events 10..14 came back from the journal
    w.send_range(15, 25)
    w.kill9()

    # ---- phase 3: recover (pure WAL for 10..24), persist, send, SIGKILL
    w = _Worker(base)
    rec = w.cmd("recover", "RECOVERED").split()
    assert int(rec[2]) == 15  # replayed 10..24: replay re-journals itself
    w.cmd("persist", "PERSISTED")
    w.send_range(25, 32)
    w.kill9()

    # ---- phase 4: final recovery, finish the stream, compare to oracle
    w = _Worker(base)
    rec = w.cmd("recover", "RECOVERED").split()
    assert int(rec[2]) == 7  # rotation pruned everything before the persist
    w.send_range(32, EVENTS)
    got = w.cmd("result", "RESULT")
    stats = w.cmd("stats", "STATS")
    w.close()

    assert got == oracle
    assert stats == "STATS 1 7"  # this process: one recovery, 7 replayed


# --------------------------------------------------------------------------- #
# WAL unit behavior (no subprocess)
# --------------------------------------------------------------------------- #


class TestWriteAheadLog:
    def test_torn_tail_stops_replay_cleanly(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), "App", fsync=False)
        wal.append_rows("S", [1, 2], [("a",), ("b",)])
        wal.append_rows("S", [3], [("c",)])
        wal.close()
        # crash mid-append: half a record at the tail
        seg = [f for f in os.listdir(tmp_path / "App") if f.endswith(".wal")]
        with open(tmp_path / "App" / seg[0], "ab") as f:
            f.write(b"\x40\x00\x00\x00\xde\xad\xbe\xefTORN")
        wal2 = WriteAheadLog(str(tmp_path), "App", fsync=False)
        recs = wal2.records()
        assert [r[2] for r in recs] == [[1, 2], [3]]  # whole records only
        # resuming truncated the tear: appends stay reachable
        wal2.append_rows("S", [4], [("d",)])
        assert [r[2] for r in wal2.records()] == [[1, 2], [3], [4]]
        wal2.close()

    def test_rotate_prunes_subsumed_segments(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path), "App", fsync=False)
        wal.append_rows("S", [1], [("a",)])
        wal.rotate("100_App")
        wal.append_rows("S", [2], [("b",)])
        segs = sorted(os.listdir(tmp_path / "App"))
        assert segs == ["00000001_100_App.wal"]
        assert [r[2] for r in wal.records()] == [[2]]
        wal.close()

    def test_replay_restores_original_timestamps_and_rejournals(
            self, tmp_path):
        app = ("@app:name('WApp')\n"
               "define stream S (v long);\n"
               "@info(name='q') from S select v insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=4, wal_dir=str(tmp_path))
        rt.start()
        rt.get_input_handler("S").send((1,), timestamp=111)
        rt.get_input_handler("S").send((2,), timestamp=222)
        rt.flush()
        # fresh runtime over the same journal (simulated restart)
        rt2 = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=4, wal_dir=str(tmp_path))
        got = []
        rt2.add_callback("Out", lambda evs: got.extend(
            (e.timestamp, tuple(e.data)) for e in evs))
        rt2.start()
        res = rt2.recover()
        assert res == {"revision": None, "wal_replayed": 2}
        assert got == [(111, (1,)), (222, (2,))]
        # replay re-journaled itself (record-for-record): a crash DURING
        # recovery still recovers
        assert [r[2] for r in rt2.wal.records()] == [[111], [222]]
        rt2.shutdown()

    def test_columnar_sends_journal_original_values(self, tmp_path):
        import numpy as np
        app = ("@app:name('CApp')\n"
               "define stream S (sym string, v long);\n"
               "@info(name='q') from S select sym, v insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=4, wal_dir=str(tmp_path))
        rt.start()
        rt.get_input_handler("S").send_columns(
            {"sym": np.array(["x", "y"], dtype=object),
             "v": np.array([5, 6])},
            timestamps=np.array([10, 11], dtype=np.int64))
        kind, sid, tss, cols = rt.wal.records()[-1]
        assert (kind, sid, tss) == ("cols", "S", [10, 11])
        assert list(cols["sym"]) == ["x", "y"]  # strings, not dict codes
        # a fresh process replays the columnar record
        rt2 = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=4, wal_dir=str(tmp_path))
        got = []
        rt2.add_callback("Out", lambda evs: got.extend(
            tuple(e.data) for e in evs))
        rt2.start()
        assert rt2.recover()["wal_replayed"] == 2
        assert got == [("x", 5), ("y", 6)]
        rt2.shutdown()

    def test_periodic_persistence_scheduler(self, tmp_path):
        import time
        from siddhi_tpu.state.persistence import InMemoryPersistenceStore
        mgr = SiddhiManager()
        store = InMemoryPersistenceStore()
        mgr.set_persistence_store(store)
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('PApp')\n"
            "define stream S (v long);\n"
            "from S select sum(v) as s insert into Out;",
            batch_size=4, persistence_interval_s=0.05)
        rt.start()
        rt.get_input_handler("S").send((1,))
        rt.flush()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and store.get_last_revision("PApp") is None:
            time.sleep(0.02)
        assert store.get_last_revision("PApp") is not None
        rt.shutdown()
        assert rt._persist_thread is None  # scheduler stopped with the app

    def test_shutdown_drains_staged_rows(self):
        """Rows accepted by send() but still below the batch threshold must
        flow at shutdown, not silently vanish (core/stream.py staging)."""
        rt = SiddhiManager().create_siddhi_app_runtime(
            "@app:name('DrainApp')\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;", batch_size=100)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send((i,))  # staged: 3 < batch_size, no flush
        rt.shutdown()
        assert [g[0] for g in got] == [0, 1, 2]
        assert rt.statistics_report()["recovery"]["shutdown_discarded"] == 0

    def test_shutdown_counts_undrainable_rows(self):
        """When the drain itself fails (a raising subscriber, no @OnError),
        the loss is counted and reported — never a silent zero."""
        rt = SiddhiManager().create_siddhi_app_runtime(
            "@app:name('DrainApp2')\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;", batch_size=100)

        def boom(evs):
            raise RuntimeError("subscriber down")

        rt.add_callback("Out", boom)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send((i,))
        rt.shutdown()  # must not raise
        assert rt.statistics_report()["recovery"]["shutdown_discarded"] == 3

    def test_persist_annotation_parses_interval_and_wal_dir(self, tmp_path):
        rt = SiddhiManager().create_siddhi_app_runtime(
            "@app:name('AnnApp')\n"
            f"@app:persist(interval='2 sec', wal.dir='{tmp_path}')\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        assert rt.persistence_interval_s == 2.0
        assert rt.wal is not None
        assert os.path.isdir(os.path.join(str(tmp_path), "AnnApp"))
        rt.shutdown()
