"""/health + /ready endpoint tests: liveness is unconditional, readiness
reflects per-app state (breaker-open -> degraded -> 503) and lock busyness."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from siddhi_tpu.service import SiddhiService
from siddhi_tpu.util.faults import FaultPlan, InjectedFault, inject

pytestmark = pytest.mark.smoke

APP = """@app:name('hsvc')
define stream S (v long);
@info(name='q') @breaker(threshold='1', cooldown='1 hour')
from S select v insert into Out;
"""


@pytest.fixture()
def server():
    svc = SiddhiService(token="secret-token")
    httpd = svc.make_server(port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", svc
    httpd.shutdown()


def _get(url, token=None):
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(url, body, token=None):
    req = urllib.request.Request(url, data=body.encode(), method="POST")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


class TestHealth:
    def test_health_is_up_and_unauthenticated(self, server):
        base, _svc = server
        code, body = _get(f"{base}/health")  # no bearer token on purpose
        assert code == 200
        assert body["status"] == "up"

    def test_data_endpoints_still_require_auth(self, server):
        base, _svc = server
        code, _ = _get(f"{base}/siddhi-apps")
        assert code == 401

    def test_ready_with_no_apps(self, server):
        base, _svc = server
        code, body = _get(f"{base}/ready")
        assert code == 200 and body["ready"] is True


class TestReady:
    def test_running_app_is_ready(self, server):
        base, _svc = server
        _post(f"{base}/siddhi-apps", APP, token="secret-token")
        code, body = _get(f"{base}/ready")
        assert code == 200 and body["ready"] is True
        assert body["apps"]["hsvc"]["state"] == "running"
        assert body["apps"]["hsvc"]["breakers"]["q"]["state"] == "closed"

    def test_breaker_open_reports_degraded_503(self, server):
        base, svc = server
        _post(f"{base}/siddhi-apps", APP, token="secret-token")
        rt = svc.manager.runtimes["hsvc"]
        inject(rt.query_runtimes["q"], "on_batch",
               FaultPlan(nth=(1,), exc=InjectedFault))
        _post(f"{base}/siddhi-apps/hsvc/streams/S",
              json.dumps({"events": [[1]]}), token="secret-token")
        code, body = _get(f"{base}/ready")
        assert code == 503 and body["ready"] is False
        assert body["apps"]["hsvc"]["state"] == "degraded"
        assert body["apps"]["hsvc"]["breakers"]["q"]["state"] == "open"
        # liveness is unaffected: the process still serves
        code, _body = _get(f"{base}/health")
        assert code == 200

    def test_busy_service_lock_does_not_block_probes(self, server):
        # /ready is lock-free: a wedged deploy holding the service lock
        # must not make probes hang or 503-flap
        base, svc = server
        _post(f"{base}/siddhi-apps", APP, token="secret-token")
        with svc.lock:  # a long deploy in flight
            code, body = _get(f"{base}/ready")
            assert code == 200 and body["ready"] is True
            assert body["apps"]["hsvc"]["state"] == "running"
            # metrics scrape is equally lock-free
            req = urllib.request.Request(f"{base}/metrics")
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
                assert b"siddhi_app_up" in resp.read()
