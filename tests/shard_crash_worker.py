"""Subprocess worker for tests/test_shard_recovery.py.

Runs ONE sharded plane (4 replicas, per-shard WAL directories under the
base dir) and is driven line-by-line over stdin, acknowledging every
command before blocking on the next read — so a SIGKILL issued after an
ack lands while the plane is idle with a KNOWN set of accepted (journaled)
rows. Commands:

    send <lo> <hi>     send rows lo..hi-1 (deterministic key/value/ts),
                       drain, reply "OK <hi>"
    kill <i>           in-process chaos: drop shard i's runtime without
                       shutdown (WAL handle released as death would),
                       reply "KILLED <i>"
    recover_shard <i>  rebuild shard i from its own WAL dir,
                       reply "SHARD-RECOVERED <i> <replayed>"
    recover            whole-plane recovery (every shard restores +
                       replays its journal), reply "RECOVERED <replayed>"
    rebalance          force a skew rebalance (epoch bump, WAL re-route),
                       reply "REBALANCED <epoch> <replayed>"
    result             drain, reply "RESULT <json>" — the last emitted
                       row per key (running aggregates are monotone, so
                       last == final; at-least-once replay re-emission
                       makes a multiset comparison invalid here)
    exit               clean shutdown, reply "BYE"
"""

import json
import os
import sys


def row(i: int):
    # multiples of 0.25: per-key partial sums are exactly representable
    return (f"K{i % 13}", ((i * 7 + 3) % 400 + 1) * 0.25)


APP = """
@app:name('ShardCrashApp')
@app:shards(n='4', key='k')
define stream S (k string, v double);
@info(name='agg')
from S select k, sum(v) as total, count() as n group by k insert into Out;
"""


def main() -> None:
    base = sys.argv[1]
    from siddhi_tpu.util.platform import force_cpu_platform
    force_cpu_platform(1)
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    plane = mgr.create_siddhi_app_runtime(APP, wal_dir=base)
    last: dict = {}

    def cb(events):
        for e in events:
            last[e.data[0]] = list(e.data)

    plane.add_callback("Out", cb)
    plane.start()
    print("READY", flush=True)

    for line in sys.stdin:
        parts = line.split()
        if not parts:
            continue
        cmd = parts[0]
        if cmd == "send":
            lo, hi = int(parts[1]), int(parts[2])
            h = plane.get_input_handler("S")
            h.send_batch([row(i) for i in range(lo, hi)],
                         timestamps=[1000 + i for i in range(lo, hi)])
            plane.drain()
            print(f"OK {hi}", flush=True)
        elif cmd == "kill":
            i = int(parts[1])
            plane.kill_shard(i)
            print(f"KILLED {i}", flush=True)
        elif cmd == "recover_shard":
            i = int(parts[1])
            r = plane.recover_shard(i)
            plane.drain()
            print(f"SHARD-RECOVERED {i} {r.get('wal_replayed', 0)}",
                  flush=True)
        elif cmd == "recover":
            r = plane.recover()
            plane.drain()
            print(f"RECOVERED {r['wal_replayed']}", flush=True)
        elif cmd == "rebalance":
            r = plane.rebalance(force=True)
            plane.drain()
            print(f"REBALANCED {r['epoch']} {r['replayed']}", flush=True)
        elif cmd == "result":
            plane.drain()
            print("RESULT " + json.dumps(last, sort_keys=True), flush=True)
        elif cmd == "exit":
            plane.shutdown()
            print("BYE", flush=True)
            return


if __name__ == "__main__":
    main()
