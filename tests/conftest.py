"""Test configuration: run everything on a virtual 8-device CPU mesh so sharding
tests work without TPU hardware (the driver separately dry-runs multi-chip).

The shared helper also forces the platform through jax.config, because env-var
overrides are not enough here — the axon TPU plugin registers itself regardless
of JAX_PLATFORMS in some images.

Also hosts the multi-process test harness: `worker_fleet` launches real OS
worker processes (fresh interpreters — jax.distributed and service workers
both need env-configured startup, not a fork of this mesh-configured
process), with deterministic port allocation, output capture, the shared
"MULTIHOST UNSUPPORTED" named-skip contract, and guaranteed teardown.
"""

import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from siddhi_tpu.util.platform import force_cpu_platform

force_cpu_platform(8)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "smoke: fast representative tier — `pytest -m smoke` finishes in "
        "~2-3 min on one core (full suite needs tens of minutes there)")
    config.addinivalue_line(
        "markers",
        "slow: excluded from tier-1 (`-m 'not slow'`) — bounded bench runs "
        "and other multi-minute cases")


class WorkerFleet:
    """Launcher/janitor for multi-process integration tests: spawns worker
    subprocesses with the repo on PYTHONPATH, hands out free localhost
    ports, waits on HTTP bring-up, and guarantees every child is reaped on
    teardown no matter how the test exits.

    Two spawn shapes:
      * `spawn_script(source, args)` — a fresh interpreter running inline
        worker source (the jax.distributed bring-up pattern: platform env
        must be set BEFORE the interpreter imports jax, so forking the
        mesh-configured test process is not an option);
      * `spawn_service(port)` — a `python -m siddhi_tpu.service <port>`
        worker host on the CPU backend (the multi-host shard tier's
        worker shape).
    """

    #: sentinel a distributed worker prints when the backend cannot run
    #: cross-process computations at all (capability limit, not a defect)
    UNSUPPORTED_SENTINEL = "MULTIHOST UNSUPPORTED"
    UNSUPPORTED_SKIP = (
        "jax CPU backend cannot execute cross-process computations "
        "(XLA INVALID_ARGUMENT: \"Multiprocess computations aren't "
        "implemented on the CPU backend\") — this capability test "
        "needs a real multi-host TPU/GPU backend")

    def __init__(self, tmp_path) -> None:
        self.tmp_path = tmp_path
        self.procs: list = []

    # ------------------------------------------------------------- plumbing

    @staticmethod
    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _env(self, overrides=None) -> dict:
        env = dict(os.environ)
        # workers own their platform choice (set it in overrides or in the
        # worker source itself, BEFORE jax imports)
        env.pop("JAX_PLATFORMS", None)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        if overrides:
            env.update(overrides)
        return env

    # --------------------------------------------------------------- spawns

    def spawn(self, argv, *, env=None, name=None) -> subprocess.Popen:
        p = subprocess.Popen(
            argv, cwd=str(self.tmp_path), env=self._env(env),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        p.fleet_name = name or os.path.basename(str(argv[1]))
        self.procs.append(p)
        return p

    def spawn_script(self, source: str, args=(), *, env=None,
                     name="worker.py") -> subprocess.Popen:
        path = self.tmp_path / name
        path.write_text(source)
        return self.spawn([sys.executable, str(path), *map(str, args)],
                          env=env, name=name)

    def spawn_service(self, port: int, *, env=None) -> subprocess.Popen:
        overrides = {"JAX_PLATFORMS": "cpu"}
        if env:
            overrides.update(env)
        return self.spawn(
            [sys.executable, "-m", "siddhi_tpu.service", str(port)],
            env=overrides, name=f"service:{port}")

    # ----------------------------------------------------------------- waits

    @staticmethod
    def wait_http_ready(port: int, timeout: float = 60.0,
                        path: str = "/health") -> None:
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=2.0) as r:
                    if r.status == 200:
                        return
                    last = r.status
            except (urllib.error.URLError, OSError) as e:
                last = e
            time.sleep(0.05)
        pytest.fail(f"worker on port {port} never served {path} "
                    f"(last: {last})")

    def communicate_all(self, timeout: float = 420.0) -> list:
        """Wait for every spawned process; on any timeout, kill the whole
        fleet and fail. Returns the combined stdout/stderr per process in
        spawn order."""
        outs = []
        for p in self.procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.shutdown()
                pytest.fail(f"worker {p.fleet_name} timed out")
            outs.append(out)
        return outs

    def skip_if_unsupported(self, outs) -> None:
        """Turn the worker-side capability sentinel into a NAMED skip —
        the test stays real on TPU/GPU multi-host CI."""
        if any(self.UNSUPPORTED_SENTINEL in out for out in outs):
            pytest.skip(self.UNSUPPORTED_SKIP)

    # -------------------------------------------------------------- teardown

    def kill(self, proc) -> None:
        """SIGKILL one worker (the host-kill chaos fault — no goodbye)."""
        from siddhi_tpu.util.faults import kill_host
        kill_host(proc)

    def shutdown(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for p in self.procs:
            try:
                p.communicate(timeout=30)
            except Exception:  # noqa: BLE001 — best-effort reap
                pass


@pytest.fixture
def worker_fleet(tmp_path):
    fleet = WorkerFleet(tmp_path)
    try:
        yield fleet
    finally:
        fleet.shutdown()
