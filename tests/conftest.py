"""Test configuration: run everything on a virtual 8-device CPU mesh so sharding
tests work without TPU hardware (the driver separately dry-runs multi-chip).

The shared helper also forces the platform through jax.config, because env-var
overrides are not enough here — the axon TPU plugin registers itself regardless
of JAX_PLATFORMS in some images.
"""

from siddhi_tpu.util.platform import force_cpu_platform

force_cpu_platform(8)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "smoke: fast representative tier — `pytest -m smoke` finishes in "
        "~2-3 min on one core (full suite needs tens of minutes there)")
    config.addinivalue_line(
        "markers",
        "slow: excluded from tier-1 (`-m 'not slow'`) — bounded bench runs "
        "and other multi-minute cases")
