"""Test configuration: run everything on a virtual 8-device CPU mesh so sharding
tests work without TPU hardware (the driver separately dry-runs multi-chip).

Note: env-var overrides are not enough here — the axon TPU plugin registers
itself regardless of JAX_PLATFORMS in some images — so we also force the
platform through jax.config before any device is initialised.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
