"""Seeded defect: module-level mutable registry written without a lock."""

from siddhi_tpu.util.locks import named_lock

_REGISTRY = {}
_lock = named_lock("corpus.registry")


def register_unguarded(name, value):
    _REGISTRY[name] = value                   # SL405


def register_guarded(name, value):
    with _lock:
        _REGISTRY[name] = value               # guarded: no finding
