"""Seeded defect: raw primitives constructed outside util/locks.py."""

import threading


class Cache:
    def __init__(self):
        self.lock = threading.Lock()          # SL401
        self.rlock = threading.RLock()        # SL401
        self.cv = threading.Condition()       # SL401
        self.ok = threading.Event()           # not a lock: no finding
