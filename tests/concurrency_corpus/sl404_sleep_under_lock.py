"""Seeded defect: blocking calls made while a lock is held."""

import os
import time

from siddhi_tpu.util.locks import named_lock

_lock = named_lock("corpus.slow")


def checkpoint(fd, worker):
    with _lock:
        time.sleep(0.5)                       # SL404
        os.fsync(fd)                          # SL404
        worker.join()                         # SL404 (zero-arg join)
        ",".join(["a", "b"])                  # str.join: no finding
