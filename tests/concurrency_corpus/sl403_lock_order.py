"""Seeded defect: the two named locks are nested in BOTH orders."""

from siddhi_tpu.util.locks import named_lock


class Transfer:
    def __init__(self):
        self._accounts = named_lock("corpus.accounts")
        self._audit = named_lock("corpus.audit")
        self.balance = 0
        self.entries = 0

    def debit(self):
        with self._accounts:                  # accounts -> audit
            with self._audit:
                self.balance -= 1
                self.entries += 1

    def reconcile(self):
        with self._audit:                     # audit -> accounts: SL403
            with self._accounts:
                self.entries = self.balance
