"""Seeded defect: self.depth written from a daemon loop AND the public
caller-thread API with no common guarding lock."""

import threading

from siddhi_tpu.util.locks import named_lock


class Pump:
    def __init__(self):
        self._lock = named_lock("corpus.pump")
        self.depth = 0
        self._t = threading.Thread(target=self._drain_loop, daemon=True)

    def _drain_loop(self):
        while True:
            self.depth = self.depth - 1       # entry point #1, unguarded

    def submit(self, n):
        self.depth = self.depth + n           # entry point #2, unguarded

    def guarded_reset(self):
        with self._lock:
            self.depth = 0                    # guarded — but no COMMON guard
