"""Additional behavioral shapes mirrored from the reference's suites that had
no direct counterpart yet (reference files cited per class):

- count patterns with ranges and `e1[i]` indexing (CountPatternTestCase)
- pattern chains mixing logical + count positions (LogicalPatternTestCase)
- partitions over time windows with per-key expiry (PartitionTestCase)
- join `within` + unidirectional (JoinTestCase)
- triggers driving downstream windowed queries (TriggerTestCase)
- table updates driven by window expiry output (UpdateTableTestCase shape)
"""

import pytest

from siddhi_tpu import SiddhiManager

TWO = ("define stream S1 (symbol string, price float);\n"
       "define stream S2 (symbol string, price float);\n")


def make(app, batch_size=8, playback=False):
    manager = SiddhiManager()
    text = ("@app:playback\n" if playback else "") + app
    rt = manager.create_siddhi_app_runtime(text, batch_size=batch_size)
    got = []
    rt.add_callback("OutStream", lambda evs: got.extend(e.data for e in evs))
    rt.start()
    return rt, got


class TestCountPatterns:
    """Reference: query/pattern/CountPatternTestCase."""

    def test_count_range_collects_two_to_three(self):
        app = (TWO +
               "from e1=S1[price > 20.0]<2:3> -> e2=S2[price > 100.0] "
               "select e1[0].price as p0, e1[1].price as p1, "
               "e2.price as p2 insert into OutStream;")
        rt, got = make(app)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("A", 25.0)); rt.flush()
        s1.send(("B", 30.0)); rt.flush()
        s2.send(("C", 150.0)); rt.flush()
        assert got == [(25.0, 30.0, 150.0)]

    def test_count_min_not_met_blocks(self):
        app = (TWO +
               "from e1=S1[price > 20.0]<2:3> -> e2=S2[price > 100.0] "
               "select e2.price as p insert into OutStream;")
        rt, got = make(app)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("A", 25.0)); rt.flush()  # only ONE e1: min 2 not met
        s2.send(("C", 150.0)); rt.flush()
        assert got == []

    def test_last_index_reads_newest_occurrence(self):
        app = (TWO +
               "from e1=S1[price > 20.0]<1:2> -> e2=S2[price > 100.0] "
               "select e1[last].price as pl insert into OutStream;")
        rt, got = make(app)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("A", 25.0)); rt.flush()
        s1.send(("B", 30.0)); rt.flush()
        s2.send(("C", 150.0)); rt.flush()
        # e1[last] follows each match's newest captured occurrence. (The
        # 1-occurrence epsilon match also completes — documented divergence,
        # core/pattern_runtime._advance — so the 2-capture match's value
        # must be present and correct.)
        assert (30.0,) in got


class TestPartitionTimeWindows:
    """Reference: query/partition/PartitionTestCase1 — per-key windows expire
    independently."""

    def test_per_key_time_window_counts(self):
        app = ("define stream S (k string, v double);\n"
               "partition with (k of S) begin\n"
               "@info(name='q') from S#window.time(1 sec) "
               "select k, count() as n insert into OutStream;\n"
               "end;")
        rt, got = make(app, playback=True)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 1.0), timestamp=150)
        h.send(("a", 1.0), timestamp=200)
        rt.flush()
        by = {}
        for k, n in got:
            by[k] = n
        assert by == {"a": 2, "b": 1}
        # a's first event expires at 1100; b's at 1150
        del got[:]
        h.send(("a", 1.0), timestamp=1_120)
        rt.flush()
        assert ("a", 2) in got  # one expired, one live, plus the new one


class TestJoinWithinUnidirectional:
    """Reference: query/join/JoinTestCase — `within` bounds pair ages;
    `unidirectional` restricts the triggering side."""

    APP = ("define stream L (k int, v double);\n"
           "define stream R (k int, w double);\n")

    def test_within_excludes_stale_pairs(self):
        app = (self.APP +
               "@info(name='q') from L#window.length(10) as a "
               "join R#window.length(10) as b on a.k == b.k "
               "within 1 sec "
               "select a.k as k insert into OutStream;")
        rt, got = make(app, playback=True)
        l, r = rt.get_input_handler("L"), rt.get_input_handler("R")
        r.send((1, 9.0), timestamp=100)
        rt.flush()
        l.send((1, 1.0), timestamp=500)
        rt.flush()
        assert got == [(1,)]  # 400ms apart: inside within
        del got[:]
        l.send((1, 2.0), timestamp=5_000)
        rt.flush()
        assert got == []  # 4.9s apart: outside within

    def test_left_unidirectional_right_does_not_trigger(self):
        app = (self.APP +
               "@info(name='q') from L#window.length(10) as a unidirectional "
               "join R#window.length(10) as b on a.k == b.k "
               "select a.k as k insert into OutStream;")
        rt, got = make(app)
        l, r = rt.get_input_handler("L"), rt.get_input_handler("R")
        l.send((1, 1.0)); rt.flush()
        r.send((1, 9.0)); rt.flush()   # right arrival must NOT emit
        assert got == []
        l.send((1, 2.0)); rt.flush()   # left arrival probes and emits
        assert got == [(1,)]


class TestTriggerDrivenQueries:
    """Reference: trigger tests — periodic trigger events feed queries."""

    def test_start_trigger_fires_once(self):
        app = ("define trigger T at 'start';\n"
               "@info(name='q') from T select triggered_time "
               "insert into OutStream;")
        rt, got = make(app)
        rt.flush()
        assert len(got) == 1

    def test_periodic_trigger_windowed_count(self):
        app = ("@app:playback\n"
               "define trigger T at every 1 sec;\n"
               "@info(name='q') from T#window.lengthBatch(3) "
               "select count() as n insert into OutStream;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=8)
        got = []
        rt.add_callback("OutStream", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        for t in (1_000, 2_000, 3_000):
            rt.heartbeat(t)
        assert [g[0] for g in got][-1] == 3


class TestWindowExpiryToTable:
    """Reference: UpdateTableTestCase shape — expired events update tables."""

    def test_expired_events_delete_from_table(self):
        app = ("define stream S (k int);\n"
               "define table T (k int);\n"
               "from S select k insert into T;\n"
               "from S#window.length(2) "
               "insert expired events into ExpStream;\n"
               "from ExpStream select k delete T on T.k == k;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        h = rt.get_input_handler("S")
        for i in (1, 2, 3, 4):  # length(2): 1 and 2 expire
            h.send((i,))
        rt.flush()
        assert sorted(rt.tables["T"].all_rows()) == [(3,), (4,)]
