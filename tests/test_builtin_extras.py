"""UUID / set-idiom builtins + planner-resolved time functions (reference:
core/executor/function/ UUIDFunctionExecutor, CreateSetFunctionExecutor,
SizeOfSetFunctionExecutor, EventTimestampFunctionExecutor,
CurrentTimeMillisFunctionExecutor; UnionSetAttributeAggregatorExecutor)."""

import re

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError


pytestmark = pytest.mark.smoke

S = "define stream S (symbol string, price double);\n"


def build(app, batch_size=4):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    rt.start()
    return rt


def collect(rt, name="q"):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.extend(
        tuple(e.data) for e in i or []))
    return got


class TestUUID:
    def test_uuid_per_event(self):
        rt = build(S + "@info(name='q') from S select UUID() as id, symbol "
                   "insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=1)
        h.send(("b", 2.0), timestamp=2)
        rt.flush()
        assert len(got) == 2
        pat = re.compile(
            r"^[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{12}$")
        assert all(pat.match(r[0]) for r in got)
        assert got[0][0] != got[1][0]  # fresh per event
        assert [r[1] for r in got] == ["a", "b"]

    def test_uuid_nested_rejected(self):
        with pytest.raises(SiddhiAppCreationError):
            build(S + "@info(name='q') from S "
                  "select convert(UUID(), 'string') as x insert into Out;")


class TestSetIdioms:
    def test_size_of_union_set_is_exact_distinct(self):
        rt = build(S + "@info(name='q') from S#window.lengthBatch(4) "
                   "select sizeOfSet(unionSet(createSet(symbol))) as n "
                   "insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for sym in ["a", "b", "a", "c"]:
            h.send((sym, 1.0), timestamp=1)
        rt.flush()
        # per-event running distinct within the batch window
        assert [r[0] for r in got] == [1, 2, 2, 3]

    def test_size_of_union_set_without_create_set(self):
        rt = build(S + "@info(name='q') from S#window.lengthBatch(2) "
                   "select sizeOfSet(unionSet(symbol)) as n insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("x", 1.0), timestamp=1)
        h.send(("x", 2.0), timestamp=2)
        rt.flush()
        assert [r[0] for r in got] == [1, 1]

    def test_nested_union_set_rejected_with_guidance(self):
        # top-level raw unionSet materializes host-side (TestRawUnionSet);
        # INSIDE a larger expression the set stays host-opaque — rejected
        with pytest.raises(SiddhiAppCreationError, match="sizeOfSet"):
            build(S + "@info(name='q') from S "
                  "select convert(unionSet(createSet(symbol)), 'string') "
                  "as s insert into Out;")

    def test_raw_create_set_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="createSet"):
            build(S + "@info(name='q') from S select createSet(symbol) as s "
                  "insert into Out;")


class TestPlannerTimeFunctions:
    def test_event_timestamp(self):
        rt = build(S + "@info(name='q') from S "
                   "select eventTimestamp() as ts, symbol insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=123)
        h.send(("b", 2.0), timestamp=456)
        rt.flush()
        assert [r[0] for r in got] == [123, 456]

    def test_current_time_millis_is_watermark(self):
        rt = build(S + "@info(name='q') from S "
                   "select currentTimeMillis() as now, symbol insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=777)
        rt.flush()
        assert got[0][0] >= 777


class TestUUIDForwarding:
    def test_uuid_reaches_stream_callbacks_and_tables(self):
        rt = build("define stream S (symbol string);\n"
                   "define table T (id string, symbol string);\n"
                   "@info(name='q') from S select UUID() as id, symbol "
                   "insert into Mid;\n"
                   "from Mid select id, symbol insert into T;\n")
        seen = []
        rt.add_callback("Mid", lambda evs: seen.extend(e.data for e in evs))
        h = rt.get_input_handler("S")
        h.send(("a",), timestamp=1)
        rt.flush()
        pat = re.compile(r"^[0-9a-f]{8}-")
        assert len(seen) == 1 and pat.match(seen[0][0])
        rows = rt.query("from T select id, symbol")
        assert len(rows) == 1 and pat.match(rows[0].data[0])
        assert rows[0].data[0] == seen[0][0]  # one uuid per event, everywhere


class TestUuidRoundTrip:
    def test_forwarded_uuid_matches_on_demand_lookup(self):
        # transient codes must round-trip through encode(): a client reading
        # a uuid and querying it back must match the stored row
        from siddhi_tpu import SiddhiManager
        app = """
        define stream S (k string);
        define table T (k string, id string);
        from S select k, UUID() as id insert into T;
        """
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        rt.get_input_handler("S").send(("a",))
        rt.flush()
        (k, the_id), = rt.tables["T"].all_rows()
        rows = rt.query(f"from T on id == '{the_id}' select k")
        rt.shutdown()
        assert [r.data for r in rows] == [("a",)]


class TestStaleTransientCode:
    """Transient (UUID-ring) codes carry their slot generation: decoding a
    code after its slot recycled raises LOUDLY instead of silently
    returning a newer uuid (VERDICT r3 weak #5)."""

    def test_recycled_code_raises(self):
        from siddhi_tpu.core.event import StringTable
        from siddhi_tpu.errors import StaleTransientCodeError
        tbl = StringTable()
        old = tbl.encode_transient("u-0", capacity=4)
        for i in range(1, 5):  # wraps: slot 0 recycled by u-4
            tbl.encode_transient(f"u-{i}", capacity=4)
        with pytest.raises(StaleTransientCodeError, match="recycled"):
            tbl.decode(old)

    def test_live_codes_decode(self):
        from siddhi_tpu.core.event import StringTable
        tbl = StringTable()
        codes = [tbl.encode_transient(f"u-{i}", capacity=4) for i in range(4)]
        assert [tbl.decode(c) for c in codes] == [f"u-{i}" for i in range(4)]

    def test_generation_survives_snapshot_restore(self):
        from siddhi_tpu.core.event import StringTable
        from siddhi_tpu.errors import StaleTransientCodeError
        tbl = StringTable()
        old = tbl.encode_transient("u-0", capacity=2)
        tbl.encode_transient("u-1", capacity=2)
        tbl.encode_transient("u-2", capacity=2)  # recycles slot 0
        live = tbl.encode_transient("u-3", capacity=2)
        snap = tbl.snapshot()
        tbl2 = StringTable()
        tbl2.restore(snap)
        assert tbl2.decode(live) == "u-3"
        with pytest.raises(StaleTransientCodeError):
            tbl2.decode(old)


class TestRawUnionSet:
    """Raw set emission (reference:
    UnionSetAttributeAggregatorExecutor.java:71): `select unionSet(x) as s`
    materializes the LIVE value set host-side at the query-callback
    boundary (device tracks the multiset as an exact distinctCount)."""

    def test_union_set_over_sliding_window(self):
        rt = build(S + "@info(name='q') from S#window.length(2) "
                   "select unionSet(symbol) as syms insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=1)
        rt.flush()
        h.send(("b", 2.0), timestamp=2)
        rt.flush()
        h.send(("c", 3.0), timestamp=3)  # 'a' leaves the window
        rt.flush()
        assert got[0][0] == {"a"}
        assert got[1][0] == {"a", "b"}
        assert got[2][0] == {"b", "c"}

    def test_union_set_with_create_set(self):
        rt = build(S + "@info(name='q') from S#window.lengthBatch(2) "
                   "select unionSet(createSet(symbol)) as syms "
                   "insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("x", 1.0), timestamp=1)
        h.send(("y", 2.0), timestamp=2)
        rt.flush()
        assert got[-1][0] == {"x", "y"}

    def test_grouped_raw_union_set_rejected(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError, match="ungrouped"):
            build(S + "@info(name='q') from S "
                  "select unionSet(symbol) as syms group by symbol "
                  "insert into Out;")

    def test_non_string_raw_union_set_rejected(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError, match="STRING"):
            build(S + "@info(name='q') from S "
                  "select unionSet(price) as ps insert into Out;")

    def test_size_of_set_composition_still_works(self):
        rt = build(S + "@info(name='q') from S#window.length(3) "
                   "select sizeOfSet(unionSet(createSet(symbol))) as n "
                   "insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for i, sym in enumerate(["a", "b", "a"]):
            h.send((sym, 1.0), timestamp=i + 1)
        rt.flush()
        assert [r[0] for r in got] == [1, 2, 2]


class TestExtensionParameterMetadata:
    """@Extension-style parameter metadata: parse-time validation naming
    the parameter (reference: siddhi-annotations @Parameter +
    InputParameterValidator) and doc-gen parameter tables."""

    def test_wrong_type_names_parameter(self):
        import pytest as _pytest

        from siddhi_tpu import SiddhiManager
        from siddhi_tpu.errors import SiddhiAppCreationError
        with _pytest.raises(SiddhiAppCreationError,
                            match=r"window.length.*must be int"):
            SiddhiManager().create_siddhi_app_runtime(
                "define stream S (v double);\n"
                "from S#window.lengthBatch('ten') select v insert into O;")

    def test_missing_parameter_names_it(self):
        import pytest as _pytest

        from siddhi_tpu import SiddhiManager
        from siddhi_tpu.errors import SiddhiAppCreationError
        with _pytest.raises(SiddhiAppCreationError,
                            match=r"needs parameter 1 \(window.time"):
            SiddhiManager().create_siddhi_app_runtime(
                "define stream S (v double);\n"
                "from S#window.time() select v insert into O;")

    def test_excess_parameter_rejected(self):
        import pytest as _pytest

        from siddhi_tpu import SiddhiManager
        from siddhi_tpu.errors import SiddhiAppCreationError
        with _pytest.raises(SiddhiAppCreationError, match="at most"):
            SiddhiManager().create_siddhi_app_runtime(
                "define stream S (v double);\n"
                "from S#window.length(5, 6) select v insert into O;")

    def test_docgen_renders_parameter_tables(self):
        from siddhi_tpu.util.docgen import generate_markdown
        md = generate_markdown()
        assert "| Parameter | Type | Optional | Default | Description |" in md
        assert "`window.length`" in md and "`cron.expression`" in md
