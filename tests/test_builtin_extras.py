"""UUID / set-idiom builtins + planner-resolved time functions (reference:
core/executor/function/ UUIDFunctionExecutor, CreateSetFunctionExecutor,
SizeOfSetFunctionExecutor, EventTimestampFunctionExecutor,
CurrentTimeMillisFunctionExecutor; UnionSetAttributeAggregatorExecutor)."""

import re

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError

S = "define stream S (symbol string, price double);\n"


def build(app, batch_size=4):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    rt.start()
    return rt


def collect(rt, name="q"):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.extend(
        tuple(e.data) for e in i or []))
    return got


class TestUUID:
    def test_uuid_per_event(self):
        rt = build(S + "@info(name='q') from S select UUID() as id, symbol "
                   "insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=1)
        h.send(("b", 2.0), timestamp=2)
        rt.flush()
        assert len(got) == 2
        pat = re.compile(
            r"^[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{12}$")
        assert all(pat.match(r[0]) for r in got)
        assert got[0][0] != got[1][0]  # fresh per event
        assert [r[1] for r in got] == ["a", "b"]

    def test_uuid_nested_rejected(self):
        with pytest.raises(SiddhiAppCreationError):
            build(S + "@info(name='q') from S "
                  "select convert(UUID(), 'string') as x insert into Out;")


class TestSetIdioms:
    def test_size_of_union_set_is_exact_distinct(self):
        rt = build(S + "@info(name='q') from S#window.lengthBatch(4) "
                   "select sizeOfSet(unionSet(createSet(symbol))) as n "
                   "insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        for sym in ["a", "b", "a", "c"]:
            h.send((sym, 1.0), timestamp=1)
        rt.flush()
        # per-event running distinct within the batch window
        assert [r[0] for r in got] == [1, 2, 2, 3]

    def test_size_of_union_set_without_create_set(self):
        rt = build(S + "@info(name='q') from S#window.lengthBatch(2) "
                   "select sizeOfSet(unionSet(symbol)) as n insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("x", 1.0), timestamp=1)
        h.send(("x", 2.0), timestamp=2)
        rt.flush()
        assert [r[0] for r in got] == [1, 1]

    def test_raw_union_set_rejected_with_guidance(self):
        with pytest.raises(SiddhiAppCreationError, match="sizeOfSet"):
            build(S + "@info(name='q') from S "
                  "select unionSet(createSet(symbol)) as s insert into Out;")

    def test_raw_create_set_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="createSet"):
            build(S + "@info(name='q') from S select createSet(symbol) as s "
                  "insert into Out;")


class TestPlannerTimeFunctions:
    def test_event_timestamp(self):
        rt = build(S + "@info(name='q') from S "
                   "select eventTimestamp() as ts, symbol insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=123)
        h.send(("b", 2.0), timestamp=456)
        rt.flush()
        assert [r[0] for r in got] == [123, 456]

    def test_current_time_millis_is_watermark(self):
        rt = build(S + "@info(name='q') from S "
                   "select currentTimeMillis() as now, symbol insert into Out;")
        got = collect(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=777)
        rt.flush()
        assert got[0][0] >= 777


class TestUUIDForwarding:
    def test_uuid_reaches_stream_callbacks_and_tables(self):
        rt = build("define stream S (symbol string);\n"
                   "define table T (id string, symbol string);\n"
                   "@info(name='q') from S select UUID() as id, symbol "
                   "insert into Mid;\n"
                   "from Mid select id, symbol insert into T;\n")
        seen = []
        rt.add_callback("Mid", lambda evs: seen.extend(e.data for e in evs))
        h = rt.get_input_handler("S")
        h.send(("a",), timestamp=1)
        rt.flush()
        pat = re.compile(r"^[0-9a-f]{8}-")
        assert len(seen) == 1 and pat.match(seen[0][0])
        rows = rt.query("from T select id, symbol")
        assert len(rows) == 1 and pat.match(rows[0].data[0])
        assert rows[0].data[0] == seen[0][0]  # one uuid per event, everywhere


class TestUuidRoundTrip:
    def test_forwarded_uuid_matches_on_demand_lookup(self):
        # transient codes must round-trip through encode(): a client reading
        # a uuid and querying it back must match the stored row
        from siddhi_tpu import SiddhiManager
        app = """
        define stream S (k string);
        define table T (k string, id string);
        from S select k, UUID() as id insert into T;
        """
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        rt.get_input_handler("S").send(("a",))
        rt.flush()
        (k, the_id), = rt.tables["T"].all_rows()
        rows = rt.query(f"from T on id == '{the_id}' select k")
        rt.shutdown()
        assert [r.data for r in rows] == [("a",)]
