"""Hardening tests for the driver entry points in __graft_entry__.

The official multi-chip gate calls ``dryrun_multichip`` from a process that
may already hold an initialised (possibly broken) TPU backend; the proof must
verify the CPU mesh with real dispatches and fall back to a clean subprocess
when in-process recovery fails (reference behaviour being proven:
single-JVM Siddhi partitions, `core/partition/PartitionStreamReceiver.java:82`,
re-expressed as a mesh-sharded SPMD step).
"""

from __future__ import annotations

import sys
import types

import __graft_entry__ as graft


def test_verify_cpu_mesh_passes_under_cpu_conftest():
    # conftest already forced an 8-device CPU platform; verification must
    # agree (this is the gate's happy path — no subprocess needed).
    assert graft._verify_cpu_mesh(8)


def test_subprocess_fallback_env(monkeypatch):
    # When in-process verification fails, dryrun must re-exec in a clean
    # interpreter with JAX_PLATFORMS=cpu and the device-count flag exported
    # BEFORE any jax import, and must not recurse in the child.
    captured = {}

    def fake_run(cmd, cwd=None, env=None, capture_output=None, text=None,
                 timeout=None):
        captured.update(cmd=cmd, cwd=cwd, env=env)
        return types.SimpleNamespace(returncode=0, stdout="ok\n", stderr="")

    monkeypatch.setattr(graft.subprocess, "run", fake_run)
    monkeypatch.setattr(graft, "_verify_cpu_mesh", lambda n: False)
    monkeypatch.delenv("SIDDHI_TPU_DRYRUN_CHILD", raising=False)

    graft.dryrun_multichip(8)

    env = captured["env"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert env["XLA_FLAGS"].count("--xla_force_host_platform_device_count") == 1
    assert env["SIDDHI_TPU_DRYRUN_CHILD"] == "1"
    assert captured["cmd"][0] == sys.executable
    assert "dryrun_multichip(8)" in captured["cmd"][-1]


def test_child_does_not_recurse(monkeypatch):
    monkeypatch.setattr(graft, "_verify_cpu_mesh", lambda n: False)
    monkeypatch.setenv("SIDDHI_TPU_DRYRUN_CHILD", "1")
    try:
        graft.dryrun_multichip(8)
    except RuntimeError as e:
        assert "clean subprocess" in str(e)
    else:
        raise AssertionError("child with no CPU mesh must raise, not recurse")


def test_subprocess_failure_raises(monkeypatch):
    def fake_run(*a, **k):
        return types.SimpleNamespace(returncode=3, stdout="", stderr="boom")

    monkeypatch.setattr(graft.subprocess, "run", fake_run)
    monkeypatch.setattr(graft, "_verify_cpu_mesh", lambda n: False)
    monkeypatch.delenv("SIDDHI_TPU_DRYRUN_CHILD", raising=False)
    try:
        graft.dryrun_multichip(8)
    except RuntimeError as e:
        assert "rc=3" in str(e) and "boom" in str(e)
    else:
        raise AssertionError("subprocess failure must propagate")
