"""Stream-function FROM chains + distinctCount aggregator tests (reference:
query/streamfunction/Pol2CartTestCase, query/aggregator/DistinctCountTestCase
— incl. the BASELINE config-3 shape: sliding distinctCount)."""

import pytest

from siddhi_tpu import SiddhiManager

S = "define stream S (symbol string, theta double, rho double, v long);\n"


def build(app, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=batch_size)
    rt.start()
    return rt


def q_callback(rt, name):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.extend(i or []))
    return got


class TestStreamFunctions:
    def test_pol2cart_adds_columns(self):
        rt = build(
            S + "@info(name='q') from S#pol2Cart(theta, rho) "
            "select symbol, x, y insert into Out;")
        got = q_callback(rt, "q")
        rt.get_input_handler("S").send(("A", 0.0, 5.0, 1))
        rt.get_input_handler("S").send(("B", 90.0, 2.0, 1))
        rt.flush()
        assert got[0].data == ("A", pytest.approx(5.0), pytest.approx(0.0, abs=1e-6))
        assert got[1].data == ("B", pytest.approx(0.0, abs=1e-6), pytest.approx(2.0))

    def test_stream_fn_feeds_window_aggregate(self):
        rt = build(
            S + "@info(name='q') from S#pol2Cart(theta, rho)#window.lengthBatch(2) "
            "select symbol, sum(x) as sx insert into Out;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        h.send(("A", 0.0, 3.0, 1))   # x=3
        h.send(("A", 0.0, 4.0, 1))   # x=4
        rt.flush()
        assert got[-1].data[1] == pytest.approx(7.0)

    def test_select_star_includes_new_attrs(self):
        rt = build(
            S + "@info(name='q') from S#pol2Cart(theta, rho) "
            "select * insert into Out;")
        got = q_callback(rt, "q")
        rt.get_input_handler("S").send(("A", 0.0, 5.0, 9))
        rt.flush()
        # original attrs + x, y
        assert len(got[0].data) == 6


class TestDistinctCount:
    APP = ("define stream T (user string, page string, v long);\n"
           "@info(name='q') from T{window} "
           "select user, distinctCount(page) as pages "
           "group by user insert into Out;")

    def test_plain_distinct_count(self):
        rt = build(self.APP.format(window=""))
        got = q_callback(rt, "q")
        h = rt.get_input_handler("T")
        for row in [("u1", "a", 1), ("u1", "b", 1), ("u1", "a", 1),
                    ("u2", "a", 1), ("u1", "c", 1)]:
            h.send(row)
        rt.flush()
        per_lane = [(e.data[0], e.data[1]) for e in got]
        assert per_lane == [("u1", 1), ("u1", 2), ("u1", 2), ("u2", 1), ("u1", 3)]

    def test_sliding_window_removal(self):
        # BASELINE config 3 shape: sliding length window — values leaving the
        # window decrement the distinct count exactly
        rt = build(self.APP.format(window="#window.length(2)"))
        got = q_callback(rt, "q")
        h = rt.get_input_handler("T")
        for row in [("u1", "a", 1), ("u1", "b", 1), ("u1", "c", 1)]:
            h.send(row)
            rt.flush()
        # after c arrives, a expired: distinct = {b, c} = 2
        currents = [e.data[1] for e in got if not e.is_expired]
        assert currents[-1] == 2

    def test_duplicate_survives_partial_expiry(self):
        rt = build(self.APP.format(window="#window.length(2)"))
        got = q_callback(rt, "q")
        h = rt.get_input_handler("T")
        for row in [("u1", "a", 1), ("u1", "a", 1), ("u1", "b", 1)]:
            h.send(row)
            rt.flush()
        # window holds [a, b] after first a expired — 'a' still present once
        currents = [e.data[1] for e in got if not e.is_expired]
        assert currents == [1, 1, 2]

    def test_float_values_distinct_by_bits(self):
        app = ("define stream T (user string, price double, v long);\n"
               "@info(name='q') from T select user, distinctCount(price) as n "
               "group by user insert into Out;")
        rt = build(app)
        got = q_callback(rt, "q")
        h = rt.get_input_handler("T")
        for p in [1.2, 1.9, 2.5, 1.2]:
            h.send(("u", p, 1))
        rt.flush()
        assert [e.data[1] for e in got] == [1, 2, 3, 3]

    def test_batch_window_reset(self):
        rt = build(self.APP.format(window="#window.lengthBatch(2)"))
        got = q_callback(rt, "q")
        h = rt.get_input_handler("T")
        for row in [("u1", "a", 1), ("u1", "b", 1), ("u1", "b", 1), ("u1", "b", 1)]:
            h.send(row)
            rt.flush()
        currents = [e.data[1] for e in got if not e.is_expired]
        # batch 1: a,b → 1,2 ; batch 2 (after reset): b,b → 1,1
        assert currents == [1, 2, 1, 1]


class TestDistinctPairEviction:
    """Lifetime-unique pairs past capacity must not corrupt counts: the
    capacity monitor compacts the append-only pair table, evicting dead
    (count==0) pairs (reference behavior: HashMap entries are removed
    naturally on processRemove)."""

    def test_counts_stay_correct_past_lifetime_capacity(self):
        import warnings as _warnings

        rt = SiddhiManager().create_siddhi_app_runtime(
            "@app:playback\n"
            "define stream S (k long);\n"
            "@info(name='q') from S#window.time(1 sec) "
            "select distinctCount(k) as dc insert into Out;",
            batch_size=8, group_capacity=64)
        rt.start()
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(
            e.data[0] for e in i or []))
        h = rt.get_input_handler("S")
        # 64 waves x 8 fresh values = 512 lifetime-unique >> capacity 64;
        # waves are 2 s apart so at most one wave is ever live
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")  # post-compaction warn = failure
            for wave in range(64):
                base_ts = 2_000 * wave
                for j in range(8):
                    h.send((wave * 8 + j,), timestamp=base_ts + j)
                rt.flush()
        # final wave: running distinct within the window is 1..8
        assert got[-8:] == [1, 2, 3, 4, 5, 6, 7, 8]


class TestUnionSetForwarding:
    """Forwarded raw unionSet: downstream consumers get the LONG set-size
    projection; sizeOfSet reads it exactly (docs/PARITY.md divergence
    note; reference UnionSetAttributeAggregatorExecutor.java:71)."""

    def test_insert_into_table_then_size_of_set(self):
        from siddhi_tpu import SiddhiManager
        app = ("define stream S (sym string);\n"
               "define table T (s long);\n"
               "@info(name='fw') from S select unionSet(sym) as s "
               "insert into T;")
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)
        rt.start()
        h = rt.get_input_handler("S")
        for x in ("a", "b", "a", "c"):
            h.send((x,))
            rt.flush()
        rows = rt.query("from T select sizeOfSet(s) as n")
        assert [r.data for r in rows] == [(1,), (2,), (2,), (3,)]
        # callback boundary still materializes the REAL set
        got = []
        rt.add_query_callback(
            "fw", lambda ts, i, r: got.extend(e.data for e in i or []))
        h.send(("d",))
        rt.flush()
        assert got[-1][0] == {"a", "b", "c", "d"}
