"""Cross-feature integration tests — multi-query chaining, junction fan-out,
mixed entities in one app (reference: stream/JunctionTestCase,
PassThroughTestCase, multi-query apps)."""

import pytest

from siddhi_tpu import SiddhiManager


def build(app, **kw):
    rt = SiddhiManager().create_siddhi_app_runtime(app, **kw)
    rt.start()
    return rt


class TestQueryChaining:
    def test_three_stage_chain(self):
        rt = build(
            "define stream S (symbol string, price double);\n"
            "from S[price > 0.0] select symbol, price insert into A;\n"
            "from A select symbol, price * 2.0 as price insert into B;\n"
            "@info(name='q3') from B[price > 10.0] select symbol, price "
            "insert into C;")
        got = []
        rt.add_query_callback("q3", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        h.send(("a", 3.0))   # 6.0 < 10 → filtered at q3
        h.send(("b", 7.0))   # 14.0 → passes
        rt.flush()
        assert [(e.data[0], e.data[1]) for e in got] == [("b", pytest.approx(14.0))]

    def test_fan_out_two_queries_one_stream(self):
        rt = build(
            "define stream S (symbol string, price double);\n"
            "@info(name='hi') from S[price > 50.0] select symbol insert into Hi;\n"
            "@info(name='lo') from S[price <= 50.0] select symbol insert into Lo;")
        hi, lo = [], []
        rt.add_query_callback("hi", lambda ts, i, r: hi.extend(i or []))
        rt.add_query_callback("lo", lambda ts, i, r: lo.extend(i or []))
        h = rt.get_input_handler("S")
        for row in [("a", 60.0), ("b", 40.0), ("c", 70.0)]:
            h.send(row)
        rt.flush()
        assert [e.data[0] for e in hi] == ["a", "c"]
        assert [e.data[0] for e in lo] == ["b"]

    def test_window_feeds_table_feeds_join(self):
        rt = build(
            "define stream Trades (symbol string, price double);\n"
            "define stream Checks (symbol string);\n"
            "define table LastBatch (symbol string, total double);\n"
            "from Trades#window.lengthBatch(2) select symbol, sum(price) as total "
            "group by symbol insert into LastBatch;\n"
            "@info(name='j') from Checks join LastBatch "
            "on Checks.symbol == LastBatch.symbol "
            "select Checks.symbol as symbol, LastBatch.total as total "
            "insert into Out;")
        got = []
        rt.add_query_callback("j", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("Trades")
        h.send(("x", 10.0))
        h.send(("x", 20.0))
        rt.flush()
        rt.get_input_handler("Checks").send(("x",))
        rt.flush()
        assert got[-1].data[1] == pytest.approx(30.0)

    def test_async_annotation_buffer_size(self):
        # @Async(buffer.size=N) tunes the micro-batch (the Disruptor knob)
        # and enables ring+feeder ingestion: delivery is asynchronous, and
        # flush() is the barrier that drains the staging ring
        rt = build(
            "@Async(buffer.size='4')\n"
            "define stream S (v long);\n"
            "@info(name='q') from S select count() as n insert into Out;")
        assert rt.junctions["S"].batch_size == 4
        assert rt.junctions["S"].is_async
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        for i in range(4):
            h.send((i,))
        rt.flush()
        assert got and got[-1].data[0] == 4

    def test_many_entities_one_app(self):
        rt = build(
            "@app:playback\n"
            "define stream S (symbol string, price double, ts long);\n"
            "define table T (symbol string, price double);\n"
            "define window W (symbol string, price double) length(5);\n"
            "define trigger Tick at every 1 sec;\n"
            "define aggregation Agg from S select symbol, sum(price) as total "
            "group by symbol aggregate by ts every sec, min;\n"
            "from S select symbol, price insert into T;\n"
            "from S select symbol, price insert into W;\n"
            "@info(name='tq') from Tick select count() as n insert into TickCount;")
        got = []
        rt.add_query_callback("tq", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        h.send(("a", 5.0, 500))
        h.send(("a", 7.0, 1500))
        rt.heartbeat(2_000)
        assert len(rt.tables["T"]) == 2
        assert len(rt.query("from W select symbol")) == 2
        agg = rt.query("from Agg within 0, 10000 per 'sec' select total")
        assert sorted(e.data[0] for e in agg) == [pytest.approx(5.0),
                                                  pytest.approx(7.0)]
        assert got and got[-1].data[0] == 2  # trigger fired at 1s and 2s
