"""Distributed (sharded) incremental aggregation over a device mesh.

Reference: `isDistributed` mode — per-shard aggregation stores with a
shard-merged `find()` (core/aggregation/AggregationRuntime.java:87,266,384).
Here the duration stores carry a mesh-sharded shard axis keyed by group-hash
ownership; these tests assert exact parity with the single-device runtime on
the virtual 8-device CPU mesh (conftest forces it).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from siddhi_tpu import SiddhiManager

APP = """
define stream TradeStream (symbol string, price double, volume long, ts long);
define aggregation TradeAgg
from TradeStream
select symbol, avg(price) as avgPrice, sum(price) as total, count() as n
group by symbol
aggregate by ts every sec, min;
"""


def _mesh(n=8):
    devs = jax.devices()[:n]
    assert len(devs) == n
    return Mesh(np.asarray(devs), ("part",))


def _trades(n, n_keys, seed=3):
    rng = np.random.default_rng(seed)
    return [(f"S{int(k)}", float(round(p, 2)), int(v), int(t))
            for k, p, v, t in zip(
                rng.integers(0, n_keys, n), rng.uniform(1, 100, n),
                rng.integers(1, 50, n), rng.integers(0, 9000, n))]


def _run(mesh, rows, query):
    rt = SiddhiManager().create_siddhi_app_runtime(
        APP, batch_size=32, group_capacity=256, mesh=mesh)
    rt.start()
    h = rt.get_input_handler("TradeStream")
    for row in rows:
        h.send(row)
    rt.flush()
    out = [tuple(e.data) for e in rt.query(query)]
    rt.shutdown()
    return out


def test_sharded_state_has_shard_axis():
    mesh = _mesh()
    rt = SiddhiManager().create_siddhi_app_runtime(
        APP, batch_size=32, group_capacity=256, mesh=mesh)
    agg = rt.aggregations["TradeAgg"]
    assert agg.n_shards == 8
    assert agg.state[0].bucket_ts.shape[0] == 8
    rt.shutdown()


def test_sharded_find_matches_single_device():
    rows = _trades(96, 12)
    q = ("from TradeAgg within 0, 10000 per 'sec' "
         "select symbol, avgPrice, total, n")
    got = _run(_mesh(), rows, q)
    want = _run(None, rows, q)
    assert sorted(got) == pytest.approx(sorted(want))
    assert len(got) > 0


def test_sharded_rollup_and_within():
    rows = _trades(64, 5)
    q = "from TradeAgg within 0, 60000 per 'min' select symbol, total, n"
    got = _run(_mesh(), rows, q)
    want = _run(None, rows, q)
    assert sorted(got) == pytest.approx(sorted(want))
    # every group lands on exactly one shard: no duplicate (symbol) rows
    # for the single minute bucket
    syms = [g[0] for g in got]
    assert len(syms) == len(set(syms))


def test_sharded_join_against_aggregation():
    app = APP + """
    define stream Probe (symbol string, ts long);
    @info(name='j')
    from Probe as p
    join TradeAgg as a
    on p.symbol == a.symbol
    per 'sec'
    select p.symbol as symbol, a.total as total
    insert into Out;
    """
    rows = _trades(48, 4)

    def run(mesh):
        rt = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=32, group_capacity=256, mesh=mesh)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(tuple(e) for e in evs))
        rt.start()
        h = rt.get_input_handler("TradeStream")
        for row in rows:
            h.send(row)
        rt.flush()
        p = rt.get_input_handler("Probe")
        for s in ("S0", "S1", "S2", "S3"):
            p.send((s, 0))
        rt.flush()
        rt.shutdown()
        return got

    got, want = run(_mesh()), run(None)
    assert sorted(got) == pytest.approx(sorted(want))
    assert len(got) > 0


def test_per_host_sharded_ingestion_matches_replicated():
    """Per-host SHARDED ingestion (VERDICT r04 item 8): rows routed to
    their owning shard host-side, device_put lane-sharded onto the global
    mesh (parallel/multihost.global_lane_batch), ingested WITHOUT the
    replicated broadcast — merged find() must equal the replicated path.
    Single process: all shards are addressable, so this validates the
    routing + lane assembly + ingest_global program end to end."""
    from siddhi_tpu.parallel.multihost import global_lane_batch

    mesh = _mesh()
    rows = _trades(96, 8, seed=11)
    q = "from TradeAgg within 0, 10000 per 'sec' select symbol, total, n"
    want = sorted(_run(mesh, rows, q))

    rt = SiddhiManager().create_siddhi_app_runtime(
        APP, batch_size=32, group_capacity=256, mesh=mesh)
    rt.start()
    agg = rt.aggregations["TradeAgg"]
    codec = rt.junctions["TradeStream"].codec
    cols = {
        "symbol": np.array([r[0] for r in rows], dtype=object),
        "price": np.array([r[1] for r in rows]),
        "volume": np.array([r[2] for r in rows], dtype=np.int64),
        "ts": np.array([r[3] for r in rows], dtype=np.int64),
    }
    batch, dropped = global_lane_batch(
        codec, cols["ts"], cols, mesh, ["symbol"], lane_width=64)
    assert dropped == 0  # single process: every shard is local
    agg.ingest_global(batch, int(cols["ts"].max()) + 1)
    got = sorted(tuple(e.data) for e in rt.query(q))
    rt.shutdown()

    assert len(got) == len(want) > 0
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[2] == w[2], (g, w)
        assert abs(g[1] - w[1]) <= 1e-3 * max(1.0, abs(w[1])), (g, w)
