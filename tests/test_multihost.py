"""Multi-host integration: 2 localhost processes over jax.distributed.

VERDICT r3 item 6: prove `parallel/multihost.py` is capability, not recipe.
Each test spawns TWO real OS processes that connect through
`init_distributed` (CPU backend, 2 virtual devices per process -> a
4-device global mesh), run the SAME sharded-aggregation app, ingest the
same replicated event stream (the multi-process SPMD discipline: every
host executes the same sequence of global programs with consistent
replicated inputs), and assert the shard-merged `find()` on the mesh
equals the plain single-process result.

Runs outside the conftest CPU-mesh process on purpose: jax.distributed
must be initialized before any backend touch, so the workers are fresh
interpreters configured by env vars. The subprocess bring-up (ports,
PYTHONPATH, output capture, teardown, the "MULTIHOST UNSUPPORTED" named
skip) lives in conftest.WorkerFleet so every multi-process test shares it.
"""

WORKER = r"""
import os, sys
# platform config BEFORE jax import: 2 virtual CPU devices per process
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")

coordinator = sys.argv[1]
pid = int(sys.argv[2])

from siddhi_tpu.parallel.multihost import (global_mesh, init_distributed,
                                           is_coordinator)
init_distributed(coordinator=coordinator, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, len(jax.devices())  # 2 local x 2 processes


def _die_if_backend_cannot(e: BaseException):
    # jaxlib's CPU backend cannot execute cross-process computations at
    # all (XLA INVALID_ARGUMENT). That is an environment capability limit,
    # not an engine defect: report it as a sentinel the test harness turns
    # into a named skip, so the test stays REAL on TPU/GPU multi-host.
    if "Multiprocess computations aren't implemented" in str(e):
        print("MULTIHOST UNSUPPORTED:", str(e).strip().splitlines()[-1])
        sys.stdout.flush()
        os._exit(0)


_orig_excepthook = sys.excepthook


def _capability_hook(tp, val, tb):
    _die_if_backend_cannot(val)
    _orig_excepthook(tp, val, tb)


sys.excepthook = _capability_hook

import numpy as np
from siddhi_tpu import SiddhiManager

APP = '''
define stream TradeStream (symbol string, price double, volume long, ts long);
define aggregation TradeAgg
from TradeStream
select symbol, sum(price) as total, count() as n
group by symbol
aggregate by ts every sec, min;
'''
Q = "from TradeAgg within 0, 10000 per 'sec' select symbol, total, n"

rng = np.random.default_rng(11)
rows = [(f"S{int(k)}", float(round(p, 2)), int(v), int(t))
        for k, p, v, t in zip(rng.integers(0, 8, 48),
                              rng.uniform(1, 100, 48),
                              rng.integers(1, 50, 48),
                              rng.integers(0, 9000, 48))]

# --- mesh run: identical global program on both processes ---
mesh = global_mesh()
rt = SiddhiManager().create_siddhi_app_runtime(
    APP, batch_size=16, group_capacity=128, mesh=mesh)
rt.start()
h = rt.get_input_handler("TradeStream")
for row in rows:  # replicated ingestion: every host feeds the same stream
    h.send(row)
rt.flush()
got = sorted(tuple(e.data) for e in rt.query(Q))
rt.shutdown()

# --- single-process reference (no mesh) on the coordinator only ---
if is_coordinator():
    rt2 = SiddhiManager().create_siddhi_app_runtime(
        APP, batch_size=16, group_capacity=128)
    rt2.start()
    h2 = rt2.get_input_handler("TradeStream")
    for row in rows:
        h2.send(row)
    rt2.flush()
    want = sorted(tuple(e.data) for e in rt2.query(Q))
    rt2.shutdown()
    assert len(got) > 0, "mesh run produced no rows"
    assert len(got) == len(want), (len(got), len(want))
    for g, w in zip(got, want):
        assert g[0] == w[0], (g, w)
        assert abs(g[1] - w[1]) <= 1e-3 * max(1.0, abs(w[1])), (g, w)
        assert g[2] == w[2], (g, w)
    print("MULTIHOST PASS", len(got))
else:
    print("WORKER DONE")

# --- phase 2: per-host SHARDED ingestion (VERDICT r04 item 8) ---
# each host encodes and device_puts ONLY the rows its shards own; the
# merged find() must equal the union. String codes are made host-consistent
# by pre-encoding the symbol universe in one agreed order.
from siddhi_tpu.parallel.multihost import global_lane_batch
from siddhi_tpu.parallel.sharded import np_shard_of

rt3 = SiddhiManager().create_siddhi_app_runtime(
    APP, batch_size=16, group_capacity=128, mesh=mesh)
rt3.start()
codec = rt3.junctions["TradeStream"].codec
for s_ in [f"S{i}" for i in range(8)]:  # agreed interning order
    codec.string_tables["symbol"].encode(s_)

cols_all = {
    "symbol": np.array([r[0] for r in rows], dtype=object),
    "price": np.array([r[1] for r in rows]),
    "volume": np.array([r[2] for r in rows], dtype=np.int64),
    "ts": np.array([r[3] for r in rows], dtype=np.int64),
}
# external partitioner: this host keeps only rows its LOCAL shards own
enc_sym = codec.string_tables["symbol"].encode_array(cols_all["symbol"])
shard_of = np_shard_of([enc_sym], 4)
mesh_flat = list(mesh.devices.flat)
local = np.isin(shard_of,
                [i for i, d in enumerate(mesh_flat)
                 if d.process_index == jax.process_index()])
host_cols = {k: v[local] for k, v in cols_all.items()}
assert 0 < local.sum() < len(rows)  # genuinely disjoint split

batch, dropped = global_lane_batch(
    rt3.junctions["TradeStream"].codec, host_cols["ts"], host_cols, mesh,
    ["symbol"], lane_width=48)
assert dropped == 0, dropped
rt3.aggregations["TradeAgg"].ingest_global(
    batch, int(cols_all["ts"].max()) + 1)
got3 = sorted(tuple(e.data) for e in rt3.query(Q))
rt3.shutdown()
if is_coordinator():
    assert len(got3) == len(want), (len(got3), len(want))
    for g, w in zip(got3, want):
        assert g[0] == w[0] and g[2] == w[2], (g, w)
        assert abs(g[1] - w[1]) <= 1e-3 * max(1.0, abs(w[1])), (g, w)
    print("MULTIHOST SHARDED-INGEST PASS", len(got3))
else:
    print("WORKER2 DONE")
"""


def test_two_process_sharded_aggregation(worker_fleet):
    coordinator = f"127.0.0.1:{worker_fleet.free_port()}"
    procs = [worker_fleet.spawn_script(WORKER, [coordinator, i],
                                       name=f"worker{i}.py")
             for i in range(2)]
    outs = worker_fleet.communicate_all(timeout=420)
    worker_fleet.skip_if_unsupported(outs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
    assert "MULTIHOST PASS" in outs[0], outs[0][-3000:]
    assert "WORKER DONE" in outs[1], outs[1][-3000:]
    assert "MULTIHOST SHARDED-INGEST PASS" in outs[0], outs[0][-3000:]
    assert "WORKER2 DONE" in outs[1], outs[1][-3000:]
