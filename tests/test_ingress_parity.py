"""Parallel-ingress parity & conservation tests (core/ingress.py).

The acceptance bar: the ingress pipeline must be INVISIBLE downstream — the
same single-producer row stream yields bit-identical delivered blocks
(timestamps, every column including string dictionary codes, expiry flags)
whether it runs through the lock-free pipeline or the plain synchronous
staging path, and with either the C colring or the pure-Python fallback
underneath. CI runs this module twice: once natively and once with
SIDDHI_NATIVE=0, so both ring implementations face the same oracle.
Multi-producer runs cannot promise delivery order, so their invariant is
exact conservation: sent == delivered + dropped.
"""

import threading

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu import native as native_mod

pytestmark = pytest.mark.smoke

BS = 64  # micro-batch capacity for both variants (buffer.size == batch_size)

APP_PIPE = f"""
@app:name('Pipe')
@Async(buffer.size='{BS}', workers='2')
define stream TradeStream (symbol string, price double, volume long);
@info(name='q')
from TradeStream[price < 700.0]
select symbol, price, volume
insert into OutStream;
"""

#: same query, no @Async: the synchronous staging path is the oracle
APP_SERIAL = """
@app:name('Serial')
define stream TradeStream (symbol string, price double, volume long);
@info(name='q')
from TradeStream[price < 700.0]
select symbol, price, volume
insert into OutStream;
"""


def _rows(n: int, seed: int = 11):
    rng = np.random.default_rng(seed)
    ks = rng.integers(1, 40, n)
    ps = rng.uniform(1.0, 1000.0, n)
    vs = rng.integers(1, 1000, n)
    rows = [(f"S{int(k)}", float(p), int(v))
            for k, p, v in zip(ks, ps, vs)]
    for i in range(0, n, 17):  # sprinkle nulls through the string column
        rows[i] = (None,) + rows[i][1:]
    return rows


def _capture(app: str, feed, *, batch_size=None):
    """Build, feed via `feed(handler, runtime)`, return the delivered blocks
    as host tuples (ts, {col: array}, expired) for bit-exact comparison."""
    kw = {"batch_size": batch_size} if batch_size else {}
    rt = SiddhiManager().create_siddhi_app_runtime(app, **kw)
    blocks: list = []
    rt.add_callback("OutStream", lambda b: blocks.append(
        (b.timestamps.copy(),
         {k: v.copy() for k, v in b.columns.items()},
         b.is_expired.copy())), columnar=True)
    rt.start()
    try:
        feed(rt.get_input_handler("TradeStream"), rt)
        rt.drain()
    finally:
        rt.shutdown()
    return blocks


def _assert_blocks_identical(got, want):
    assert len(got) == len(want)
    for (gt, gc, ge), (wt, wc, we) in zip(got, want):
        np.testing.assert_array_equal(gt, wt)
        np.testing.assert_array_equal(ge, we)
        assert gc.keys() == wc.keys()
        for k in wc:
            assert gc[k].dtype == wc[k].dtype, k
            np.testing.assert_array_equal(gc[k], wc[k], err_msg=k)


def _pipeline_of(rt):
    return rt.junctions["TradeStream"]._pipeline


class TestBitParity:
    """Single producer: identical chunk boundaries, padding, and interning
    order are guaranteed by construction — so the blocks must match bit for
    bit, dictionary codes included."""

    def test_rows_path(self):
        rows = _rows(500)
        tss = np.arange(1, 501, dtype=np.int64)

        def feed(h, rt):
            h.send_batch(rows, timestamps=tss)
            rt.flush()

        pipe = _capture(APP_PIPE, feed)
        serial = _capture(APP_SERIAL, feed, batch_size=BS)
        assert sum(len(b[0]) for b in pipe) > 0
        _assert_blocks_identical(pipe, serial)

    def test_columns_path(self):
        rows = _rows(300, seed=12)
        cols = {
            "symbol": np.array([r[0] for r in rows], dtype=object),
            "price": np.array([r[1] for r in rows]),
            "volume": np.array([r[2] for r in rows], dtype=np.int64),
        }
        tss = np.arange(10, 310, dtype=np.int64)

        def feed(h, rt):
            h.send_columns(cols, timestamps=tss)
            rt.flush()

        pipe = _capture(APP_PIPE, feed)
        serial = _capture(APP_SERIAL, feed, batch_size=BS)
        _assert_blocks_identical(pipe, serial)

    def test_wire_frames_path(self):
        from siddhi_tpu.io import wire
        rows = _rows(400, seed=13)
        cols = {
            "symbol": np.array([r[0] for r in rows], dtype=object),
            "price": np.array([r[1] for r in rows]),
            "volume": np.array([r[2] for r in rows], dtype=np.int64),
        }
        tss = np.arange(5, 405, dtype=np.int64)

        def feed_frames(h, rt):
            plan = wire.schema_plan(h.junction.definition)
            body = wire.encode_frames(plan, cols, 400, ts=tss, chunk=96)
            assert wire.deliver_frames(h, body) == 400
            rt.flush()

        def feed_serial(h, rt):
            h.send_columns(cols, timestamps=tss)
            rt.flush()

        pipe = _capture(APP_PIPE, feed_frames)
        serial = _capture(APP_SERIAL, feed_serial, batch_size=BS)
        _assert_blocks_identical(pipe, serial)

    def test_pipeline_actually_engaged(self):
        """Guard against the parity tests silently comparing serial vs
        serial (e.g. the gate falling back): the @Async(workers=) app must
        run the pipeline, and its stats must show the traffic."""
        rows = _rows(200, seed=14)
        tss = np.arange(1, 201, dtype=np.int64)
        seen: dict = {}

        def feed(h, rt):
            p = _pipeline_of(rt)
            assert p is not None, "pipeline did not engage"
            h.send_batch(rows, timestamps=tss)
            rt.flush()
            seen.update(p.stats_snapshot())

        _capture(APP_PIPE, feed)
        assert seen["rows_in"] == 200
        assert seen["batches_delivered"] >= 1
        assert seen["ring_depth_hwm"] >= 1
        assert set(seen["stage_ms"]) == {"decode", "intern", "h2d", "device"}

    def test_fallback_ring_selected_without_native(self):
        """With SIDDHI_NATIVE=0 (or the C module missing) the pipeline must
        ride the pure-Python ring — same API, same parity oracle."""
        from siddhi_tpu.core.ingress import _PyColRing

        def feed(h, rt):
            p = _pipeline_of(rt)
            assert p is not None
            if native_mod.available() and hasattr(native_mod.native,
                                                 "colring_new"):
                assert not isinstance(p.ring, _PyColRing)
            else:
                assert isinstance(p.ring, _PyColRing)
            h.send_batch(_rows(64), timestamps=np.arange(64, dtype=np.int64))
            rt.flush()

        _capture(APP_PIPE, feed)


class TestConservation:
    """Multi-producer: order is unspecified, accounting is not. Every sent
    event is delivered exactly once or counted as dropped — under the
    pipeline (block policy) and under the fallback ring (drop policies,
    where @Async(workers=) gates back to the MPSC path)."""

    N_PRODUCERS = 4
    PER_PRODUCER = 600

    def _stress(self, app: str, *, expect_pipeline: bool):
        rt = SiddhiManager().create_siddhi_app_runtime(app)
        delivered = [0]
        lock = threading.Lock()

        def cb(b):
            with lock:
                delivered[0] += b.count

        rt.add_callback("OutStream", cb, columnar=True)
        rt.start()
        try:
            assert (rt.junctions["TradeStream"]._pipeline
                    is not None) == expect_pipeline
            h = rt.get_input_handler("TradeStream")
            rows = _rows(self.PER_PRODUCER, seed=21)

            def produce(p):
                tss = np.arange(p * self.PER_PRODUCER,
                                (p + 1) * self.PER_PRODUCER, dtype=np.int64)
                h.send_batch(rows, timestamps=tss)

            threads = [threading.Thread(target=produce, args=(p,))
                       for p in range(self.N_PRODUCERS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            rt.flush()
        finally:
            rt.shutdown()  # drains whatever is still staged
        rep = rt.statistics_report()
        sent = self.N_PRODUCERS * self.PER_PRODUCER
        dropped = sum(rep["ingress_dropped"].get("TradeStream", {}).values())
        discarded = rep["recovery"]["shutdown_discarded"]
        # pass-through query: every admitted row reaches the callback, so
        # conservation is exact — delivered + dropped + discarded == sent
        assert delivered[0] + dropped + discarded == sent
        return delivered[0]

    def test_pipeline_block_policy_conserves(self):
        app = ("@app:name('C1')\n"
               "@Async(buffer.size='128', workers='2', "
               "overflow.policy='block', block.timeout='30 sec')\n"
               "define stream TradeStream "
               "(symbol string, price double, volume long);\n"
               "@info(name='q') from TradeStream "
               "select symbol, price, volume insert into OutStream;")
        self._stress(app, expect_pipeline=True)

    def test_drop_policy_falls_back_and_conserves(self):
        app = ("@app:name('C2')\n"
               "@Async(buffer.size='128', workers='2', "
               "overflow.policy='drop.old', max.staged='512')\n"
               "define stream TradeStream "
               "(symbol string, price double, volume long);\n"
               "@info(name='q') from TradeStream "
               "select symbol, price, volume insert into OutStream;")
        self._stress(app, expect_pipeline=False)


class TestStatisticsSection:
    def test_ingress_pipeline_section_always_present(self):
        """statistics_report() carries the section even for apps with no
        pipeline (empty dict) — dashboards key on it unconditionally."""
        rt = SiddhiManager().create_siddhi_app_runtime(APP_SERIAL)
        try:
            rep = rt.statistics_report()
            assert rep["ingress_pipeline"] == {}
        finally:
            rt.shutdown()

    def test_ingress_pipeline_section_populated(self):
        rt = SiddhiManager().create_siddhi_app_runtime(APP_PIPE)
        rt.start()
        try:
            h = rt.get_input_handler("TradeStream")
            h.send_batch(_rows(100),
                         timestamps=np.arange(100, dtype=np.int64))
            rt.flush()
            rep = rt.statistics_report()
            sec = rep["ingress_pipeline"]["TradeStream"]
            assert sec["workers"] == 2
            assert sec["rows_in"] == 100
            for key in ("ring_depth_hwm", "h2d_overlap_ratio",
                        "worker_utilization", "stage_ms"):
                assert key in sec
        finally:
            rt.shutdown()
