"""siddhi_tpu.analysis tests: golden corpus (one seeded defect per rule,
exact rule IDs), suppression, the SIDDHI_LINT startup gate, the jaxpr hazard
pass, the CLI, REST validate, and the zero-false-positive sweep over every
app string that builds in this tree."""

import json
import pathlib
import re
import threading
import urllib.request

import pytest

from siddhi_tpu import compiler
from siddhi_tpu.analysis import Severity, analyze
from siddhi_tpu.analysis.rules import RULES
from siddhi_tpu.core.manager import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError, SiddhiParserError
from siddhi_tpu.lint import lint_text, main as lint_main

pytestmark = pytest.mark.smoke

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "tests" / "lint_corpus"

#: filename prefix → (expected rule, expected severity)
CORPUS_EXPECTATIONS = {
    "sl101": ("SL101", Severity.ERROR),
    "sl102": ("SL102", Severity.WARN),
    "sl103": ("SL103", Severity.ERROR),
    "sl104": ("SL104", Severity.ERROR),
    "sl105": ("SL105", Severity.INFO),
    "sl106": ("SL106", Severity.WARN),
    "sl107": ("SL107", Severity.WARN),
    "sl108": ("SL108", Severity.WARN),
    "sl109": ("SL109", Severity.ERROR),
    "sl110": ("SL110", Severity.WARN),
    "sl111": ("SL111", Severity.ERROR),
    "sl112": ("SL112", Severity.ERROR),
    "sl113": ("SL113", Severity.WARN),
    "sl114": ("SL114", Severity.INFO),
    "sl116": ("SL116", Severity.ERROR),
    "sl501": ("SL501", Severity.ERROR),
    "sl502": ("SL502", Severity.ERROR),
    "sl503": ("SL503", Severity.WARN),
    "sl505": ("SL505", Severity.INFO),
    "sl506": ("SL506", Severity.INFO),
    "sl601": ("SL601", Severity.ERROR),
    "sl602": ("SL602", Severity.WARN),
}


def _corpus_files():
    files = sorted(CORPUS.glob("*.siddhi"))
    assert len(files) == len(CORPUS_EXPECTATIONS)
    return files


class TestGoldenCorpus:
    @pytest.mark.parametrize("path", _corpus_files(),
                             ids=lambda p: p.stem)
    def test_corpus_app_flags_its_rule(self, path):
        rule_id, severity = CORPUS_EXPECTATIONS[path.stem.split("_")[0]]
        report = analyze(path.read_text())
        hits = [d for d in report.diagnostics if d.rule_id == rule_id]
        assert hits, (f"{path.name}: expected {rule_id}, got "
                      f"{[d.rule_id for d in report.diagnostics]}")
        assert all(d.severity is severity for d in hits)
        # the seeded defect is the ONLY rule of its severity class firing
        same_class = {d.rule_id for d in report.diagnostics
                      if d.severity is severity}
        assert same_class == {rule_id}

    def test_corpus_diagnostics_carry_locations(self):
        for path in _corpus_files():
            report = analyze(path.read_text())
            assert all(d.loc is not None for d in report.diagnostics), \
                path.name

    def test_rule_catalog_ids_are_unique(self):
        ids = [r[0] for r in RULES]
        assert len(ids) == len(set(ids))
        assert set(CORPUS_EXPECTATIONS.values()) <= {
            (rid, sev) for rid, sev, _fn, _d in RULES}


class TestSuppression:
    def test_element_level_suppression(self):
        app = """
        define stream S (price double);
        @suppress.lint('SL110')
        from S[1 > 2] select price insert into Out;
        """
        assert "SL110" not in analyze(app).rule_counts()

    def test_app_level_suppression(self):
        app = """
        @app:name('Sup')
        @suppress.lint('SL102')
        define stream Orphan (x int);
        define stream S (price double);
        from S select price insert into Out;
        """
        assert "SL102" not in analyze(app).rule_counts()

    def test_argless_suppression_silences_element(self):
        app = """
        define stream S (price double);
        @suppress.lint
        from S[1 > 2] select price insert into Out;
        """
        assert analyze(app).rule_counts() == {}

    def test_unsuppressed_still_fires(self):
        app = """
        define stream S (price double);
        from S[1 > 2] select price insert into Out;
        """
        assert "SL110" in analyze(app).rule_counts()


class TestLintGate:
    BAD = (CORPUS / "sl109_shadowed_query.siddhi").read_text()
    GOOD = """
    @app:name('CleanApp')
    define stream S (price double);
    from S[price > 0.0] select price insert into Out;
    """

    def test_default_warn_mode_builds_and_attaches_report(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_LINT", raising=False)
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(self.BAD)
        assert rt.lint_report is not None
        assert rt.lint_report.has_errors
        m.shutdown()

    def test_error_mode_refuses_corpus_app(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_LINT", "error")
        m = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError, match="SL109"):
            m.create_siddhi_app_runtime(self.BAD)
        m.shutdown()

    def test_error_mode_accepts_clean_app(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_LINT", "error")
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(self.GOOD)
        assert not rt.lint_report.has_errors
        m.shutdown()

    def test_off_mode_skips_lint(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_LINT", "off")
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(self.BAD)
        assert rt.lint_report is None
        m.shutdown()

    def test_statistics_report_carries_lint_section(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_LINT", raising=False)
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(self.BAD)
        stats = rt.statistics_report()
        assert stats["lint"]["valid"] is False
        assert stats["lint"]["rules"].get("SL109") == 1
        m.shutdown()

    def test_manager_validate_returns_report_without_runtime(self):
        m = SiddhiManager()
        report = m.validate(self.BAD)
        assert "SL109" in report.rule_counts()
        assert not m.runtimes


class TestJaxprPass:
    def test_detects_radix_argsort_host_callback(self, monkeypatch):
        # the packed-key device sort retired the CPU radix pure_callback;
        # re-enable it via the legacy escape hatch so the SL201 detector
        # (host callback in the traced jaxpr) still has a live target
        monkeypatch.setenv("SIDDHI_RADIX_CALLBACK", "1")
        app = """
        define stream S (symbol string, price double);
        @info(name='grouped')
        from S#window.lengthBatch(16)
        select symbol, avg(price) as ap
        group by symbol
        insert into Out;
        """
        report = analyze(app, jaxpr=True)
        hits = [d for d in report.diagnostics if d.rule_id == "SL201"]
        assert hits and hits[0].severity is Severity.WARN
        assert "radix" in hits[0].message or "host" in hits[0].message

    def test_clean_passthrough_has_no_callback_warning(self):
        app = """
        define stream S (price double);
        from S[price > 0.0] select price insert into Out;
        """
        report = analyze(app, jaxpr=True)
        assert "SL201" not in report.rule_counts()


class TestParseErrorLocations:
    def test_parse_error_carries_line_column_snippet(self):
        with pytest.raises(SiddhiParserError) as ei:
            compiler.parse("define stream S (price double);\nfrom ???")
        e = ei.value
        assert e.line == 2
        assert e.snippet and "^" in e.snippet
        assert f"at line {e.line}:" in str(e)

    def test_lint_text_wraps_parse_failure_as_sl000(self):
        report = lint_text("define stream S (price double")
        assert report.rule_counts() == {"SL000": 1}
        d = report.diagnostics[0]
        assert d.severity is Severity.ERROR and d.loc is not None

    def test_lint_and_parser_share_location_format(self):
        report = analyze((CORPUS / "sl110_dead_query.siddhi").read_text())
        d = report.diagnostics[0]
        assert re.search(r" at line \d+:\d+$", d.format())


class TestCli:
    def test_cli_flags_whole_corpus(self, capsys):
        rc = lint_main(["--scan", "--json", str(CORPUS)])
        out = json.loads(capsys.readouterr().out)
        assert len(out) == len(CORPUS_EXPECTATIONS)
        for path, result in out.items():
            rule_id, _sev = CORPUS_EXPECTATIONS[
                pathlib.Path(path).stem.split("_")[0]]
            assert rule_id in result["counts"], path
        assert rc in (0, 1)  # 1 iff some corpus rule is an ERROR

    def test_cli_exit_codes(self, tmp_path, capsys):
        clean = tmp_path / "clean.siddhi"
        clean.write_text("define stream S (price double);\n"
                         "from S[price > 0.0] select price insert into O;\n")
        assert lint_main([str(clean)]) == 0
        bad = tmp_path / "bad.siddhi"
        bad.write_text("define stream S (price double);\n"
                       "from Ghost select * insert into O;\n")
        assert lint_main([str(bad)]) == 1
        broken = tmp_path / "broken.siddhi"
        broken.write_text("define stream S (")
        assert lint_main([str(broken)]) == 2
        capsys.readouterr()


class TestRestValidate:
    @pytest.fixture()
    def server(self):
        from siddhi_tpu.service import SiddhiService
        svc = SiddhiService(token="tkn")
        httpd = svc.make_server(port=0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        yield f"http://127.0.0.1:{httpd.server_address[1]}"
        httpd.shutdown()

    def _post(self, url, body, token=None):
        req = urllib.request.Request(url, data=body.encode(), method="POST")
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:  # pragma: no cover - auth path
            return e.code, json.loads(e.read())

    def test_validate_endpoint_reports_without_deploying(self, server):
        bad = (CORPUS / "sl101_undefined_stream.siddhi").read_text()
        code, body = self._post(f"{server}/siddhi-apps/validate", bad,
                                token="tkn")
        assert code == 200
        assert body["valid"] is False
        assert "SL101" in body["counts"]

    def test_validate_requires_auth(self, server):
        import urllib.error
        req = urllib.request.Request(
            f"{server}/siddhi-apps/validate",
            data=b"define stream S (x int);", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 401

    def test_validate_handles_parse_failure_in_band(self, server):
        code, body = self._post(f"{server}/siddhi-apps/validate",
                                "define stream S (", token="tkn")
        assert code == 200
        assert body["counts"] == {"SL000": 1}


TRIPLE = re.compile(r'("""|\'\'\')(.*?)\1', re.DOTALL)


def _in_tree_app_strings():
    """Every triple-quoted SiddhiQL-looking string under tests/ + samples/."""
    for root in ("tests", "samples"):
        for p in (REPO / root).rglob("*.py"):
            for m in TRIPLE.finditer(p.read_text()):
                s = m.group(2)
                if "define stream" in s and (
                        "insert into" in s or "select" in s):
                    yield str(p), s


def test_zero_false_positives_on_in_tree_apps(monkeypatch):
    """Every app string in this tree that parses AND builds must lint with
    zero ERROR findings — the linter may not reject working apps."""
    monkeypatch.setenv("SIDDHI_LINT", "off")
    m = SiddhiManager()
    built = 0
    failures = []
    for src, text in _in_tree_app_strings():
        try:
            app = compiler.parse(text)
        except Exception:
            continue  # deliberately-invalid fixtures are out of scope
        try:
            rt = m.create_siddhi_app_runtime(app)
        except Exception:
            continue
        built += 1
        report = analyze(app)
        if report.has_errors:
            failures.append((src, [d.format() for d in report.errors]))
        rt.shutdown()
        m.runtimes.pop(app.name, None)
    assert built >= 25, f"sweep found too few buildable apps ({built})"
    assert not failures, failures
