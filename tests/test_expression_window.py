"""expression / expressionBatch window tests (reference:
query/window/ExpressionWindowTestCase, ExpressionBatchWindowTestCase)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError

S = "define stream S (symbol string, price double, volume long);\n"


def build(app, batch_size=4):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    rt.start()
    return rt


def collect_all(rt, name="q"):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.append(
        ([tuple(e.data) for e in i or []], [tuple(e.data) for e in r or []])))
    return got


class TestExpressionWindow:
    def test_count_condition_behaves_like_length(self):
        rt = build(S + "@info(name='q') from S#window.expression('count() <= 2') "
                   "select symbol, sum(price) as total "
                   "insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([1.0, 2.0, 4.0, 8.0]):
            h.send((f"s{i}", p, i), timestamp=i)
        rt.flush()
        sums = [e[1] for pair in got for e in pair[0]]
        removed = [e[1] for pair in got for e in pair[1]]
        # pop-after-arrival (reference ExpressionWindowProcessor): the
        # arrival emits with the pre-pop sum, the popped event emits next
        assert sums == [1.0, 3.0, 7.0, 14.0]
        assert removed == [6.0, 12.0]

    def test_sum_condition(self):
        rt = build(S + "@info(name='q') from S"
                   "#window.expression('sum(price) <= 10.0') "
                   "select symbol, price insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        # prices 6,5 -> 6 must expire when 5 arrives (11 > 10)
        h.send(("a", 6.0, 0), timestamp=0)
        h.send(("b", 5.0, 1), timestamp=1)
        h.send(("c", 4.0, 2), timestamp=2)
        rt.flush()
        expired = [e[0] for pair in got for e in pair[1]]
        assert expired == ["a"]  # 6 evicted; 5+4=9 <= 10 stays

    def test_ts_span_condition(self):
        rt = build(S + "@info(name='q') from S#window.expression("
                   "'eventTimestamp(last) - eventTimestamp(first) < 5000') "
                   "select symbol insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0, 0), timestamp=1_000)
        h.send(("b", 1.0, 1), timestamp=2_000)
        h.send(("c", 1.0, 2), timestamp=7_500)  # span 6500 -> a,b evicted
        rt.flush()
        expired = [e[0] for pair in got for e in pair[1]]
        assert expired == ["a", "b"]

    def test_non_monotone_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="monotone|bound"):
            build(S + "@info(name='q') from S"
                  "#window.expression('count() > 3') "
                  "select symbol insert into Out;")

    def test_or_rejected(self):
        with pytest.raises(SiddhiAppCreationError):
            build(S + "@info(name='q') from S#window.expression("
                  "'count() < 3 or sum(price) < 5.0') "
                  "select symbol insert into Out;")


class TestExpressionBatchWindow:
    def test_count_form_is_length_batch(self):
        rt = build(S + "@info(name='q') from S"
                   "#window.expressionBatch('count() <= 2') "
                   "select symbol, sum(price) as t insert into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([1.0, 2.0, 4.0, 8.0]):
            h.send((f"s{i}", p, i), timestamp=i)
        rt.flush()
        sums = [e[1] for pair in got for e in pair[0]]
        assert sums == [1.0, 3.0, 4.0, 12.0]  # flushes of 2

    def test_non_count_form_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="count"):
            build(S + "@info(name='q') from S"
                  "#window.expressionBatch('sum(price) <= 10.0') "
                  "select symbol insert into Out;")
