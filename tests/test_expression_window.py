"""expression / expressionBatch window tests (reference:
query/window/ExpressionWindowTestCase, ExpressionBatchWindowTestCase)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError

S = "define stream S (symbol string, price double, volume long);\n"


def build(app, batch_size=4):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    rt.start()
    return rt


def collect_all(rt, name="q"):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.append(
        ([tuple(e.data) for e in i or []], [tuple(e.data) for e in r or []])))
    return got


class TestExpressionWindow:
    def test_count_condition_behaves_like_length(self):
        rt = build(S + "@info(name='q') from S#window.expression('count() <= 2') "
                   "select symbol, sum(price) as total "
                   "insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([1.0, 2.0, 4.0, 8.0]):
            h.send((f"s{i}", p, i), timestamp=i)
        rt.flush()
        sums = [e[1] for pair in got for e in pair[0]]
        removed = [e[1] for pair in got for e in pair[1]]
        # pop-after-arrival (reference ExpressionWindowProcessor): the
        # arrival emits with the pre-pop sum, the popped event emits next
        assert sums == [1.0, 3.0, 7.0, 14.0]
        assert removed == [6.0, 12.0]

    def test_sum_condition(self):
        rt = build(S + "@info(name='q') from S"
                   "#window.expression('sum(price) <= 10.0') "
                   "select symbol, price insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        # prices 6,5 -> 6 must expire when 5 arrives (11 > 10)
        h.send(("a", 6.0, 0), timestamp=0)
        h.send(("b", 5.0, 1), timestamp=1)
        h.send(("c", 4.0, 2), timestamp=2)
        rt.flush()
        expired = [e[0] for pair in got for e in pair[1]]
        assert expired == ["a"]  # 6 evicted; 5+4=9 <= 10 stays

    def test_ts_span_condition(self):
        rt = build(S + "@info(name='q') from S#window.expression("
                   "'eventTimestamp(last) - eventTimestamp(first) < 5000') "
                   "select symbol insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0, 0), timestamp=1_000)
        h.send(("b", 1.0, 1), timestamp=2_000)
        h.send(("c", 1.0, 2), timestamp=7_500)  # span 6500 -> a,b evicted
        rt.flush()
        expired = [e[0] for pair in got for e in pair[1]]
        assert expired == ["a", "b"]

class TestGeneralExpressionWindow:
    """Arbitrary (non-monotone) conditions: the exact sequential pop-loop
    (reference: ExpressionWindowProcessor.java:204-234 — append, evaluate
    over (current, first, last) + running aggregates, pop-from-front while
    false with `current` rebinding to the popped event)."""

    def _run(self, condition, events, flush_each=True):
        rt = build(S + f"@info(name='q') from S#window.expression("
                   f"'{condition}') "
                   "select symbol, price insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        for i, (sym, price) in enumerate(events):
            h.send((sym, price, i), timestamp=i)
            if flush_each:
                rt.flush()
        rt.flush()
        current = [e[0] for pair in got for e in pair[0]]
        expired = [e[0] for pair in got for e in pair[1]]
        return current, expired

    def test_inverted_count_expires_everything(self):
        # count() > 3 can never become true by adding one event to a window
        # kept empty: each arrival is popped straight back out
        current, expired = self._run(
            "count() > 3", [("a", 1.0), ("b", 2.0), ("c", 3.0)])
        assert current == ["a", "b", "c"]
        assert expired == ["a", "b", "c"]

    def test_current_attribute_condition(self):
        # cheap arrivals purge from the front until a >=10 event pops
        # (current rebinds to the popped event in the pop loop)
        current, expired = self._run(
            "price >= 10.0",
            [("a", 5.0), ("b", 12.0), ("c", 3.0), ("d", 20.0)])
        assert current == ["a", "b", "c", "d"]
        # a pops itself (empty window, still false -> loop ends);
        # c pops b (12 >= 10 -> stop), then c STAYS; d keeps all
        assert expired == ["a", "b"]

    def test_or_condition(self):
        current, expired = self._run(
            "sum(price) < 10.0 or count() <= 1",
            [("a", 6.0), ("b", 5.0), ("c", 9.0)])
        # b: sum 11, count 2 -> pop a -> [b] ok; c: sum 14 -> pop b -> ok
        assert expired == ["a", "b"]

    def test_avg_condition_empties_window(self):
        current, expired = self._run(
            "avg(price) < 5.0", [("a", 4.0), ("b", 8.0), ("c", 2.0)])
        # b: avg 6 -> pop a (avg 8, false) -> pop b (empty, loop ends);
        # c: avg 2 ok
        assert expired == ["a", "b"]

    def test_sum_exact_matches_monotone_shape(self):
        # the same data as TestExpressionWindow.test_sum_condition — the
        # general path must agree on monotone-friendly input
        current, expired = self._run(
            "sum(price) <= 10.0", [("a", 6.0), ("b", 5.0), ("c", 4.0)],
            flush_each=False)
        assert expired == ["a"]

    def test_unsupported_function_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="min"):
            build(S + "@info(name='q') from S#window.expression("
                  "'min(price) < 5.0') select symbol insert into Out;")

    def test_string_constant_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="string"):
            build(S + "@info(name='q') from S#window.expression("
                  "\"symbol == 'IBM'\") select symbol insert into Out;")


class TestExpressionBatchWindow:
    def test_count_form_is_length_batch(self):
        rt = build(S + "@info(name='q') from S"
                   "#window.expressionBatch('count() <= 2') "
                   "select symbol, sum(price) as t insert into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([1.0, 2.0, 4.0, 8.0]):
            h.send((f"s{i}", p, i), timestamp=i)
        rt.flush()
        sums = [e[1] for pair in got for e in pair[0]]
        assert sums == [1.0, 3.0, 4.0, 12.0]  # flushes of 2

    def test_sum_form_segments_greedily(self):
        """Reference ExpressionBatchWindowProcessor: accumulate while the
        condition (including the arrival) holds; on break, flush the window
        and start a new one with the trigger."""
        rt = build(S + "@info(name='q') from S"
                   "#window.expressionBatch('sum(price) <= 10.0') "
                   "select symbol, price insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        for i, (sym, p) in enumerate([("a", 4.0), ("b", 5.0), ("c", 3.0),
                                      ("d", 8.0), ("e", 1.0)]):
            h.send((sym, p, i), timestamp=i)
            rt.flush()
        # c breaks 4+5+3=12: flush [a,b], window [c]; d breaks 3+8=11:
        # flush [c] (+ expired [a,b]), window [d]; e accumulates (9 <= 10)
        current = [e[0] for pair in got for e in pair[0]]
        expired = [e[0] for pair in got for e in pair[1]]
        assert current == ["a", "b", "c"]
        assert expired == ["a", "b"]

    def test_include_triggering_event(self):
        rt = build(S + "@info(name='q') from S"
                   "#window.expressionBatch('count() <= 2', true) "
                   "select symbol insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        for i, sym in enumerate(["a", "b", "c", "d", "e", "f"]):
            h.send((sym, 1.0, i), timestamp=i)
            rt.flush()
        current = [e[0] for pair in got for e in pair[0]]
        expired = [e[0] for pair in got for e in pair[1]]
        # count()<=2 with the trigger included: flushes of 3
        assert current == ["a", "b", "c", "d", "e", "f"]
        assert expired == ["a", "b", "c"]

    def test_oversized_single_event_passes_through(self):
        """An arrival that breaks the condition on an EMPTY window flushes
        itself immediately as [EXPIRED, CURRENT] and leaves no previous
        batch (reference else-branch, ExpressionBatchWindowProcessor)."""
        rt = build(S + "@info(name='q') from S"
                   "#window.expressionBatch('sum(price) <= 10.0') "
                   "select symbol insert all events into Out;")
        got = collect_all(rt)
        h = rt.get_input_handler("S")
        h.send(("big", 50.0, 0), timestamp=0)
        rt.flush()
        h.send(("a", 4.0, 1), timestamp=1)
        h.send(("b", 9.0, 2), timestamp=2)  # breaks: flush [a], window [b]
        rt.flush()
        current = [e[0] for pair in got for e in pair[0]]
        expired = [e[0] for pair in got for e in pair[1]]
        assert current == ["big", "a"]
        # big expires in its own flush; [a]'s flush has no prior batch
        assert expired == ["big"]

    def test_stream_mode_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="3rd parameter"):
            build(S + "@info(name='q') from S"
                  "#window.expressionBatch('count() <= 2', false, true) "
                  "select symbol insert into Out;")
