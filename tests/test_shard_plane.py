"""Sharded execution plane (parallel/shard_plane.py + the ShardRouter).

The correctness contract under test: for a shard-eligible (key-local) app,
the MERGED output of N shards is bit-identical to the serial engine's as a
sorted multiset AND per partition key as an ordered sequence — routing
happens over ORIGINAL values before interning, per-key order is preserved
by the boolean-mask split, and a key's state never leaves its shard. All
values are multiples of 0.25 so per-key partial sums are exactly
representable: equality below is `==` on floats, not approx.

Plus the operational surface: the routing conservation identity, loud
SL601 refusal of global plans, skew-triggered rebalancing (epoch protocol,
WAL re-routing, refusal conditions), single-shard moves, kill/recover, and
the duck-typed manager/service integration (error store, upgrade guard,
Prometheus families).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.ingress import ShardRouter
from siddhi_tpu.errors import SiddhiAppCreationError
from siddhi_tpu.state.persistence import FileSystemPersistenceStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHARDED_APP = """
@app:name('PlaneApp')
@app:shards(n='4', key='k')
define stream S (k string, v double);
@info(name='agg')
from S select k, sum(v) as total, count() as n group by k insert into Out;
"""
SERIAL_APP = SHARDED_APP.replace("@app:shards(n='4', key='k')\n", "") \
                        .replace("PlaneApp", "PlaneAppSerial")


def _rows(n: int, seed: int = 5, n_keys: int = 13):
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, n_keys, n)
    vs = rng.integers(1, 400, n) * 0.25  # exact in float64
    return [(f"K{int(k)}", float(v)) for k, v in zip(ks, vs)]


def _by_key(rows_out):
    seqs: dict = {}
    for r in rows_out:
        seqs.setdefault(r[0], []).append(r)
    return seqs


def _run(mgr, app_text, rows, *, wal_dir=None, shutdown=True):
    rt = mgr.create_siddhi_app_runtime(app_text, wal_dir=wal_dir)
    out: list = []
    rt.add_callback("Out", lambda evs: out.extend(tuple(e.data)
                                                 for e in evs))
    rt.start()
    h = rt.get_input_handler("S")
    h.send_batch(rows, timestamps=list(range(1, len(rows) + 1)))
    rt.drain()
    if shutdown:
        rt.shutdown()
    return rt, out


class TestShardRouter:
    def test_scalar_vector_hash_agree(self):
        r = ShardRouter("k", 4, n_slots=64)
        cols = [
            np.array(["a", "b", "xyzzy", "", "a", "K7"], dtype=object),
            np.arange(-5, 11, dtype=np.int64),
            np.array([0.0, -1.5, 3.25, 1e9, -0.25]),
        ]
        for col in cols:
            vec = r.slots_of_column(col)
            scal = [r.slot_of(v) for v in col.tolist()]
            assert vec.tolist() == scal, col.dtype

    def test_dict_triple_slots_match_materialized(self):
        r = ShardRouter("k", 4, n_slots=64)
        values = ["K1", "K2", "K3"]
        idx = np.array([0, 2, 2, 1, 0, 1], dtype=np.int64)
        triple = ("dict", values, idx)
        vec = r.slots_of_column(triple)
        mat = r.slots_of_column(
            np.array([values[i] for i in idx], dtype=object))
        assert vec.tolist() == mat.tolist()

    def test_split_columns_conserves_and_keeps_keys_local(self):
        r = ShardRouter("k", 4, n_slots=64)
        n = 500
        rng = np.random.default_rng(3)
        cols = {"k": np.array([f"K{i % 17}" for i in range(n)],
                              dtype=object),
                "v": rng.normal(size=n)}
        ts = np.arange(n, dtype=np.int64)
        parts = r.split_columns(cols, ts, n)
        assert sum(cnt for _, _, cnt in parts.values()) == n
        owner: dict = {}
        for shard, (_, sub, cnt) in parts.items():
            assert len(sub["k"]) == cnt == len(sub["v"])
            for key in sub["k"].tolist():
                assert owner.setdefault(key, shard) == shard
        assert r.total_rows == n

    def test_split_rows_preserves_per_key_order(self):
        r = ShardRouter("k", 3, n_slots=16)
        rows = [(f"K{i % 5}", i) for i in range(60)]
        parts = r.split_rows(list(range(60)), rows, 0)
        for shard, (tss, srows) in parts.items():
            assert tss == sorted(tss)
            per_key: dict = {}
            for key, v in srows:
                per_key.setdefault(key, []).append(v)
            for key, vs in per_key.items():
                assert vs == sorted(vs), (shard, key)

    def test_assignment_validation(self):
        with pytest.raises(ValueError):
            ShardRouter("k", 2, n_slots=8,
                        assignment=[7] * 8)  # shard 7 out of range
        with pytest.raises(ValueError):
            ShardRouter("k", 2, n_slots=8, assignment=[0, 1])  # wrong len

    def test_propose_assignment_balances_hot_slots(self):
        r = ShardRouter("k", 2, n_slots=8)
        # all traffic lands on slots owned by shard 0 under the default
        # modulo assignment -> proposal must spread it
        r.slot_rows[0] = 1000
        r.slot_rows[2] = 1000
        r.routed[0] = 2000
        r.total_rows = 2000
        prop = r.propose_assignment()
        assert {int(prop[0]), int(prop[2])} == {0, 1}
        # cold slots keep their shard: no gratuitous moves
        assert all(int(prop[s]) == int(r.assignment[s])
                   for s in range(8) if s not in (0, 2))


class TestParity:
    pytestmark = pytest.mark.smoke

    def test_sharded_vs_serial_bit_identical(self):
        rows = _rows(2000)
        mgr = SiddhiManager()
        plane, got = _run(mgr, SHARDED_APP, rows)
        _, want = _run(SiddhiManager(), SERIAL_APP, rows)
        assert len(got) == len(want) == len(rows)
        assert sorted(got) == sorted(want)  # multiset, exact floats
        assert _by_key(got) == _by_key(want)  # per-key ORDERED sequences

    def test_parity_under_python_ring(self, tmp_path):
        """SIDDHI_NATIVE=0 forces the pure-Python ingress ring (decided at
        import time, hence the subprocess): same parity oracle."""
        script = tmp_path / "parity_py.py"
        script.write_text(
            "import sys; sys.path.insert(0, %r)\n" % REPO
            + "from siddhi_tpu.util.platform import force_cpu_platform\n"
            "force_cpu_platform(1)\n"
            "from tests.test_shard_plane import (SHARDED_APP, SERIAL_APP,"
            " _rows, _run, _by_key)\n"
            "from siddhi_tpu import SiddhiManager\n"
            "import siddhi_tpu.native as native_mod\n"
            "assert not native_mod.available()\n"
            "rows = _rows(800)\n"
            "_, got = _run(SiddhiManager(), SHARDED_APP, rows)\n"
            "_, want = _run(SiddhiManager(), SERIAL_APP, rows)\n"
            "assert sorted(got) == sorted(want)\n"
            "assert _by_key(got) == _by_key(want)\n"
            "print('PARITY-PY OK', len(got))\n")
        env = {**os.environ, "SIDDHI_NATIVE": "0", "JAX_PLATFORMS": "cpu"}
        p = subprocess.run([sys.executable, str(script)], env=env,
                           capture_output=True, text=True, timeout=420)
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        assert "PARITY-PY OK 800" in p.stdout

    def test_conservation_identity(self):
        rows = _rows(1500, seed=9)
        mgr = SiddhiManager()
        plane, _ = _run(mgr, SHARDED_APP, rows, shutdown=False)
        rep = plane.conservation_report()
        plane.shutdown()
        assert rep["sent"] == len(rows)
        assert rep["conserved"] is True
        assert rep["sent"] == rep["delivered"] + rep["dropped"] \
            + rep["diverted"]
        per = rep["per_shard"]
        assert sum(s["delivered"] for s in per.values()) \
            == rep["delivered"]
        # every shard that was routed rows must account for them
        for s in per.values():
            assert s["routed"] == s["delivered"] + s["dropped"] \
                + s["diverted"]


class TestEligibility:
    def test_sl601_global_plan_refused_loudly(self):
        bad = """
        @app:name('BadPlane')
        @app:shards(n='4', key='k')
        define stream S (k string, v double);
        from S#window.length(100)
        select k, sum(v) as total group by k insert into Out;
        """
        with pytest.raises(SiddhiAppCreationError) as ei:
            SiddhiManager().create_siddhi_app_runtime(bad)
        assert "SL601" in str(ei.value)
        assert "shard-eligible" in str(ei.value)

    def test_stream_without_key_attribute_refused(self):
        app = """
        @app:name('NoKeyPlane')
        @app:shards(n='2', key='k')
        define stream S (k string, v double);
        define stream T (x long);
        @info(name='agg')
        from S select k, sum(v) as total group by k insert into Out;
        @info(name='echo') from T select x insert into TOut;
        """
        with pytest.raises(SiddhiAppCreationError) as ei:
            SiddhiManager().create_siddhi_app_runtime(app)
        assert "lacks the partition key" in str(ei.value)

    def test_env_override_and_shards_1(self):
        os.environ["SIDDHI_SHARDS"] = "2"
        try:
            plane = SiddhiManager().create_siddhi_app_runtime(SHARDED_APP)
        finally:
            os.environ.pop("SIDDHI_SHARDS", None)
        assert plane.n_shards == 2
        plane.shutdown()


class TestLifecycle:
    def test_rebalance_force_reroutes_and_preserves_state(self, tmp_path):
        rows = _rows(1200, seed=11)
        more = _rows(800, seed=12)
        mgr = SiddhiManager()
        plane, got = _run(mgr, SHARDED_APP, rows,
                          wal_dir=str(tmp_path), shutdown=False)
        res = plane.rebalance(force=True)
        assert res["rebalanced"] is True
        assert plane.epoch == 1
        assert res["replayed"] == len(rows)
        meta = json.load(open(tmp_path / "PlaneApp.shardmeta.json"))
        assert meta["epoch"] == 1 and meta["key"] == "k"
        # state continuity: running per-key aggregates keep counting
        h = plane.get_input_handler("S")
        h.send_batch(more, timestamps=list(
            range(len(rows) + 1, len(rows) + len(more) + 1)))
        plane.drain()
        plane.shutdown()
        _, want = _run(SiddhiManager(), SERIAL_APP, rows + more)
        assert sorted(got) == sorted(want)
        assert _by_key(got) == _by_key(want)

    def test_rebalance_noop_below_threshold(self, tmp_path):
        mgr = SiddhiManager()
        plane, _ = _run(mgr, SHARDED_APP, _rows(400),
                        wal_dir=str(tmp_path), shutdown=False)
        res = plane.rebalance(threshold=1e9)
        plane.shutdown()
        assert res["rebalanced"] is False
        assert "below" in res["reason"]
        assert plane.epoch == 0

    def test_rebalance_refused_without_wal(self):
        mgr = SiddhiManager()
        plane, _ = _run(mgr, SHARDED_APP, _rows(200), shutdown=False)
        with pytest.raises(SiddhiAppCreationError, match="needs a WAL"):
            plane.rebalance(force=True)
        plane.shutdown()

    def test_rebalance_refused_after_persist(self, tmp_path):
        mgr = SiddhiManager()
        mgr.set_persistence_store(
            FileSystemPersistenceStore(str(tmp_path / "snap")))
        plane, _ = _run(mgr, SHARDED_APP, _rows(200),
                        wal_dir=str(tmp_path / "wal"), shutdown=False)
        plane.persist()
        with pytest.raises(SiddhiAppCreationError, match="persist"):
            plane.rebalance(force=True)
        plane.shutdown()

    def test_move_shard_keeps_state_and_journal(self, tmp_path):
        rows = _rows(600, seed=21)
        more = _rows(400, seed=22)
        mgr = SiddhiManager()
        plane, got = _run(mgr, SHARDED_APP, rows,
                          wal_dir=str(tmp_path), shutdown=False)
        res = plane.move_shard(1)
        assert res == {"moved": 1, "epoch": 0}
        assert plane.shards[1].wal is not None  # journal handed over
        h = plane.get_input_handler("S")
        h.send_batch(more, timestamps=list(
            range(len(rows) + 1, len(rows) + len(more) + 1)))
        plane.drain()
        plane.shutdown()
        _, want = _run(SiddhiManager(), SERIAL_APP, rows + more)
        assert sorted(got) == sorted(want)
        assert _by_key(got) == _by_key(want)

    def test_kill_and_recover_shard(self, tmp_path):
        rows = _rows(600, seed=31)
        mgr = SiddhiManager()
        plane, got = _run(mgr, SHARDED_APP, rows,
                          wal_dir=str(tmp_path), shutdown=False)
        victim = 2
        plane.kill_shard(victim)
        assert plane.health()["state"] == "stopped"
        with pytest.raises(SiddhiAppCreationError, match="alive"):
            plane.recover_shard(0)
        rec = plane.recover_shard(victim)
        assert rec["wal_replayed"] > 0
        assert plane.health()["state"] in ("running", "recovering")
        plane.drain()
        plane.shutdown()
        # recovery REPLAYS the shard's journal: its rows re-emit, so the
        # multiset grows — but last-per-key (the running aggregate's final
        # value) must match the serial oracle exactly
        _, want = _run(SiddhiManager(), SERIAL_APP, rows)
        last = {r[0]: r for r in got}
        last_want = {r[0]: r for r in want}
        assert last == last_want

    def test_plane_recover_after_restart(self, tmp_path):
        rows = _rows(500, seed=41)
        mgr = SiddhiManager()
        plane, _ = _run(mgr, SHARDED_APP, rows, wal_dir=str(tmp_path))
        # fresh manager = simulated process restart on the same WAL layout
        mgr2 = SiddhiManager()
        plane2 = mgr2.create_siddhi_app_runtime(
            SHARDED_APP, wal_dir=str(tmp_path))
        out: list = []
        plane2.add_callback("Out",
                            lambda evs: out.extend(tuple(e.data)
                                                   for e in evs))
        plane2.start()
        rec = plane2.recover()
        assert rec["wal_replayed"] == len(rows)
        plane2.drain()
        plane2.shutdown()
        _, want = _run(SiddhiManager(), SERIAL_APP, rows)
        assert {r[0]: r for r in out} == {r[0]: r for r in want}


class TestIntegration:
    def test_statistics_and_skew_sections(self):
        mgr = SiddhiManager()
        plane, _ = _run(mgr, SHARDED_APP, _rows(300), shutdown=False)
        rep = plane.statistics_report()
        plane.shutdown()
        sp = rep["shard_plane"]
        assert sp["n_shards"] == 4 and sp["key"] == "k"
        assert sp["epoch"] == 0 and sp["rebalances"] == 0
        assert rep["conservation"]["conserved"] is True
        assert set(rep["shards"]) == {"s0", "s1", "s2", "s3"}
        assert rep["cost"]["predicted_state_bytes"] > 0
        skew = plane.skew_report()
        assert skew["total_rows"] == 300
        assert skew["imbalance"] >= 1.0

    def test_cost_report_is_fleet_priced(self):
        from siddhi_tpu.analysis.cost import compute_cost
        mgr = SiddhiManager()
        plane = mgr.create_siddhi_app_runtime(SHARDED_APP)
        ctx = plane.shards[0].ctx
        serial_rep = compute_cost(SERIAL_APP, batch_size=ctx.batch_size,
                                  group_capacity=ctx.group_capacity)
        try:
            assert plane.cost_report["predicted_state_bytes"] \
                == 4 * serial_rep.state_bytes
            assert any("shard fleet" in n
                       for n in plane.cost_report["notes"])
        finally:
            plane.shutdown()

    def test_manager_error_store_fans_out_to_shards(self):
        from siddhi_tpu.state.error_store import InMemoryErrorStore
        mgr = SiddhiManager()
        plane = mgr.create_siddhi_app_runtime(SHARDED_APP)
        store = InMemoryErrorStore()
        mgr.set_error_store(store)
        try:
            for srt in plane.shards:
                assert srt.ctx.error_store is store
        finally:
            plane.shutdown()

    def test_upgrade_refused_on_plane(self):
        mgr = SiddhiManager()
        plane = mgr.create_siddhi_app_runtime(SHARDED_APP)
        try:
            with pytest.raises(SiddhiAppCreationError,
                               match="sharded app"):
                mgr.upgrade(SHARDED_APP)
        finally:
            plane.shutdown()

    def test_prometheus_plane_families(self):
        from siddhi_tpu.telemetry.prometheus import render_manager
        mgr = SiddhiManager()
        plane, _ = _run(mgr, SHARDED_APP, _rows(200), shutdown=False)
        text = render_manager(mgr)
        plane.shutdown()
        assert 'siddhi_shard_count{app="PlaneApp"} 4' in text
        assert 'siddhi_shard_epoch{app="PlaneApp"} 0' in text
        assert 'siddhi_shard_routed_rows_total{app="PlaneApp",shard="s0"}' \
            in text
        assert "siddhi_shard_imbalance_ratio" in text
        # per-shard runtime families exist under the replica names
        assert 'app="PlaneApp@s0"' in text
