"""Anonymous streams in FROM clauses (reference:
api/execution/query/input/stream/AnonymousInputStream.java; grammar rule
anonymous stream in SiddhiQL.g4): `from (from S select ...) ...` desugars at
parse time to a synthetic stream fed by the inner query."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.compiler import parse
from siddhi_tpu.errors import SiddhiAppCreationError


def run(app, sends, out="Out", batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=batch_size)
    rows = []
    rt.add_callback(out, lambda evs: rows.extend(tuple(e) for e in evs))
    rt.start()
    for stream, row in sends:
        rt.get_input_handler(stream).send(row)
    rt.flush()
    rt.shutdown()
    return rows


class TestAnonymousStreams:
    def test_desugars_to_inner_query(self):
        app = """
        define stream S (sym string, price double);
        from (from S[price > 10.0] select sym, price) #window.lengthBatch(4)
        select sym, sum(price) as total
        group by sym
        insert into Out;
        """
        sapp = parse(app)
        assert len(sapp.queries) == 2
        inner, outer = sapp.queries
        assert inner.output_stream.target_id == outer.input_stream.stream_id
        assert outer.input_stream.handlers.window is not None

    def test_filter_project_feeds_window(self):
        app = """
        define stream S (sym string, price double);
        from (from S[price > 10.0] select sym, price) #window.lengthBatch(4)
        select sym, sum(price) as total
        group by sym
        insert into Out;
        """
        sends = [("S", ("A", p)) for p in (5.0, 20.0, 30.0, 40.0, 50.0, 7.0)]
        rows = run(app, sends)
        # 4 events pass the inner filter; per-event emission inside the
        # lengthBatch flush ends on the full batch sum
        assert rows[-1] == ("A", 140.0)

    def test_inner_aggregation(self):
        app = """
        define stream S (sym string, price double);
        from (from S#window.lengthBatch(2) select sym, sum(price) as p2)
        select sym, p2
        insert into Out;
        """
        sends = [("S", ("A", 1.0)), ("S", ("A", 2.0)),
                 ("S", ("B", 10.0)), ("S", ("B", 20.0))]
        rows = run(app, sends)
        assert ("A", 3.0) in rows and ("B", 30.0) in rows

    def test_join_side_anonymous(self):
        app = """
        define stream L (k int, v double);
        define stream R (k int, w double);
        from L#window.length(4) as a
        join (from R[w > 1.0] select k, w) #window.length(4) as b
        on a.k == b.k
        select a.k as k, b.w as w
        insert into Out;
        """
        sends = [("R", (1, 5.0)), ("R", (2, 0.5)),
                 ("L", (1, 9.0)), ("L", (2, 9.0))]
        rows = run(app, sends)
        assert rows == [(1, 5.0)]

    def test_rejected_in_partitions(self):
        app = """
        define stream S (sym string, price double);
        partition with (sym of S)
        begin
          from (from S select sym, price) select sym insert into Out;
        end;
        """
        with pytest.raises(SiddhiAppCreationError, match="partitions"):
            SiddhiManager().create_siddhi_app_runtime(app)

    def test_rejected_in_patterns(self):
        app = """
        define stream S (sym string, price double);
        define stream T (sym string, price double);
        from every e1=(from S select sym, price) -> e2=T
        select e1.sym as s insert into Out;
        """
        with pytest.raises(Exception):  # parse or creation error
            SiddhiManager().create_siddhi_app_runtime(app)
