"""SiddhiQL parser tests (model: reference siddhi-query-compiler test suite —
modules/siddhi-query-compiler/src/test/java/io/siddhi/query/test/, grammar →
AST equality)."""

import pytest

from siddhi_tpu.compiler import parse, parse_query, parse_stream_definition, update_variables
from siddhi_tpu.errors import SiddhiParserError
from siddhi_tpu.query_api import (
    AbsentStreamStateElement,
    And,
    AttributeFunction,
    AttributeType,
    Compare,
    CompareOp,
    Constant,
    CountStateElement,
    Duration,
    EveryStateElement,
    JoinInputStream,
    JoinType,
    MathExpression,
    MathOp,
    NextStateElement,
    OutputAction,
    OutputEventType,
    OutputRateType,
    SingleInputStream,
    StateInputStream,
    StateType,
    StreamStateElement,
    Variable,
)



pytestmark = pytest.mark.smoke

class TestDefinitions:
    def test_stream_definition(self):
        d = parse_stream_definition(
            "define stream StockStream (symbol string, price float, volume long);")
        assert d.id == "StockStream"
        assert d.attribute_names == ("symbol", "price", "volume")
        assert d.attribute_type("price") == AttributeType.FLOAT

    def test_all_attribute_types(self):
        d = parse_stream_definition(
            "define stream S (a string, b int, c long, d float, e double, f bool, g object);")
        assert [a.type for a in d.attributes] == [
            AttributeType.STRING, AttributeType.INT, AttributeType.LONG,
            AttributeType.FLOAT, AttributeType.DOUBLE, AttributeType.BOOL,
            AttributeType.OBJECT]

    def test_table_with_primary_key_and_index(self):
        app = parse("""
            @PrimaryKey('sym')
            @Index('vol')
            define table T (sym string, price double, vol long);
        """)
        t = app.table_definitions["T"]
        assert t.primary_keys == ("sym",)
        assert t.indexes == ("vol",)

    def test_window_definition(self):
        app = parse("define window W (x int) length(10) output all events;")
        w = app.window_definitions["W"]
        assert w.window.name == "length"
        assert w.output_event_type == "all"

    def test_trigger_definitions(self):
        app = parse("""
            define trigger T1 at every 5 sec;
            define trigger T2 at 'start';
            define trigger T3 at '*/5 * * * * ?';
        """)
        assert app.trigger_definitions["T1"].at_every_ms == 5000
        assert app.trigger_definitions["T2"].at_start
        assert app.trigger_definitions["T3"].at_cron == "*/5 * * * * ?"

    def test_aggregation_definition(self):
        app = parse("""
            define stream S (sym string, price double, ts long);
            define aggregation Agg
            from S select sym, sum(price) as total, avg(price) as mean
            group by sym
            aggregate by ts every sec ... day;
        """)
        a = app.aggregation_definitions["Agg"]
        assert a.input_stream_id == "S"
        assert a.aggregate_attribute == "ts"
        assert a.durations == (Duration.SECONDS, Duration.MINUTES,
                               Duration.HOURS, Duration.DAYS)

    def test_function_definition(self):
        app = parse("""
            define function concatFn[python] return string { return x + y };
        """)
        f = app.function_definitions["concatFn"]
        assert f.language == "python"
        assert f.return_type == AttributeType.STRING
        assert "return x + y" in f.body

    def test_app_annotation(self):
        app = parse("@app:name('MyApp')\ndefine stream S (x int);")
        assert app.name == "MyApp"

    def test_duplicate_definition_rejected(self):
        with pytest.raises(Exception):
            parse("define stream S (x int); define stream S (y int);")


class TestExpressions:
    def _filter(self, expr_text):
        q = parse_query(
            f"define stream S (a int, b long, p double, s string, f bool);\n"
            f"from S[{expr_text}] select a insert into Out;")
        return q.input_stream.handlers.filters[0]

    def test_precedence_mul_over_add(self):
        e = self._filter("a + b * 2 > 10")
        assert isinstance(e, Compare)
        assert isinstance(e.left, MathExpression)
        assert e.left.op == MathOp.ADD
        assert e.left.right.op == MathOp.MULTIPLY

    def test_and_or_not(self):
        e = self._filter("not f and (a > 1 or b < 2)")
        assert isinstance(e, And)

    def test_string_compare(self):
        e = self._filter("s == 'IBM'")
        assert e.right == Constant("IBM", "string")

    def test_time_constant(self):
        q = parse_query(
            "define stream S (x int);"
            "from every e1=S -> e2=S within 1 min 30 sec select e1.x insert into O;")
        assert q.input_stream.within_ms == 90_000

    def test_typed_literals(self):
        e = self._filter("p > 5.5f")
        assert e.right.type_name == "float"
        e = self._filter("b > 100L")
        assert e.right.type_name == "long"
        e = self._filter("a > -3")
        assert e.right.value == -3

    def test_function_call(self):
        e = self._filter("math:abs(a - b) > 5")
        assert isinstance(e.left, AttributeFunction)
        assert e.left.namespace == "math"

    def test_is_null(self):
        from siddhi_tpu.query_api import IsNull
        e = self._filter("s is null")
        assert isinstance(e, IsNull)


class TestQueries:
    def test_filter_window_groupby(self):
        q = parse_query("""
            define stream S (sym string, price double, vol long);
            @info(name='q1')
            from S[price > 20.0]#window.lengthBatch(10000)
            select sym, sum(price) as total
            group by sym having total > 5.0
            order by total desc limit 10 offset 2
            insert all events into Out;
        """)
        assert q.name == "q1"
        h = q.input_stream.handlers
        assert len(h.filters) == 1
        assert h.window.name == "lengthBatch"
        assert h.window.parameters[0].value == 10000
        assert q.selector.group_by[0].attribute == "sym"
        assert q.selector.having is not None
        assert q.selector.limit == 10 and q.selector.offset == 2
        assert q.selector.order_by[0].variable.attribute == "total"
        assert q.output_stream.event_type == OutputEventType.ALL

    def test_select_star(self):
        q = parse_query("define stream S (x int); from S select * insert into O;")
        assert q.selector.is_select_all

    def test_output_rate(self):
        q = parse_query(
            "define stream S (x int);"
            "from S select x output last every 3 events insert into O;")
        assert q.output_rate.type == OutputRateType.LAST
        assert q.output_rate.event_count == 3
        q = parse_query(
            "define stream S (x int);"
            "from S select x output snapshot every 5 sec insert into O;")
        assert q.output_rate.type == OutputRateType.SNAPSHOT
        assert q.output_rate.time_ms == 5000

    def test_join(self):
        q = parse_query("""
            define stream A (x int); define stream B (x int, v double);
            from A#window.length(100) as l
            left outer join B#window.length(200) as r
            on l.x == r.x within 2 sec
            select l.x as x, r.v as v insert into J;
        """)
        j = q.input_stream
        assert isinstance(j, JoinInputStream)
        assert j.join_type == JoinType.LEFT_OUTER
        assert j.left.alias == "l" and j.right.alias == "r"
        assert j.within_ms == 2000

    def test_pattern(self):
        q = parse_query("""
            define stream A (x int); define stream B (y int);
            from every e1=A[x > 5] -> e2=B[y > e1.x] within 5 sec
            select e1.x as ax, e2.y as doubled insert into P;
        """)
        s = q.input_stream
        assert isinstance(s, StateInputStream)
        assert s.state_type == StateType.PATTERN
        assert s.within_ms == 5000
        assert isinstance(s.state, NextStateElement)
        assert isinstance(s.state.state, EveryStateElement)

    def test_pattern_count_and_absent(self):
        q = parse_query("""
            define stream A (x int); define stream B (y int);
            from e1=A<2:5> -> not B[y > 1] for 3 sec
            select e1[0].x as first insert into P;
        """)
        s = q.input_stream.state
        assert isinstance(s.state, CountStateElement)
        assert (s.state.min_count, s.state.max_count) == (2, 5)
        assert isinstance(s.next, AbsentStreamStateElement)
        assert s.next.waiting_time_ms == 3000
        # indexed variable
        v = q.selector.attributes[0].expression
        assert v.stream_index == 0

    def test_logical_pattern(self):
        q = parse_query("""
            define stream A (x int); define stream B (y int); define stream C (z int);
            from every (e1=A and e2=B) -> e3=C
            select e1.x, e2.y, e3.z insert into O;
        """)
        from siddhi_tpu.query_api import LogicalStateElement
        st = q.input_stream.state
        assert isinstance(st.state, EveryStateElement)
        assert isinstance(st.state.state, LogicalStateElement)
        assert st.state.state.logical_type == "and"

    def test_sequence(self):
        q = parse_query("""
            define stream A (x int);
            from every e1=A, e2=A[x > e1.x]
            select e1.x as a, e2.x as b insert into Sq;
        """)
        s = q.input_stream
        assert s.state_type == StateType.SEQUENCE

    def test_table_crud_queries(self):
        app = parse("""
            define stream S (sym string, price double);
            define table T (sym string, price double);
            from S select sym, price insert into T;
            from S delete T on T.sym == sym;
            from S update T set T.price = price on T.sym == sym;
            from S update or insert into T set T.price = price on T.sym == sym;
        """)
        actions = [q.output_stream.action for q in app.queries]
        assert actions == [OutputAction.INSERT, OutputAction.DELETE,
                           OutputAction.UPDATE, OutputAction.UPDATE_OR_INSERT]

    def test_partition(self):
        app = parse("""
            define stream S (sym string, price double);
            partition with (sym of S)
            begin
              from S select sym, sum(price) as t insert into #inner;
              from #inner select sym, t insert into Out;
            end;
        """)
        p = app.partitions[0]
        assert len(p.queries) == 2
        assert p.queries[1].input_stream.is_inner

    def test_range_partition(self):
        app = parse("""
            define stream S (price double);
            partition with (price < 100.0 as 'cheap' or price >= 100.0 as 'pricey' of S)
            begin
              from S select price insert into Out;
            end;
        """)
        from siddhi_tpu.query_api import RangePartitionType
        pt = app.partitions[0].partition_types[0]
        assert isinstance(pt, RangePartitionType)
        assert [r.partition_key for r in pt.ranges] == ["cheap", "pricey"]

    def test_fault_stream_output(self):
        q = parse_query(
            "define stream S (x int); from S select x insert into !S;")
        assert q.output_stream.is_fault


class TestMisc:
    def test_update_variables(self):
        out = update_variables("define stream ${NAME} (x int);", {"NAME": "S"})
        assert "stream S" in out

    def test_update_variables_missing(self):
        with pytest.raises(SiddhiParserError):
            update_variables("${MISSING}", {})

    def test_syntax_error_has_location(self):
        with pytest.raises(SiddhiParserError):
            parse("define stream S x int);")

    def test_comments_ignored(self):
        app = parse("""
            -- a line comment
            /* a block
               comment */
            define stream S (x int);
        """)
        assert "S" in app.stream_definitions

    def test_source_sink_annotations(self):
        app = parse("""
            @source(type='inMemory', topic='t1', @map(type='passThrough'))
            define stream In (x int);
            @sink(type='log', prefix='OUT')
            define stream Out (x int);
        """)
        src = app.stream_definitions["In"].annotation("source")
        assert src.element("type") == "inMemory"
        assert src.nested_annotation("map").element("type") == "passThrough"
