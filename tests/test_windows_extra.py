"""cron / hopping / frequent / lossyFrequent window tests (reference:
query/window/CronWindowTestCase, HoppingWindowTestCase,
FrequentWindowTestCase, LossyFrequentWindowTestCase)."""

import pytest

from siddhi_tpu import SiddhiManager

S = "define stream S (symbol string, price float, volume long);\n"


def build(app, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    rt.start()
    return rt


def q_callback(rt, name):
    got = []
    rt.add_query_callback(
        name, lambda ts, i, r: got.append((i or [], r or [])))
    return got


class TestCronWindow:
    def test_cron_flush(self):
        rt = build(
            S + "@info(name='q') from S#window.cron('*/2 * * * * ?') "
            "select symbol, sum(price) as total insert into Out;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        h.send(("A", 10.0, 1), timestamp=100)
        h.send(("B", 20.0, 1), timestamp=300)
        rt.flush(now=500)
        assert got == []  # nothing until the cron fires
        rt.heartbeat(2_100)  # cron boundary at 2000 crossed
        ins = [e for i, _ in got for e in i]
        assert [e.data[0] for e in ins] == ["A", "B"]
        assert ins[-1].data[1] == pytest.approx(30.0)

    def test_cron_expired_on_next_fire(self):
        # `insert all events` opts into EXPIRED emission (reference:
        # outputExpectsExpiredEvents — CURRENT-only inserts skip expired lanes)
        rt = build(
            S + "@info(name='q') from S#window.cron('*/2 * * * * ?') "
            "select symbol insert all events into Out;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        h.send(("A", 1.0, 1), timestamp=100)
        rt.heartbeat(2_100)
        h.send(("B", 2.0, 1), timestamp=2_500)
        rt.heartbeat(4_100)
        removes = [e for _, r in got for e in r]
        assert [e.data[0] for e in removes] == ["A"]


class TestHoppingWindow:
    def test_hop_emissions_overlap(self):
        rt = build(
            S + "@info(name='q') from S#window.hopping(2 sec, 1 sec) "
            "select symbol, count() as n insert into Out;")
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        h.send(("A", 1.0, 1), timestamp=200)
        h.send(("B", 1.0, 1), timestamp=700)
        rt.heartbeat(1_050)  # hop at 1000: both in window
        h.send(("C", 1.0, 1), timestamp=1_500)
        rt.heartbeat(2_050)  # hop at 2000: window (0,2000] → A,B,C
        rt.heartbeat(3_050)  # hop at 3000: window (1000,3000] → C only
        counts = [i[-1].data[1] for i, _ in got if i]
        assert counts == [2, 3, 1]


class TestFrequentWindow:
    def test_keeps_top_keys(self):
        rt = build(
            S + "@info(name='q') from S#window.frequent(2, symbol) "
            "select symbol, price insert into Out;", batch_size=4)
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        # 2 slots: A and B occupy them; C decrements both instead of entering
        for row in [("A", 1.0, 1), ("B", 2.0, 1), ("A", 3.0, 1)]:
            h.send(row)
        rt.flush()
        for row in [("C", 9.0, 1)]:
            h.send(row)
        rt.flush()
        ins = [e for i, _ in got for e in i]
        assert [e.data[0] for e in ins] == ["A", "B", "A"]  # C swallowed

    def test_eviction_emits_expired(self):
        rt = build(
            S + "@info(name='q') from S#window.frequent(1, symbol) "
            "select symbol insert all events into Out;", batch_size=4)
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        h.send(("A", 1.0, 1))
        rt.flush()
        # B decrements A to 0 → A evicted (expired); next B takes the slot
        h.send(("B", 1.0, 1))
        rt.flush()
        removes = [e for _, r in got for e in r]
        assert [e.data[0] for e in removes] == ["A"]


class TestFrequentSameBatchAdmitEvict:
    def test_no_phantom_expired(self):
        # A admitted and decremented away within ONE batch: nothing was ever
        # remembered for that slot, so no EXPIRED event may emit
        rt = build(
            S + "@info(name='q') from S#window.frequent(1, symbol) "
            "select symbol insert into Out;", batch_size=4)
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        for row in [("A", 1.0, 1), ("B", 1.0, 1), ("B", 2.0, 1)]:
            h.send(row)
        rt.flush()
        removes = [e for _, r in got for e in r]
        assert removes == []


class TestLossyFrequentWindow:
    def test_support_threshold(self):
        rt = build(
            S + "@info(name='q') from S#window.lossyFrequent(0.5, 0.1, symbol) "
            "select symbol insert into Out;", batch_size=4)
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        rows = [("A", 1.0, 1)] * 6 + [("B", 1.0, 1)]
        for row in rows:
            h.send(row)
        rt.flush()
        ins = [e for i, _ in got for e in i]
        # A is above 50% support throughout; the lone B (1/7 < 0.4) is not
        assert set(e.data[0] for e in ins) == {"A"}


class TestLengthBatchDoubleFlushExpired:
    def test_first_batch_double_flush_expired_values(self):
        # Two flushes complete while f_done == 0: flush 0's events [10, 11]
        # sit in the ring and must re-emit as EXPIRED with their true values
        # when flush 1 completes (regression: the expired-lane gather used a
        # clamped negative base and read the wrong ring slots)
        rt = build(
            S + "@info(name='q') from S#window.lengthBatch(4) "
            "select symbol, volume insert all events into Out;",
            batch_size=8)
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        h.send(("A", 1.0, 10)); h.send(("A", 1.0, 11))
        rt.flush()  # partial bucket: 2 of 4
        for v in (12, 13, 14, 15, 16, 17):
            h.send(("A", 1.0, v))
        rt.flush()  # completes flush 0 (10..13) and flush 1 (14..17)
        removes = [e.data[1] for _, r in got for e in r]
        assert removes == [10, 11, 12, 13]
        ins = [e.data[1] for i, _ in got for e in i]
        assert ins == [10, 11, 12, 13, 14, 15, 16, 17]


class TestSmallBatchSlidingWindow:
    """Regression: the packed candidate fetch misaligned batch rows whenever
    E (expiry lanes, min 1024 for time windows) exceeded the batch size and
    the window held fewer than E - B events — expired lanes read zero
    padding, emitting garbage payloads with ts = 0 + windowTime."""

    def test_time_window_batch_smaller_than_expiry_lanes(self):
        rt = build(
            S + "@info(name='q') from S#window.time(5 sec) "
            "select symbol, price insert all events into Out;", batch_size=8)
        got = q_callback(rt, "q")
        h = rt.get_input_handler("S")
        for i in range(8):
            h.send((f"s{i}", float(i), i), timestamp=1_000 * i)
        rt.flush()
        # events 0..2 are > 5 s older than ts 7000 — they expire with their
        # real payloads; 3..7 stay current
        cur = [e.data[0] for pair in got for e in pair[0]]
        exp = [(e.data[0], e.data[1]) for pair in got for e in pair[1]]
        assert cur == [f"s{i}" for i in range(8)]
        assert exp == [(f"s{i}", float(i)) for i in range(3)]


class TestKeyedSessionWindow:
    """session(gap, key) — reference SessionWindowProcessor with a session
    key keeps independent per-key sessions."""

    def _build(self):
        rt = build(
            S + "@info(name='q') from S#window.session(2 sec, symbol) "
            "select symbol, price insert all events into Out;", batch_size=4)
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.append(
            ([tuple(e.data[:2]) for e in i or []],
             [tuple(e.data[:2]) for e in r or []])))
        return rt, got

    def test_per_key_sessions_close_independently(self):
        rt, got = self._build()
        h = rt.get_input_handler("S")
        h.send(("a", 1.0, 0), timestamp=1_000)
        h.send(("b", 2.0, 0), timestamp=1_500)
        h.send(("a", 3.0, 0), timestamp=2_000)
        rt.flush()
        # 'a' goes quiet; 'b' keeps its session alive
        h.send(("b", 4.0, 0), timestamp=3_400)
        rt.flush()
        h.send(("b", 5.0, 0), timestamp=4_600)
        rt.flush()
        # watermark far past a's last event (2000): a's session expires;
        # b's latest (4600) is still within gap at 4_700? advance past a only
        rt.heartbeat(4_700)
        expired = [e for pair in got for e in pair[1]]
        assert sorted(expired) == [("a", 1.0), ("a", 3.0)]
        # now b goes quiet too
        rt.heartbeat(7_000)
        expired = [e for pair in got for e in pair[1]]
        assert sorted(expired) == [
            ("a", 1.0), ("a", 3.0), ("b", 2.0), ("b", 4.0), ("b", 5.0)]

    def test_in_batch_gap_closes_only_that_key(self):
        rt, got = self._build()
        h = rt.get_input_handler("S")
        h.send(("a", 1.0, 0), timestamp=1_000)
        h.send(("b", 2.0, 0), timestamp=1_100)
        # a's next event gaps (>2s since 1000); the watermark at 5000 also
        # closes b's idle session (last event 1100 + gap < 5000)
        h.send(("a", 9.0, 0), timestamp=5_000)
        rt.flush()
        expired = [e for pair in got for e in pair[1]]
        assert sorted(expired) == [("a", 1.0), ("b", 2.0)]
        currents = [e for pair in got for e in pair[0]]
        assert ("a", 9.0) in currents and ("b", 2.0) in currents
