"""Absent-pattern matrix (reference: query/pattern/absent/ — 4 test classes,
AbsentPatternTestCase / EveryAbsentPatternTestCase /
AbsentWithEveryPatternTestCase / LogicalAbsentPatternTestCase).

Shapes mirrored (reference file:line cited per test): leading/middle/
trailing `not X for t`, correlated absent filters over earlier captures,
logical `not A and B` without a timer, and every-variants. VERDICT r3
item 8 (absent-pattern tranche)."""

import pytest

from siddhi_tpu import SiddhiManager

THREE = ("define stream S1 (symbol string, price float);\n"
         "define stream S2 (symbol string, price float);\n"
         "define stream S3 (symbol string, price float);\n")


def make(app, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    got = []
    rt.add_callback("OutStream", lambda evs: got.extend(
        tuple(e.data) for e in evs))
    rt.start()
    return rt, got


class TestTrailingAbsent:
    """`e1 -> not S2 for 1 sec` (AbsentPatternTestCase.java:49-190)."""

    APP = (THREE + "from e1=S1[price>20] -> not S2[price>e1.price] for 1 sec "
           "select e1.symbol as s insert into OutStream;")

    def test_fires_when_nothing_bigger_arrives(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("IBM", 25.0), timestamp=1_000)
        rt.flush()
        # an S2 BELOW the correlated bound does not kill the absence
        rt.get_input_handler("S2").send(("LO", 10.0), timestamp=1_400)
        rt.flush()
        rt.heartbeat(now=2_500)
        assert got == [("IBM",)]

    def test_killed_by_correlated_match(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("IBM", 25.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("HI", 30.0), timestamp=1_400)
        rt.flush()
        rt.heartbeat(now=2_500)
        assert got == []

    def test_filter_below_threshold_never_arms(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("IBM", 15.0), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=2_500)
        assert got == []

    def test_after_chain(self):
        # e1 -> e2 -> not S3 for 1 sec (AbsentPatternTestCase.java:339-460)
        app = (THREE +
               "from e1=S1[price>10] -> e2=S2[price>20] -> "
               "not S3[price>30] for 1 sec "
               "select e1.symbol as a, e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 25.0), timestamp=1_500)
        rt.flush()
        rt.heartbeat(now=3_000)
        assert got == [("A", "B")]
        # with a killing S3 inside the window instead
        rt2, got2 = make(app)
        rt2.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt2.flush()
        rt2.get_input_handler("S2").send(("B", 25.0), timestamp=1_500)
        rt2.flush()
        rt2.get_input_handler("S3").send(("C", 35.0), timestamp=2_000)
        rt2.flush()
        rt2.heartbeat(now=3_000)
        assert got2 == []


class TestLeadingAbsent:
    """`not S1 for 1 sec -> e2` (AbsentPatternTestCase.java:193-335)."""

    APP = (THREE + "from not S1[price>20] for 1 sec -> e2=S2[price>30] "
           "select e2.symbol as s insert into OutStream;")

    def test_fires_after_quiet_period(self):
        rt, got = make(self.APP)
        # playback arms the leading absent LAZILY at the first observed
        # instant (epoch replays must not measure from virtual 0): anchor
        # the virtual clock, then stay quiet past the waiting time
        rt.heartbeat(now=100)
        rt.heartbeat(now=1_500)  # quiet 1 sec from the anchor
        rt.get_input_handler("S2").send(("OK", 35.0), timestamp=1_600)
        rt.flush()
        assert got == [("OK",)]

    def test_playback_epoch_replay_does_not_fire_spuriously(self):
        # first observed instant is an epoch timestamp with a killing S1 in
        # the same batch: the arming anchors THERE, so the kill applies and
        # nothing fires (regression: arming at virtual 0 made the deadline
        # trivially past and the kill window empty)
        epoch = 1_700_000_000_000
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("X", 25.0), timestamp=epoch + 100)
        rt.flush()
        rt.get_input_handler("S2").send(("OK", 35.0),
                                        timestamp=epoch + 1_600)
        rt.flush()
        rt.heartbeat(now=epoch + 3_000)
        assert got == []

    def test_blocked_by_early_event(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("X", 25.0), timestamp=500)
        rt.flush()
        rt.get_input_handler("S2").send(("OK", 35.0), timestamp=1_600)
        rt.flush()
        rt.heartbeat(now=3_000)
        assert got == []

    def test_e2_before_quiet_period_elapses_does_not_match(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S2").send(("EARLY", 35.0), timestamp=400)
        rt.flush()
        rt.heartbeat(now=3_000)
        assert got == []


class TestMiddleAbsent:
    """`e1 -> not S2 for 1 sec -> e3` (AbsentPatternTestCase.java:462-580)."""

    APP = (THREE +
           "from e1=S1[price>10] -> not S2[price>20] for 1 sec -> "
           "e3=S3[price>30] "
           "select e1.symbol as a, e3.symbol as c insert into OutStream;")

    def test_fires_when_gap_is_quiet(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=2_200)  # quiet 1.2 sec
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=2_300)
        rt.flush()
        assert got == [("A", "C")]

    def test_blocked_by_middle_event(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 25.0), timestamp=1_500)
        rt.flush()
        rt.heartbeat(now=2_200)
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=2_300)
        rt.flush()
        assert got == []

    def test_e3_too_early_does_not_match(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=1_500)
        rt.flush()
        rt.heartbeat(now=3_000)
        assert got == []


class TestLogicalAbsent:
    """`e1 -> not S2 and e3` — absence valid until the AND partner arrives
    (LogicalAbsentPatternTestCase.java:56-130)."""

    APP = (THREE +
           "from e1=S1[price>10] -> not S2[price>20] and e3=S3[price>30] "
           "select e1.symbol as a, e3.symbol as c insert into OutStream;")

    def test_fires_with_partner_when_quiet(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=1_800)
        rt.flush()
        assert got == [("A", "C")]

    def test_blocked_by_absent_stream_event(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 25.0), timestamp=1_400)
        rt.flush()
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=1_800)
        rt.flush()
        assert got == []


class TestEveryAbsent:
    """every + absent (EveryAbsentPatternTestCase /
    AbsentWithEveryPatternTestCase): repeated arming, one firing per arm."""

    def test_every_trailing_absent_repeats(self):
        app = (THREE + "from every e1=S1[price>20] -> not S2 for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 25.0), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=2_500)
        rt.get_input_handler("S1").send(("B", 26.0), timestamp=3_000)
        rt.flush()
        rt.heartbeat(now=4_500)
        assert got == [("A",), ("B",)]

    def test_every_arm_killed_independently(self):
        app = (THREE + "from every e1=S1[price>20] -> not S2 for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 25.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("K", 1.0), timestamp=1_500)  # kills A
        rt.flush()
        rt.get_input_handler("S1").send(("B", 26.0), timestamp=3_000)
        rt.flush()
        rt.heartbeat(now=4_500)
        assert got == [("B",)]


class TestMidPatternEvery:
    """`A -> every B` (reference: EveryPatternTestCase mid-chain shapes):
    the B position re-arms — every qualifying B fires with the same A."""

    def test_every_second_element_repeats(self):
        app = (THREE + "from e1=S1[price>10] -> every e2=S2[price>20] "
               "select e1.symbol as a, e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        for i, sym in enumerate(["X", "Y", "Z"]):
            rt.get_input_handler("S2").send((sym, 25.0),
                                            timestamp=1_100 + i)
            rt.flush()
        assert got == [("A", "X"), ("A", "Y"), ("A", "Z")]

    def test_multiple_matches_in_one_batch(self):
        app = (THREE + "from e1=S1[price>10] -> every e2=S2[price>20] "
               "select e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        h = rt.get_input_handler("S2")
        for i, sym in enumerate(["X", "Y", "Z"]):  # ONE batch
            h.send((sym, 25.0), timestamp=1_100 + i)
        rt.flush()
        assert sorted(got) == [("X",), ("Y",), ("Z",)]

    def test_head_every_times_mid_every(self):
        app = (THREE + "from every e1=S1[price>10] -> every e2=S2[price>20] "
               "select e1.symbol as a, e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A1", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S1").send(("A2", 16.0), timestamp=1_001)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 25.0), timestamp=1_100)
        rt.flush()
        assert sorted(got) == [("A1", "B"), ("A2", "B")]

    def test_within_bounds_the_rearming(self):
        app = (THREE +
               "from e1=S1[price>10] -> every e2=S2[price>20] within 1 sec "
               "select e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("in", 25.0), timestamp=1_500)
        rt.flush()
        rt.get_input_handler("S2").send(("out", 25.0), timestamp=2_500)
        rt.flush()
        assert got == [("in",)]

    def test_per_batch_pass_bound_counts_dropped(self):
        """Same-batch matches past config.pattern_sticky_passes advance up
        to the bound and count the leftover into `dropped`."""
        app = (THREE + "from e1=S1[price>10] -> every e2=S2[price>20] "
               "select e2.symbol as b insert into OutStream;")
        rt, got = make(app, batch_size=8)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        h = rt.get_input_handler("S2")
        for i in range(6):  # ONE batch, 6 qualifying arrivals
            h.send((f"B{i}", 25.0), timestamp=1_100 + i)
        rt.flush()
        assert len(got) == 4  # the pass bound
        qr = next(iter(rt.query_runtimes.values()))
        assert int(qr.state.dropped) == 2
        # cross-batch repetition stays exact
        h.send(("B9", 25.0), timestamp=1_200)
        rt.flush()
        assert len(got) == 5

    def test_grouped_every_rejected(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError, match="grouped"):
            make(THREE + "from e1=S1 -> every (e2=S2 -> e3=S3) "
                 "select e1.symbol as a insert into OutStream;")


class TestEveryNot:
    """`every not` — sticky absent positions (reference:
    EveryAbsentPatternTestCase.java testQueryAbsent1/2/4/5)."""

    def test_trailing_every_not_fires_each_quiet_period(self):
        # testQueryAbsent1: e1, 3.2s quiet -> 3 fires
        app = (THREE +
               "from e1=S1[price>20] -> every not S2[price>e1.price] "
               "for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("WSO2", 55.6), timestamp=1_000)
        rt.flush()
        for t in (2_050, 3_050, 4_050):
            rt.heartbeat(now=t)
        assert got == [("WSO2",)] * 3

    def test_trailing_every_not_within_caps_periods(self):
        # testQueryAbsent2: within 2 sec -> only 2 periods fit
        app = (THREE +
               "from (e1=S1[price>20] -> every not S2[price>e1.price] "
               "for 900 millisecond) within 2 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("WSO2", 55.6), timestamp=1_000)
        rt.flush()
        for t in (2_000, 2_900, 3_800, 4_700):
            rt.heartbeat(now=t)
        assert got == [("WSO2",)] * 2

    def test_trailing_every_not_killed_permanently(self):
        # testQueryAbsent4: 2 fires, then a matching e2 consumes the arming
        app = (THREE +
               "from e1=S1[price>20] -> every not S2[price>e1.price] "
               "for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("WSO2", 55.6), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=2_050)
        rt.heartbeat(now=3_050)
        assert got == [("WSO2",)] * 2
        rt.get_input_handler("S2").send(("IBM", 58.7), timestamp=3_100)
        rt.flush()
        rt.heartbeat(now=5_000)
        assert got == [("WSO2",)] * 2  # no further fires

    def test_leading_every_not_entries_accumulate(self):
        # testQueryAbsent5: quiet 2 periods, then ONE e2 -> 2 outputs
        app = (THREE +
               "from every not S1[price>20] for 1 sec -> e2=S2[price>30] "
               "select e2.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.heartbeat(now=100)    # playback anchor
        rt.heartbeat(now=1_150)  # period 1 elapses
        rt.heartbeat(now=2_200)  # period 2 elapses
        rt.get_input_handler("S2").send(("IBM", 58.7), timestamp=2_300)
        rt.flush()
        assert got == [("IBM",)] * 2

    def test_leading_every_not_kill_restarts_measurement(self):
        app = (THREE +
               "from every not S1[price>20] for 1 sec -> e2=S2[price>30] "
               "select e2.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.heartbeat(now=100)
        rt.get_input_handler("S1").send(("X", 25.0), timestamp=600)
        rt.flush()  # period broken: restart from 600
        rt.heartbeat(now=1_200)  # 600ms quiet: not yet a period
        rt.get_input_handler("S2").send(("EARLY", 35.0), timestamp=1_250)
        rt.flush()
        assert got == []
        rt.heartbeat(now=1_800)  # 1.2s quiet since 600: period elapsed
        rt.get_input_handler("S2").send(("OK", 35.0), timestamp=1_900)
        rt.flush()
        assert got == [("OK",)]

    def test_trailing_every_not_late_match_consumes_after_fire(self):
        """A matching X past the current deadline: the completed quiet
        period still fires, then the arming is consumed permanently."""
        app = (THREE +
               "from e1=S1[price>20] -> every not S2[price>e1.price] "
               "for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("WSO2", 55.6), timestamp=1_000)
        rt.flush()
        # no heartbeat: the matching S2 at 2500 is past period 1's deadline
        rt.get_input_handler("S2").send(("IBM", 58.7), timestamp=2_500)
        rt.flush()
        assert got == [("WSO2",)]  # period 1 fired
        rt.heartbeat(now=3_600)
        rt.heartbeat(now=4_600)
        assert got == [("WSO2",)]  # consumed: no further fires

    def test_leading_every_not_late_match_restarts(self):
        """A matching X past the deadline restarts measurement from its own
        timestamp (the completed period still advanced one entry)."""
        app = (THREE +
               "from every not S1[price>20] for 1 sec -> e2=S2[price>30] "
               "select e2.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.heartbeat(now=100)
        rt.get_input_handler("S1").send(("X", 25.0), timestamp=1_500)
        rt.flush()  # period [100,1100] completed; restart from 1500
        rt.heartbeat(now=2_000)  # only 500ms quiet since the restart
        rt.get_input_handler("S2").send(("OK", 35.0), timestamp=2_100)
        rt.flush()
        assert got == [("OK",)]  # exactly the one completed period

    def test_within_inside_every_group_rejected(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError, match="within"):
            make(THREE +
                 "from e1=S1[price>20] -> every ((not S2[price>e1.price] "
                 "for 1 sec) within 2 sec) "
                 "select e1.symbol as s insert into OutStream;")
