"""Absent-pattern matrix (reference: query/pattern/absent/ — 4 test classes,
AbsentPatternTestCase / EveryAbsentPatternTestCase /
AbsentWithEveryPatternTestCase / LogicalAbsentPatternTestCase).

Shapes mirrored (reference file:line cited per test): leading/middle/
trailing `not X for t`, correlated absent filters over earlier captures,
logical `not A and B` without a timer, and every-variants. VERDICT r3
item 8 (absent-pattern tranche)."""

import pytest

from siddhi_tpu import SiddhiManager

THREE = ("define stream S1 (symbol string, price float);\n"
         "define stream S2 (symbol string, price float);\n"
         "define stream S3 (symbol string, price float);\n")


def make(app, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(
        "@app:playback\n" + app, batch_size=batch_size)
    got = []
    rt.add_callback("OutStream", lambda evs: got.extend(
        tuple(e.data) for e in evs))
    rt.start()
    return rt, got


class TestTrailingAbsent:
    """`e1 -> not S2 for 1 sec` (AbsentPatternTestCase.java:49-190)."""

    APP = (THREE + "from e1=S1[price>20] -> not S2[price>e1.price] for 1 sec "
           "select e1.symbol as s insert into OutStream;")

    def test_fires_when_nothing_bigger_arrives(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("IBM", 25.0), timestamp=1_000)
        rt.flush()
        # an S2 BELOW the correlated bound does not kill the absence
        rt.get_input_handler("S2").send(("LO", 10.0), timestamp=1_400)
        rt.flush()
        rt.heartbeat(now=2_500)
        assert got == [("IBM",)]

    def test_killed_by_correlated_match(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("IBM", 25.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("HI", 30.0), timestamp=1_400)
        rt.flush()
        rt.heartbeat(now=2_500)
        assert got == []

    def test_filter_below_threshold_never_arms(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("IBM", 15.0), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=2_500)
        assert got == []

    def test_after_chain(self):
        # e1 -> e2 -> not S3 for 1 sec (AbsentPatternTestCase.java:339-460)
        app = (THREE +
               "from e1=S1[price>10] -> e2=S2[price>20] -> "
               "not S3[price>30] for 1 sec "
               "select e1.symbol as a, e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 25.0), timestamp=1_500)
        rt.flush()
        rt.heartbeat(now=3_000)
        assert got == [("A", "B")]
        # with a killing S3 inside the window instead
        rt2, got2 = make(app)
        rt2.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt2.flush()
        rt2.get_input_handler("S2").send(("B", 25.0), timestamp=1_500)
        rt2.flush()
        rt2.get_input_handler("S3").send(("C", 35.0), timestamp=2_000)
        rt2.flush()
        rt2.heartbeat(now=3_000)
        assert got2 == []


class TestLeadingAbsent:
    """`not S1 for 1 sec -> e2` (AbsentPatternTestCase.java:193-335)."""

    APP = (THREE + "from not S1[price>20] for 1 sec -> e2=S2[price>30] "
           "select e2.symbol as s insert into OutStream;")

    def test_fires_after_quiet_period(self):
        rt, got = make(self.APP)
        # playback arms the leading absent LAZILY at the first observed
        # instant (epoch replays must not measure from virtual 0): anchor
        # the virtual clock, then stay quiet past the waiting time
        rt.heartbeat(now=100)
        rt.heartbeat(now=1_500)  # quiet 1 sec from the anchor
        rt.get_input_handler("S2").send(("OK", 35.0), timestamp=1_600)
        rt.flush()
        assert got == [("OK",)]

    def test_playback_epoch_replay_does_not_fire_spuriously(self):
        # first observed instant is an epoch timestamp with a killing S1 in
        # the same batch: the arming anchors THERE, so the kill applies and
        # nothing fires (regression: arming at virtual 0 made the deadline
        # trivially past and the kill window empty)
        epoch = 1_700_000_000_000
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("X", 25.0), timestamp=epoch + 100)
        rt.flush()
        rt.get_input_handler("S2").send(("OK", 35.0),
                                        timestamp=epoch + 1_600)
        rt.flush()
        rt.heartbeat(now=epoch + 3_000)
        assert got == []

    def test_blocked_by_early_event(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("X", 25.0), timestamp=500)
        rt.flush()
        rt.get_input_handler("S2").send(("OK", 35.0), timestamp=1_600)
        rt.flush()
        rt.heartbeat(now=3_000)
        assert got == []

    def test_e2_before_quiet_period_elapses_does_not_match(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S2").send(("EARLY", 35.0), timestamp=400)
        rt.flush()
        rt.heartbeat(now=3_000)
        assert got == []


class TestMiddleAbsent:
    """`e1 -> not S2 for 1 sec -> e3` (AbsentPatternTestCase.java:462-580)."""

    APP = (THREE +
           "from e1=S1[price>10] -> not S2[price>20] for 1 sec -> "
           "e3=S3[price>30] "
           "select e1.symbol as a, e3.symbol as c insert into OutStream;")

    def test_fires_when_gap_is_quiet(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=2_200)  # quiet 1.2 sec
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=2_300)
        rt.flush()
        assert got == [("A", "C")]

    def test_blocked_by_middle_event(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 25.0), timestamp=1_500)
        rt.flush()
        rt.heartbeat(now=2_200)
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=2_300)
        rt.flush()
        assert got == []

    def test_e3_too_early_does_not_match(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=1_500)
        rt.flush()
        rt.heartbeat(now=3_000)
        assert got == []


class TestLogicalAbsent:
    """`e1 -> not S2 and e3` — absence valid until the AND partner arrives
    (LogicalAbsentPatternTestCase.java:56-130)."""

    APP = (THREE +
           "from e1=S1[price>10] -> not S2[price>20] and e3=S3[price>30] "
           "select e1.symbol as a, e3.symbol as c insert into OutStream;")

    def test_fires_with_partner_when_quiet(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=1_800)
        rt.flush()
        assert got == [("A", "C")]

    def test_blocked_by_absent_stream_event(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 25.0), timestamp=1_400)
        rt.flush()
        rt.get_input_handler("S3").send(("C", 35.0), timestamp=1_800)
        rt.flush()
        assert got == []


class TestEveryAbsent:
    """every + absent (EveryAbsentPatternTestCase /
    AbsentWithEveryPatternTestCase): repeated arming, one firing per arm."""

    def test_every_trailing_absent_repeats(self):
        app = (THREE + "from every e1=S1[price>20] -> not S2 for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 25.0), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=2_500)
        rt.get_input_handler("S1").send(("B", 26.0), timestamp=3_000)
        rt.flush()
        rt.heartbeat(now=4_500)
        assert got == [("A",), ("B",)]

    def test_every_arm_killed_independently(self):
        app = (THREE + "from every e1=S1[price>20] -> not S2 for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 25.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("K", 1.0), timestamp=1_500)  # kills A
        rt.flush()
        rt.get_input_handler("S1").send(("B", 26.0), timestamp=3_000)
        rt.flush()
        rt.heartbeat(now=4_500)
        assert got == [("B",)]


class TestMidPatternEvery:
    """`A -> every B` (reference: EveryPatternTestCase mid-chain shapes):
    the B position re-arms — every qualifying B fires with the same A."""

    def test_every_second_element_repeats(self):
        app = (THREE + "from e1=S1[price>10] -> every e2=S2[price>20] "
               "select e1.symbol as a, e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        for i, sym in enumerate(["X", "Y", "Z"]):
            rt.get_input_handler("S2").send((sym, 25.0),
                                            timestamp=1_100 + i)
            rt.flush()
        assert got == [("A", "X"), ("A", "Y"), ("A", "Z")]

    def test_multiple_matches_in_one_batch(self):
        app = (THREE + "from e1=S1[price>10] -> every e2=S2[price>20] "
               "select e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        h = rt.get_input_handler("S2")
        for i, sym in enumerate(["X", "Y", "Z"]):  # ONE batch
            h.send((sym, 25.0), timestamp=1_100 + i)
        rt.flush()
        assert sorted(got) == [("X",), ("Y",), ("Z",)]

    def test_head_every_times_mid_every(self):
        app = (THREE + "from every e1=S1[price>10] -> every e2=S2[price>20] "
               "select e1.symbol as a, e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A1", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S1").send(("A2", 16.0), timestamp=1_001)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 25.0), timestamp=1_100)
        rt.flush()
        assert sorted(got) == [("A1", "B"), ("A2", "B")]

    def test_within_bounds_the_rearming(self):
        app = (THREE +
               "from e1=S1[price>10] -> every e2=S2[price>20] within 1 sec "
               "select e2.symbol as b insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        rt.get_input_handler("S2").send(("in", 25.0), timestamp=1_500)
        rt.flush()
        rt.get_input_handler("S2").send(("out", 25.0), timestamp=2_500)
        rt.flush()
        assert got == [("in",)]

    def test_per_batch_pass_bound_counts_dropped(self):
        """Same-batch matches past config.pattern_sticky_passes advance up
        to the bound and count the leftover into `dropped`."""
        app = (THREE + "from e1=S1[price>10] -> every e2=S2[price>20] "
               "select e2.symbol as b insert into OutStream;")
        rt, got = make(app, batch_size=8)
        rt.get_input_handler("S1").send(("A", 15.0), timestamp=1_000)
        rt.flush()
        h = rt.get_input_handler("S2")
        for i in range(6):  # ONE batch, 6 qualifying arrivals
            h.send((f"B{i}", 25.0), timestamp=1_100 + i)
        rt.flush()
        assert len(got) == 4  # the pass bound
        qr = next(iter(rt.query_runtimes.values()))
        assert int(qr.state.dropped) == 2
        # cross-batch repetition stays exact
        h.send(("B9", 25.0), timestamp=1_200)
        rt.flush()
        assert len(got) == 5

    def test_mid_grouped_every(self):
        # EveryPatternTestCase testQuery6 shape: e4 -> every (e1 -> e3) ->
        # e2. Iterations of the group pair up sequentially (one in flight,
        # re-armed on completion)
        rt, got = make(
            THREE + "from e4=S1[symbol == 'MSFT'] -> "
            "every (e1=S1[price>20.0] -> e3=S1[price>20.0]) -> "
            "e2=S2[price>e1.price] "
            "select e1.price as p1, e3.price as p3, e2.price as p2 "
            "insert into OutStream;")
        s1 = rt.get_input_handler("S1")
        s2 = rt.get_input_handler("S2")
        for i, (sym, p) in enumerate([("MSFT", 55.6), ("WSO2", 55.7),
                                      ("GOOG", 54.0), ("WSO2", 53.6),
                                      ("GOOG", 53.0)]):
            s1.send((sym, p), timestamp=1000 + i * 100)
            rt.flush()
        s2.send(("IBM", 57.7), timestamp=2000)
        rt.flush()
        assert [tuple(round(x, 1) for x in r) for r in got] == \
            [(55.7, 54.0, 57.7), (53.6, 53.0, 57.7)]


class TestGroupedHeadEvery:
    """`every (e1 -> e3) [-> ...]` — the next iteration arms only when the
    current one completes (reference: EveryPatternTestCase testQuery4/5,
    EveryInnerStateRuntime.java:30)."""

    APP = (THREE + "from every (e1=S1[price>20.0] -> e3=S1[price>20.0]) -> "
           "e2=S2[price>e1.price] "
           "select e1.price as p1, e3.price as p3, e2.price as p2 "
           "insert into OutStream;")

    def test_single_iteration(self):
        # testQuery4: A A B -> exactly one match (not the sliding pairs)
        rt, got = make(self.APP)
        s1 = rt.get_input_handler("S1")
        s1.send(("WSO2", 55.6), timestamp=1000)
        rt.flush()
        s1.send(("GOOG", 54.0), timestamp=1100)
        rt.flush()
        rt.get_input_handler("S2").send(("IBM", 57.7), timestamp=1200)
        rt.flush()
        assert [tuple(round(x, 1) for x in r) for r in got] == \
            [(55.6, 54.0, 57.7)]

    def test_iterations_pair_up(self):
        # testQuery5: A A A A B -> (A1,A2) and (A3,A4), NOT sliding windows
        rt, got = make(self.APP)
        s1 = rt.get_input_handler("S1")
        for i, p in enumerate([55.6, 54.0, 53.6, 53.0]):
            s1.send(("X", p), timestamp=1000 + i * 100)
            rt.flush()
        rt.get_input_handler("S2").send(("IBM", 57.7), timestamp=2000)
        rt.flush()
        assert [tuple(round(x, 1) for x in r) for r in got] == \
            [(55.6, 54.0, 57.7), (53.6, 53.0, 57.7)]

    def test_iterations_pair_up_single_batch(self):
        # all four A's in ONE micro-batch: multi-pass chaining still pairs
        rt, got = make(self.APP)
        s1 = rt.get_input_handler("S1")
        for i, p in enumerate([55.6, 54.0, 53.6, 53.0]):
            s1.send(("X", p), timestamp=1000 + i * 100)
        rt.flush()
        rt.get_input_handler("S2").send(("IBM", 57.7), timestamp=2000)
        rt.flush()
        assert sorted(tuple(round(x, 1) for x in r) for r in got) == \
            [(53.6, 53.0, 57.7), (55.6, 54.0, 57.7)]

    def test_bare_group_emits_per_completion(self):
        # `from every (e1 -> e3) select ...` with nothing after: one output
        # per completed iteration (EveryPatternTestCase.java:422 shape)
        rt, got = make(
            THREE + "from every (e1=S1[price>20.0] -> e3=S1[price>20.0]) "
            "select e1.price as p1, e3.price as p3 insert into OutStream;")
        s1 = rt.get_input_handler("S1")
        for i, p in enumerate([55.6, 54.0, 53.6, 53.0, 52.0]):
            s1.send(("X", p), timestamp=1000 + i * 100)
            rt.flush()
        assert [tuple(round(x, 1) for x in r) for r in got] == \
            [(55.6, 54.0), (53.6, 53.0)]

    def test_within_inside_every_bounds_each_iteration(self):
        # `every ((e1 -> e3) within 1 sec)`: the e1->e3 gap is bounded per
        # iteration; a stale half-open iteration expires and the loop
        # re-arms (reference: per-state within lists,
        # StreamPreStateProcessor.java:119-136)
        rt, got = make(
            THREE + "from every ((e1=S1[price>20.0] -> "
            "e3=S1[price>20.0]) within 1 sec) "
            "select e1.price as p1, e3.price as p3 insert into OutStream;")
        s1 = rt.get_input_handler("S1")
        s1.send(("X", 55.6), timestamp=1000)
        rt.flush()
        rt.heartbeat(now=2500)  # iteration expires un-completed
        s1.send(("X", 54.0), timestamp=3000)
        rt.flush()
        s1.send(("X", 53.0), timestamp=3500)
        rt.flush()
        # 55.6 never pairs (expired); (54.0, 53.0) completes within 1s
        assert [tuple(round(x, 1) for x in r) for r in got] == [(54.0, 53.0)]


class TestTimedNotAnd:
    """`A -> not X for t and Y` (reference: LogicalAbsentPatternTestCase
    testQueryAbsent5/5_1/6/7/8 — AbsentLogicalPreStateProcessor with a
    waiting time)."""

    APP = (THREE + "from e1=S1[price>10.0] -> "
           "not S2[price>20.0] for 1 sec and e3=S3[price>30.0] "
           "select e1.symbol as s1, e3.symbol as s3 insert into OutStream;")

    def test_partner_after_period_fires(self):
        # testQueryAbsent5: A; quiet 1s; Y -> match at Y
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("WSO2", 15.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S3").send(("GOOGLE", 35.0), timestamp=2200)
        rt.flush()
        assert got == [("WSO2", "GOOGLE")]

    def test_partner_inside_period_fires_at_deadline(self):
        # testQueryAbsent5_1: A; Y at +0.5s; period completes at +1s -> match
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("WSO2", 15.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S3").send(("GOOGLE", 35.0), timestamp=1500)
        rt.flush()
        assert got == []  # not before the deadline
        rt.heartbeat(now=2100)
        assert got == [("WSO2", "GOOGLE")]

    def test_no_fire_before_deadline(self):
        # testQueryAbsent6: A; Y at +0.1s; nothing reaches the deadline
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("WSO2", 15.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S3").send(("GOOGLE", 35.0), timestamp=1100)
        rt.flush()
        assert got == []

    def test_x_inside_period_kills(self):
        # testQueryAbsent7: A; X at +0.1s; Y at +0.2s -> no match ever
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("WSO2", 15.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S2").send(("IBM", 25.0), timestamp=1100)
        rt.flush()
        rt.get_input_handler("S3").send(("GOOGLE", 35.0), timestamp=1200)
        rt.flush()
        rt.heartbeat(now=3000)
        assert got == []

    def test_x_after_period_is_ignored(self):
        # testQueryAbsent8: A; quiet 1s; X at +1.1s; Y at +1.2s -> match
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("WSO2", 15.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S2").send(("IBM", 25.0), timestamp=2100)
        rt.flush()
        rt.get_input_handler("S3").send(("GOOGLE", 35.0), timestamp=2200)
        rt.flush()
        assert got == [("WSO2", "GOOGLE")]

    def test_x_kills_even_after_partner_captured(self):
        # testQueryAbsent8_2: A; X and Y both inside the period -> no match
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("WSO2", 15.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S3").send(("GOOGLE", 35.0), timestamp=1100)
        rt.flush()
        rt.get_input_handler("S2").send(("IBM", 25.0), timestamp=1200)
        rt.flush()
        rt.heartbeat(now=3000)
        assert got == []


class TestEveryNot:
    """`every not` — sticky absent positions (reference:
    EveryAbsentPatternTestCase.java testQueryAbsent1/2/4/5)."""

    def test_trailing_every_not_fires_each_quiet_period(self):
        # testQueryAbsent1: e1, 3.2s quiet -> 3 fires
        app = (THREE +
               "from e1=S1[price>20] -> every not S2[price>e1.price] "
               "for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("WSO2", 55.6), timestamp=1_000)
        rt.flush()
        for t in (2_050, 3_050, 4_050):
            rt.heartbeat(now=t)
        assert got == [("WSO2",)] * 3

    def test_trailing_every_not_within_caps_periods(self):
        # testQueryAbsent2: within 2 sec -> only 2 periods fit
        app = (THREE +
               "from (e1=S1[price>20] -> every not S2[price>e1.price] "
               "for 900 millisecond) within 2 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("WSO2", 55.6), timestamp=1_000)
        rt.flush()
        for t in (2_000, 2_900, 3_800, 4_700):
            rt.heartbeat(now=t)
        assert got == [("WSO2",)] * 2

    def test_trailing_every_not_killed_permanently(self):
        # testQueryAbsent4: 2 fires, then a matching e2 consumes the arming
        app = (THREE +
               "from e1=S1[price>20] -> every not S2[price>e1.price] "
               "for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("WSO2", 55.6), timestamp=1_000)
        rt.flush()
        rt.heartbeat(now=2_050)
        rt.heartbeat(now=3_050)
        assert got == [("WSO2",)] * 2
        rt.get_input_handler("S2").send(("IBM", 58.7), timestamp=3_100)
        rt.flush()
        rt.heartbeat(now=5_000)
        assert got == [("WSO2",)] * 2  # no further fires

    def test_leading_every_not_entries_accumulate(self):
        # testQueryAbsent5: quiet 2 periods, then ONE e2 -> 2 outputs
        app = (THREE +
               "from every not S1[price>20] for 1 sec -> e2=S2[price>30] "
               "select e2.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.heartbeat(now=100)    # playback anchor
        rt.heartbeat(now=1_150)  # period 1 elapses
        rt.heartbeat(now=2_200)  # period 2 elapses
        rt.get_input_handler("S2").send(("IBM", 58.7), timestamp=2_300)
        rt.flush()
        assert got == [("IBM",)] * 2

    def test_leading_every_not_kill_restarts_measurement(self):
        app = (THREE +
               "from every not S1[price>20] for 1 sec -> e2=S2[price>30] "
               "select e2.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.heartbeat(now=100)
        rt.get_input_handler("S1").send(("X", 25.0), timestamp=600)
        rt.flush()  # period broken: restart from 600
        rt.heartbeat(now=1_200)  # 600ms quiet: not yet a period
        rt.get_input_handler("S2").send(("EARLY", 35.0), timestamp=1_250)
        rt.flush()
        assert got == []
        rt.heartbeat(now=1_800)  # 1.2s quiet since 600: period elapsed
        rt.get_input_handler("S2").send(("OK", 35.0), timestamp=1_900)
        rt.flush()
        assert got == [("OK",)]

    def test_trailing_every_not_late_match_consumes_after_fire(self):
        """A matching X past the current deadline: the completed quiet
        period still fires, then the arming is consumed permanently."""
        app = (THREE +
               "from e1=S1[price>20] -> every not S2[price>e1.price] "
               "for 1 sec "
               "select e1.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("WSO2", 55.6), timestamp=1_000)
        rt.flush()
        # no heartbeat: the matching S2 at 2500 is past period 1's deadline
        rt.get_input_handler("S2").send(("IBM", 58.7), timestamp=2_500)
        rt.flush()
        assert got == [("WSO2",)]  # period 1 fired
        rt.heartbeat(now=3_600)
        rt.heartbeat(now=4_600)
        assert got == [("WSO2",)]  # consumed: no further fires

    def test_leading_every_not_late_match_restarts(self):
        """A matching X past the deadline restarts measurement from its own
        timestamp (the completed period still advanced one entry)."""
        app = (THREE +
               "from every not S1[price>20] for 1 sec -> e2=S2[price>30] "
               "select e2.symbol as s insert into OutStream;")
        rt, got = make(app)
        rt.heartbeat(now=100)
        rt.get_input_handler("S1").send(("X", 25.0), timestamp=1_500)
        rt.flush()  # period [100,1100] completed; restart from 1500
        rt.heartbeat(now=2_000)  # only 500ms quiet since the restart
        rt.get_input_handler("S2").send(("OK", 35.0), timestamp=2_100)
        rt.flush()
        assert got == [("OK",)]  # exactly the one completed period

    def test_within_inside_every_group_rejected(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError, match="within"):
            make(THREE +
                 "from e1=S1[price>20] -> every ((not S2[price>e1.price] "
                 "for 1 sec) within 2 sec) "
                 "select e1.symbol as s insert into OutStream;")
