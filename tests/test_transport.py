"""Transport fabric tests (reference:
modules/siddhi-core/src/test/java/io/siddhi/core/transport/ —
InMemoryTransportTestCase, MultiClientDistributedSinkTestCase,
SingleClientDistributedTransportTestCases; plus mapper behavior)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.io import InMemoryBroker


@pytest.fixture(autouse=True)
def clean_broker():
    InMemoryBroker.clear()
    yield
    InMemoryBroker.clear()


def build(app_text, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(app_text, batch_size=batch_size)
    rt.start()
    return rt


class TestInMemorySourceSink:
    def test_source_to_sink_roundtrip(self):
        rt = build(
            "@source(type='inMemory', topic='in', @map(type='passThrough'))\n"
            "define stream S (symbol string, price float);\n"
            "@sink(type='inMemory', topic='out', @map(type='passThrough'))\n"
            "define stream Out (symbol string, price float);\n"
            "from S[price > 50.0] select symbol, price insert into Out;")
        got = []
        InMemoryBroker.subscribe_fn("out", got.append)
        InMemoryBroker.publish("in", ("IBM", 75.0))
        InMemoryBroker.publish("in", ("WSO2", 45.0))
        InMemoryBroker.publish("in", ("MSFT", 88.0))
        assert [g[0] for g in got] == ["IBM", "MSFT"]

    def test_json_mapper_roundtrip(self):
        rt = build(
            "@source(type='inMemory', topic='jin', @map(type='json'))\n"
            "define stream S (symbol string, price double);\n"
            "@sink(type='inMemory', topic='jout', @map(type='json'))\n"
            "define stream Out (symbol string, price double);\n"
            "from S select symbol, price insert into Out;")
        got = []
        InMemoryBroker.subscribe_fn("jout", got.append)
        InMemoryBroker.publish("jin", '{"event": {"symbol": "IBM", "price": 75.5}}')
        import json
        assert json.loads(got[0]) == {"event": {"symbol": "IBM", "price": 75.5}}

    def test_json_attribute_paths(self):
        rt = build(
            "@source(type='inMemory', topic='pin', @map(type='json', "
            "@attributes(symbol='$.stock.name', price='$.stock.value')))\n"
            "define stream S (symbol string, price double);\n"
            "@sink(type='inMemory', topic='pout', @map(type='passThrough'))\n"
            "define stream Out (symbol string, price double);\n"
            "from S select symbol, price insert into Out;")
        got = []
        InMemoryBroker.subscribe_fn("pout", got.append)
        InMemoryBroker.publish(
            "pin", '{"stock": {"name": "IBM", "value": 12.5}}')
        assert got == [("IBM", 12.5)]

    def test_text_template_sink(self):
        rt = build(
            "@source(type='inMemory', topic='tin', @map(type='passThrough'))\n"
            "define stream S (symbol string, price double);\n"
            "@sink(type='inMemory', topic='tout', @map(type='text', "
            "@payload('{{symbol}} costs {{price}}')))\n"
            "define stream Out (symbol string, price double);\n"
            "from S select symbol, price insert into Out;")
        got = []
        InMemoryBroker.subscribe_fn("tout", got.append)
        InMemoryBroker.publish("tin", ("IBM", 75.5))
        assert got == ["IBM costs 75.5"]


class TestDistributedSink:
    APP = (
        "@source(type='inMemory', topic='din', @map(type='passThrough'))\n"
        "define stream S (symbol string, price double);\n"
        "@sink(type='inMemory', @map(type='passThrough'), "
        "@distribution(strategy='{strategy}'{extra}, "
        "@destination(topic='d1'), @destination(topic='d2')))\n"
        "define stream Out (symbol string, price double);\n"
        "from S select symbol, price insert into Out;")

    def _run(self, strategy, extra="", events=4):
        rt = build(self.APP.format(strategy=strategy, extra=extra))
        d1, d2 = [], []
        InMemoryBroker.subscribe_fn("d1", d1.append)
        InMemoryBroker.subscribe_fn("d2", d2.append)
        for i in range(events):
            InMemoryBroker.publish("din", (f"S{i % 2}", float(i)))
        return d1, d2

    def test_round_robin(self):
        d1, d2 = self._run("roundRobin")
        assert len(d1) == 2 and len(d2) == 2

    def test_broadcast(self):
        d1, d2 = self._run("broadcast")
        assert len(d1) == 4 and len(d2) == 4

    def test_partitioned(self):
        d1, d2 = self._run("partitioned", extra=", partitionKey='symbol'")
        # same key always lands on the same destination
        keys1 = {r[0] for r in d1}
        keys2 = {r[0] for r in d2}
        assert not (keys1 & keys2)
        assert len(d1) + len(d2) == 4


class TestSourceLifecycle:
    def test_pause_resume(self):
        rt = build(
            "@source(type='inMemory', topic='lin', @map(type='passThrough'))\n"
            "define stream S (v long);\n"
            "@sink(type='inMemory', topic='lout', @map(type='passThrough'))\n"
            "define stream Out (v long);\n"
            "from S select v insert into Out;")
        got = []
        InMemoryBroker.subscribe_fn("lout", got.append)
        src = rt.sources[0]
        src.pause()
        InMemoryBroker.publish("lin", (1,))
        assert got == []
        src.resume()
        rt.flush()
        assert got == [(1,)]

    def test_connect_retry_backoff(self):
        from siddhi_tpu.io import ConnectionUnavailableException, Source

        class FlakySource(Source):
            attempts = 0

            def connect(self):
                FlakySource.attempts += 1
                if FlakySource.attempts < 3:
                    raise ConnectionUnavailableException("nope")

            def disconnect(self):
                pass

        src = FlakySource()
        src.init(None, {}, None, lambda rows: None, None)
        sleeps = []
        src.connect_with_retry(max_attempts=5, sleep=sleeps.append)
        assert FlakySource.attempts == 3
        assert sleeps == [0.005, 0.05]  # reference backoff schedule

    def test_shutdown_disconnects(self):
        rt = build(
            "@source(type='inMemory', topic='sin', @map(type='passThrough'))\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        rt.shutdown()
        got = []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        InMemoryBroker.publish("sin", (1,))  # no subscriber anymore
        rt.flush()
        assert got == []
