"""Table matrix — cache-policy × index × join × set-clause permutations
(reference: query/table/ block, 44 files: JoinTableTestCase,
IndexTableTestCase, LogicalTableTestCase, PrimaryKeyTableTestCase,
set/SetUpdateInMemoryTableTestCase, cache/*; VERDICT r3 item 8)."""

import pytest

from siddhi_tpu import SiddhiManager

S = "define stream S (symbol string, price double, volume long);\n"
C = "define stream C (symbol string, price double);\n"


def build(app, **kw):
    rt = SiddhiManager().create_siddhi_app_runtime(app, **kw)
    rt.start()
    return rt


def q_callback(rt, name):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.extend(
        tuple(e.data) for e in i or []))
    return got


class TestSetClauseFromStream:
    """`from S update T set T.x = <stream expr>` (reference:
    set/SetUpdateInMemoryTableTestCase.java)."""

    def test_set_single_attribute(self):
        rt = build(S + C +
                   "define table T (symbol string, price double);\n"
                   "from S select symbol, price insert into T;\n"
                   "from C update T set T.price = C.price "
                   "on T.symbol == C.symbol;")
        h = rt.get_input_handler("S")
        h.send(("IBM", 10.0, 1))
        h.send(("WSO2", 20.0, 1))
        rt.flush()
        rt.get_input_handler("C").send(("IBM", 99.0))
        rt.flush()
        assert sorted(rt.tables["T"].all_rows()) == [
            ("IBM", 99.0), ("WSO2", 20.0)]

    def test_set_arithmetic_over_both_frames(self):
        rt = build(S + C +
                   "define table T (symbol string, price double);\n"
                   "from S select symbol, price insert into T;\n"
                   "from C update T set T.price = T.price + C.price "
                   "on T.symbol == C.symbol;")
        rt.get_input_handler("S").send(("IBM", 10.0, 1))
        rt.flush()
        # one update per flush: WITHIN a micro-batch updates are last-wins
        # (documented batch granularity, test_tables.py
        # test_update_last_event_wins); across batches they compound exactly
        rt.get_input_handler("C").send(("IBM", 5.0))
        rt.flush()
        rt.get_input_handler("C").send(("IBM", 7.0))
        rt.flush()
        assert rt.tables["T"].all_rows() == [("IBM", 22.0)]

    def test_update_or_insert_with_set(self):
        rt = build(C +
                   "define table T (symbol string, price double);\n"
                   "from C update or insert into T set T.price = C.price "
                   "on T.symbol == C.symbol;")
        h = rt.get_input_handler("C")
        h.send(("A", 1.0))
        rt.flush()
        h.send(("A", 9.0))  # update path
        h.send(("B", 2.0))  # insert path
        rt.flush()
        assert sorted(rt.tables["T"].all_rows()) == [("A", 9.0), ("B", 2.0)]


class TestIndexComparisonMatrix:
    """@Index probes across comparison operators (reference:
    IndexTableTestCase.java — 63 cases over operator × attr combinations)."""

    APP = (C +
           "@Index('price')\n"
           "define table T (symbol string, price double);\n")

    def _table(self, extra_rows=()):
        rt = build(self.APP +
                   "define stream Seed (symbol string, price double);\n"
                   "from Seed select symbol, price insert into T;\n"
                   "@info(name='j') from C join T on C.price > T.price "
                   "select C.symbol as probe, T.symbol as hit "
                   "insert into Out;")
        h = rt.get_input_handler("Seed")
        for row in (("p10", 10.0), ("p20", 20.0), ("p30", 30.0)) + tuple(
                extra_rows):
            h.send(row)
        rt.flush()
        return rt

    def test_range_join_greater_than(self):
        rt = self._table()
        got = q_callback(rt, "j")
        rt.get_input_handler("C").send(("q", 25.0))
        rt.flush()
        assert sorted(h for _, h in got) == ["p10", "p20"]

    def test_on_demand_operator_matrix(self):
        rt = self._table()
        cases = {
            "price == 20.0": ["p20"],
            "price < 20.0": ["p10"],
            "price <= 20.0": ["p10", "p20"],
            "price > 20.0": ["p30"],
            "price >= 20.0": ["p20", "p30"],
            "price != 20.0": ["p10", "p30"],
        }
        for cond, want in cases.items():
            rows = rt.query(f"from T on {cond} select symbol")
            assert sorted(r.data[0] for r in rows) == want, cond


class TestLogicalTableConditions:
    """and/or/not conditions against table frames (reference:
    LogicalTableTestCase.java)."""

    APP = (C +
           "define table T (symbol string, price double);\n"
           "define stream Seed (symbol string, price double);\n"
           "from Seed select symbol, price insert into T;\n")

    def _seed(self, rt):
        h = rt.get_input_handler("Seed")
        for row in [("a", 1.0), ("b", 2.0), ("c", 3.0)]:
            h.send(row)
        rt.flush()

    def test_delete_with_or(self):
        rt = build(self.APP + "from C delete T on "
                   "T.symbol == 'a' or T.price > 2.5;")
        self._seed(rt)
        rt.get_input_handler("C").send(("x", 0.0))
        rt.flush()
        assert rt.tables["T"].all_rows() == [("b", 2.0)]

    def test_delete_with_and_stream_value(self):
        rt = build(self.APP + "from C delete T on "
                   "T.symbol == C.symbol and T.price < C.price;")
        self._seed(rt)
        rt.get_input_handler("C").send(("b", 5.0))
        rt.flush()
        assert sorted(rt.tables["T"].all_rows()) == [("a", 1.0), ("c", 3.0)]

    def test_update_with_not(self):
        rt = build(self.APP + "from C update T set T.price = 0.0 on "
                   "not (T.symbol == C.symbol);")
        self._seed(rt)
        rt.get_input_handler("C").send(("b", 5.0))
        rt.flush()
        assert sorted(rt.tables["T"].all_rows()) == [
            ("a", 0.0), ("b", 2.0), ("c", 0.0)]


class TestJoinPermutations:
    """Join sides/windows (reference: JoinTableTestCase.java)."""

    def test_table_on_left_side(self):
        rt = build(C +
                   "define table T (symbol string, price double);\n"
                   "define stream Seed (symbol string, price double);\n"
                   "from Seed select symbol, price insert into T;\n"
                   "@info(name='j') from T join C on T.symbol == C.symbol "
                   "select T.symbol as sym, T.price as tp, C.price as cp "
                   "insert into Out;")
        rt.get_input_handler("Seed").send(("IBM", 7.0))
        rt.flush()
        got = q_callback(rt, "j")
        rt.get_input_handler("C").send(("IBM", 8.0))
        rt.flush()
        assert got == [("IBM", 7.0, 8.0)]

    def test_windowed_stream_join_table(self):
        rt = build(C +
                   "define table T (symbol string, price double);\n"
                   "define stream Seed (symbol string, price double);\n"
                   "from Seed select symbol, price insert into T;\n"
                   "@info(name='j') from C#window.length(2) join T "
                   "on C.symbol == T.symbol "
                   "select C.symbol as sym insert into Out;")
        rt.get_input_handler("Seed").send(("IBM", 7.0))
        rt.flush()
        got = q_callback(rt, "j")
        h = rt.get_input_handler("C")
        for sym in ("IBM", "x", "y"):  # IBM scrolls out of the window
            h.send((sym, 1.0))
            rt.flush()
        # each arriving batch probes the table; only IBM matches once
        assert got == [("IBM",)]


class TestCachePolicyJoinMatrix:
    """FIFO/LRU/LFU × join-past-eviction (reference: table/cache/*;
    FIFO is covered in test_record_table — these close the matrix)."""

    APP = """
    define stream S (sym string, price double);
    define stream Q (sym string);
    @store(type='inMemory')
    @cache(size='2', policy='{policy}')
    @PrimaryKey('sym')
    define table T (sym string, price double);
    from S select sym, price insert into T;
    @info(name='j') from Q join T on Q.sym == T.sym
    select Q.sym as sym, T.price as price insert into Out;
    """

    @pytest.mark.parametrize("policy", ["LRU", "LFU"])
    def test_join_correct_past_eviction(self, policy):
        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt = build(self.APP.format(policy=policy))
            h = rt.get_input_handler("S")
            for i, sym in enumerate(["a", "b", "c"]):  # overflow size 2
                h.send((sym, float(i)))
                rt.flush()
            got = q_callback(rt, "j")
            evicted = next(s for s in ("a", "b", "c")
                           if (s,) not in rt.tables["T"].cache_policy.rows)
            rt.get_input_handler("Q").send((evicted,))
            rt.flush()
        assert got == [(evicted, float("abc".index(evicted)))]


class TestPrimaryKeyTable:
    """@PrimaryKey × insert/join/update/delete/in (reference:
    PrimaryKeyTableTestCase.java — 76 cases; representative matrix here)."""

    BASE = (
        "define stream StockStream (symbol string, price double, volume long);\n"
        "define stream CheckStockStream (symbol string, volume long);\n"
        "define stream UpdateStockStream "
        "(symbol string, price double, volume long);\n"
        "@PrimaryKey('symbol')\n"
        "define table StockTable (symbol string, price double, volume long);\n"
        "from StockStream insert into StockTable;\n")

    def test_pk_join_returns_latest_row(self):
        # primaryKeyTableTest1 shape: join on the PK attr
        rt = build(self.BASE +
                   "@info(name='q2') from CheckStockStream join StockTable "
                   "on CheckStockStream.symbol == StockTable.symbol "
                   "select CheckStockStream.symbol as symbol, "
                   "StockTable.volume as volume insert into OutStream;")
        h = rt.get_input_handler("StockStream")
        h.send(("WSO2", 55.6, 100))
        h.send(("IBM", 75.6, 10))
        rt.flush()
        got = q_callback(rt, "q2")
        rt.get_input_handler("CheckStockStream").send(("IBM", 0))
        rt.flush()
        assert got == [("IBM", 10)]

    def test_pk_duplicate_insert_dropped_and_counted(self):
        # duplicate-PK inserts are DROPPED (first row wins) and counted —
        # the reference rejects primary-key violations rather than replace;
        # update-or-insert is the replace path
        rt = build(self.BASE)
        h = rt.get_input_handler("StockStream")
        h.send(("IBM", 10.0, 1))
        rt.flush()
        h.send(("IBM", 20.0, 2))
        rt.flush()
        assert rt.tables["StockTable"].all_rows() == [("IBM", 10.0, 1)]
        assert rt.tables["StockTable"].dropped_duplicates == 1

    def test_pk_update_via_stream(self):
        rt = build(self.BASE +
                   "from UpdateStockStream update StockTable "
                   "set StockTable.price = UpdateStockStream.price, "
                   "StockTable.volume = UpdateStockStream.volume "
                   "on StockTable.symbol == UpdateStockStream.symbol;")
        rt.get_input_handler("StockStream").send(("IBM", 10.0, 1))
        rt.flush()
        rt.get_input_handler("UpdateStockStream").send(("IBM", 99.0, 9))
        rt.flush()
        assert rt.tables["StockTable"].all_rows() == [("IBM", 99.0, 9)]

    def test_pk_membership_probe(self):
        rt = build(self.BASE +
                   "@info(name='chk') from CheckStockStream"
                   "[CheckStockStream.symbol == StockTable.symbol "
                   "in StockTable] "
                   "select symbol insert into OutStream;")
        rt.get_input_handler("StockStream").send(("IBM", 10.0, 1))
        rt.flush()
        got = q_callback(rt, "chk")
        c = rt.get_input_handler("CheckStockStream")
        c.send(("IBM", 0))
        c.send(("MSFT", 0))
        rt.flush()
        assert got == [("IBM",)]

    def test_pk_delete_via_stream(self):
        rt = build(self.BASE +
                   "from CheckStockStream delete StockTable "
                   "on StockTable.symbol == CheckStockStream.symbol;")
        h = rt.get_input_handler("StockStream")
        h.send(("IBM", 10.0, 1))
        h.send(("WSO2", 20.0, 2))
        rt.flush()
        rt.get_input_handler("CheckStockStream").send(("IBM", 0))
        rt.flush()
        assert rt.tables["StockTable"].all_rows() == [("WSO2", 20.0, 2)]
