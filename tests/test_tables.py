"""Table CRUD + on-demand query tests.

Mirrors the reference's table behavioral suites
(modules/siddhi-core/src/test/java/io/siddhi/core/query/table/ — 44 files:
InsertIntoTableTestCase, DeleteFromTableTestCase, UpdateFromTableTestCase,
UpdateOrInsertTableTestCase, IndexedTableTestCase) and the on-demand store
suite (store/OnDemandQueryTableTestCase.java): black-box through the public
API — build app from SiddhiQL, send events, assert table contents.
"""

import pytest

from siddhi_tpu import SiddhiManager


pytestmark = pytest.mark.smoke

STOCK = "define stream StockStream (symbol string, price float, volume long);\n"


def run_app(app_text, sends, batch_size=8):
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(app_text, batch_size=batch_size)
    rt.start()
    for stream_id, rows in sends:
        h = rt.get_input_handler(stream_id)
        for row in rows:
            h.send(row)
    rt.flush()
    return rt


class TestInsertIntoTable:
    def test_insert_and_query(self):
        rt = run_app(
            STOCK + "define table StockTable (symbol string, price float, volume long);\n"
            "from StockStream insert into StockTable;",
            [("StockStream", [("IBM", 75.6, 100), ("WSO2", 57.6, 10)])])
        rows = rt.tables["StockTable"].all_rows()
        assert sorted(rows) == [
            ("IBM", pytest.approx(75.6), 100), ("WSO2", pytest.approx(57.6), 10)]

    def test_insert_with_filter(self):
        rt = run_app(
            STOCK + "define table T (symbol string, price float);\n"
            "from StockStream[price > 60.0] select symbol, price insert into T;",
            [("StockStream", [("IBM", 75.6, 100), ("WSO2", 57.6, 10)])])
        assert rt.tables["T"].all_rows() == [("IBM", pytest.approx(75.6))]

    def test_primary_key_dedupe(self):
        rt = run_app(
            STOCK + "@PrimaryKey('symbol')\n"
            "define table T (symbol string, price float);\n"
            "from StockStream select symbol, price insert into T;",
            [("StockStream", [("IBM", 10.0, 1), ("IBM", 20.0, 1), ("WSO2", 30.0, 1)])])
        rows = rt.tables["T"].all_rows()
        assert sorted(rows) == [("IBM", 10.0), ("WSO2", 30.0)]
        assert rt.tables["T"].dropped_duplicates == 1


class TestInTable:
    def test_filter_in_table(self):
        app = (STOCK +
               "define stream CheckStream (symbol string);\n"
               "define table T (symbol string, price float);\n"
               "from StockStream select symbol, price insert into T;\n"
               "from CheckStream[CheckStream.symbol == T.symbol in T] "
               "select symbol insert into OutStream;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        got = []
        rt.add_callback("OutStream", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h1 = rt.get_input_handler("StockStream")
        h1.send(("IBM", 10.0, 1))
        h1.send(("WSO2", 20.0, 1))
        rt.flush()
        h2 = rt.get_input_handler("CheckStream")
        h2.send(("IBM",))
        h2.send(("ORCL",))
        rt.flush()
        assert got == [("IBM",)]


class TestDeleteFromTable:
    def test_delete_on_condition(self):
        app = (STOCK +
               "define stream DeleteStream (symbol string);\n"
               "define table T (symbol string, price float);\n"
               "from StockStream select symbol, price insert into T;\n"
               "from DeleteStream delete T on T.symbol == DeleteStream.symbol;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        rt.get_input_handler("StockStream").send(("IBM", 10.0, 1))
        rt.get_input_handler("StockStream").send(("WSO2", 20.0, 1))
        rt.flush()
        rt.get_input_handler("DeleteStream").send(("IBM",))
        rt.flush()
        assert rt.tables["T"].all_rows() == [("WSO2", 20.0)]


class TestUpdateTable:
    def test_update_on_condition(self):
        app = (STOCK +
               "define stream UpdateStream (symbol string, price float);\n"
               "define table T (symbol string, price float);\n"
               "from StockStream select symbol, price insert into T;\n"
               "from UpdateStream update T set T.price = UpdateStream.price "
               "on T.symbol == UpdateStream.symbol;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        rt.get_input_handler("StockStream").send(("IBM", 10.0, 1))
        rt.get_input_handler("StockStream").send(("WSO2", 20.0, 1))
        rt.flush()
        rt.get_input_handler("UpdateStream").send(("IBM", 99.0))
        rt.flush()
        assert sorted(rt.tables["T"].all_rows()) == [("IBM", 99.0), ("WSO2", 20.0)]

    def test_update_last_event_wins(self):
        app = (STOCK +
               "define stream UpdateStream (symbol string, price float);\n"
               "define table T (symbol string, price float);\n"
               "from StockStream select symbol, price insert into T;\n"
               "from UpdateStream update T set T.price = UpdateStream.price "
               "on T.symbol == UpdateStream.symbol;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        rt.get_input_handler("StockStream").send(("IBM", 10.0, 1))
        rt.flush()
        u = rt.get_input_handler("UpdateStream")
        u.send(("IBM", 50.0))
        u.send(("IBM", 75.0))
        rt.flush()
        assert rt.tables["T"].all_rows() == [("IBM", 75.0)]


class TestUpdateOrInsert:
    def test_update_or_insert(self):
        app = ("define stream In (symbol string, price float);\n"
               "define table T (symbol string, price float);\n"
               "from In update or insert into T set T.price = In.price "
               "on T.symbol == In.symbol;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        h = rt.get_input_handler("In")
        h.send(("IBM", 10.0))
        rt.flush()
        h.send(("IBM", 55.0))
        h.send(("WSO2", 20.0))
        rt.flush()
        assert sorted(rt.tables["T"].all_rows()) == [("IBM", 55.0), ("WSO2", 20.0)]


class TestOnDemandQuery:
    def _rt(self):
        app = (STOCK +
               "define table T (symbol string, price float, volume long);\n"
               "from StockStream insert into T;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        h = rt.get_input_handler("StockStream")
        for row in [("IBM", 10.0, 100), ("IBM", 30.0, 200), ("WSO2", 20.0, 300)]:
            h.send(row)
        rt.flush()
        return rt

    def test_select_all(self):
        rt = self._rt()
        rows = sorted(e.data for e in rt.query("from T select symbol, price, volume"))
        assert rows == [("IBM", 10.0, 100), ("IBM", 30.0, 200), ("WSO2", 20.0, 300)]

    def test_on_condition(self):
        rt = self._rt()
        rows = [e.data for e in rt.query("from T on price > 15.0 select symbol, price")]
        assert sorted(rows) == [("IBM", 30.0), ("WSO2", 20.0)]

    def test_aggregation_group_by(self):
        rt = self._rt()
        rows = {e.data[0]: e.data[1:] for e in rt.query(
            "from T select symbol, sum(price) as total, count() as n group by symbol")}
        assert rows["IBM"] == (40.0, 2)
        assert rows["WSO2"] == (20.0, 1)

    def test_aggregation_no_group(self):
        rt = self._rt()
        rows = [e.data for e in rt.query("from T select sum(volume) as v")]
        assert rows == [(600,)]

    def test_unknown_store(self):
        from siddhi_tpu.errors import DefinitionNotExistError
        rt = self._rt()
        with pytest.raises(DefinitionNotExistError):
            rt.query("from Nope select *")


class TestReviewRegressions:
    def test_having_judges_final_aggregate(self):
        # HAVING must apply to the group's FINAL aggregate, not a running value
        rt = TestOnDemandQuery()._rt()  # IBM: 10+30=40, WSO2: 20
        rows = [e.data for e in rt.query(
            "from T select symbol, sum(price) as s group by symbol having s < 25.0")]
        assert rows == [("WSO2", 20.0)]

    def test_in_combined_with_and(self):
        app = ("define stream S (symbol string, price float);\n"
               "define table T (symbol string);\n"
               "from S[symbol == T.symbol in T and price > 10.0] "
               "select symbol insert into OutStream;")
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        got = []
        rt.add_callback("OutStream", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        rt.tables["T"].insert_rows([("IBM",)])
        h = rt.get_input_handler("S")
        h.send(("IBM", 20.0))   # in table, price ok -> out
        h.send(("IBM", 5.0))    # in table, price too low
        h.send(("WSO2", 50.0))  # not in table
        rt.flush()
        assert got == [("IBM",)]

    def test_insert_overflow_is_all_or_nothing(self):
        from siddhi_tpu.core.table import InMemoryTable
        from siddhi_tpu.errors import CapacityExceededError
        from siddhi_tpu.query_api.definition import (
            Attribute, AttributeType, TableDefinition)
        from siddhi_tpu.core.context import SiddhiAppContext, TimestampGenerator
        from siddhi_tpu.extension.registry import GLOBAL
        from siddhi_tpu.core.event import StringTable
        ctx = SiddhiAppContext(name="t", registry=GLOBAL,
                               timestamp_generator=TimestampGenerator())
        ctx.global_strings = StringTable()
        td = TableDefinition(id="T", attributes=(Attribute("x", AttributeType.INT),))
        t = InMemoryTable(td, ctx, capacity=2)
        t.insert_rows([(1,)])
        with pytest.raises(CapacityExceededError):
            t.insert_rows([(2,), (3,), (4,)])
        assert t.all_rows() == [(1,)]  # untouched


class TestSecondaryIndex:
    """@Index sorted-copy planner (reference: IndexEventHolder.java:60 +
    CompareCollectionExecutor picking index plans over exhaustive scans)."""

    APP = """
    define stream S (symbol string, price double);
    @Index('symbol')
    define table T (symbol string, price double);
    define stream C (symbol string);
    @info(name='chk') from C[C.symbol == T.symbol in T]
    select symbol insert into Hits;
    """

    def test_indexed_membership_parity(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            self.APP + "from S select symbol, price insert into T;\n")
        rt.start()
        got = []
        rt.add_query_callback("chk", lambda ts, i, r: got.extend(
            e.data[0] for e in i or []))
        hs = rt.get_input_handler("S")
        hc = rt.get_input_handler("C")
        hs.send(("IBM", 75.0))
        hs.send(("WSO2", 57.0))
        rt.flush()
        for sym in ("IBM", "GOOG", "WSO2"):
            hc.send((sym,))
        rt.flush()
        assert got == ["IBM", "WSO2"]
        # mutation invalidates and rebuilds the sorted copy
        rt.query("delete T on T.symbol == 'IBM'")
        hc.send(("IBM",))
        hc.send(("WSO2",))
        rt.flush()
        assert got == ["IBM", "WSO2", "WSO2"]

    def test_index_plan_is_chosen(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(self.APP)
        rt.start()
        t = rt.tables["T"]
        assert t.index_attrs == ("symbol",)
        assert "symbol" in t.probe_indexes()

    def test_unknown_index_attr_rejected(self):
        import pytest as _pytest

        from siddhi_tpu.errors import SiddhiAppCreationError
        with _pytest.raises(SiddhiAppCreationError, match="Index"):
            SiddhiManager().create_siddhi_app_runtime(
                "@Index('nope')\n"
                "define table T (k int);")
