"""Multi-tenant churn suite (ISSUE 18): one-retrace splice attach/detach.

The correctness bar mirrors the optimizer parity suite: a query spliced
into a LIVE fused group mid-run must produce BIT-IDENTICAL output to the
same query built from scratch and fed the same post-attach rows, and the
sibling queries' full-run output must equal a no-churn run exactly —
state tensors carry over, nothing drains, nothing re-seeds. Comparison
is exact equality, no tolerances.

Also covered here: the loud fallback (a fault injected into the splice
commit rolls the group back to the exact pre-splice jit with exact event
conservation), tenant device-time quotas (breach diverts ONLY the
offending tenant's queries; the window draining re-splices them), the
per-splice SL501 admission gate, the detach-frees-budget →
admit_pending() regression, and the REST attach/detach routes.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.analysis.cost import compute_cost
from siddhi_tpu.compiler import parse
from siddhi_tpu.errors import SiddhiAppCreationError
from siddhi_tpu.util.faults import inject, parse_fault_spec

pytestmark = pytest.mark.smoke

STREAM = "define stream S (symbol string, price double, volume long);\n"

BASE = ("@app:name('churn')\n" + STREAM +
        "@info(name='q1') from S select symbol, sum(price) as total "
        "group by symbol insert into OutA;\n"
        "@info(name='q2') from S[price > 10.0] select symbol, price "
        "insert into OutB;\n")

Q3 = ("@info(name='q3') from S[volume > 3] select symbol, volume "
      "insert into OutC;")

#: from-scratch oracle for the attached query: same query, own app
Q3_APP = "@app:name('oracle3')\n" + STREAM + Q3


def trades(n, *, t0=1000, dt=100):
    sym = ("IBM", "WSO2", "ORCL")
    return [(t0 + i * dt, (sym[i % 3], float((i * 7) % 50) + 0.25, i + 1))
            for i in range(n)]


PH1 = trades(24)
PH2 = trades(24, t0=9000)


def feed(rt, rows):
    h = rt.get_input_handler("S")
    for ts, row in rows:
        h.send(row, timestamp=ts)
    rt.flush()


def collect(rt, got, *streams):
    for s in streams:
        got.setdefault(s, [])
        rt.add_callback(s, lambda evs, s=s: got[s].extend(
            tuple(e.data) for e in evs))


def run_phases(app, phases, out_streams, *, optimize, batch_size=8):
    """No-churn oracle run: same rows, same flush boundaries, no splice."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app, batch_size=batch_size,
                                     optimize=optimize)
    got = {}
    collect(rt, got, *out_streams)
    rt.start()
    for rows in phases:
        feed(rt, rows)
    m.shutdown()
    return got


def churn_run(*, optimize, batch_size=8):
    """Phase 1 → live attach of q3 → phase 2. The OutC callback must be
    registered AFTER the attach (the output stream only exists then)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(BASE, batch_size=batch_size,
                                     optimize=optimize)
    got = {}
    collect(rt, got, "OutA", "OutB")
    rt.start()
    feed(rt, PH1)
    res = m.attach_query("churn", Q3)
    collect(rt, got, "OutC")
    feed(rt, PH2)
    rep = dict(rt.optimizer_report or {})
    stats = rt.statistics_report()
    m.shutdown()
    return got, res, rep, stats


class TestSpliceParity:
    """Spliced-in output == built-from-scratch output, bit for bit."""

    def _check(self, *, optimize):
        got, res, rep, stats = churn_run(optimize=optimize)
        base = run_phases(BASE, (PH1, PH2), ("OutA", "OutB"),
                          optimize=optimize)
        assert got["OutA"] == base["OutA"], "splice disturbed sibling q1"
        assert got["OutB"] == base["OutB"], "splice disturbed sibling q2"
        scratch = run_phases(Q3_APP, (PH2,), ("OutC",), optimize=False)
        assert got["OutC"] == scratch["OutC"], \
            "spliced-in q3 diverged from from-scratch build"
        return res, rep, stats

    def test_spliced_output_matches_from_scratch(self):
        res, rep, stats = self._check(optimize=True)
        assert res["fused"] is True
        assert res["retrace_ms"] > 0
        assert res["deploy_ms"] >= res["retrace_ms"]
        # spliced to the END of the group: siblings' step order unchanged
        assert rep["group_members"][res["group"]][-1] == "q3"
        assert stats["splices"]["counts"]["in"] == 1
        assert stats["splices"]["last_retrace_ms"] == res["retrace_ms"]

    def test_optimizer_off_attach_stays_standalone(self):
        res, _rep, _stats = self._check(optimize=False)
        assert res["fused"] is False

    def test_superstep_variant(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_SUPERSTEP_K", "8")
        res, _rep, _stats = self._check(optimize=True)
        assert res["fused"] is True

    def test_lock_checks_variant(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_LOCK_CHECKS", "1")
        res, _rep, _stats = self._check(optimize=True)
        assert res["fused"] is True

    def test_detach_mid_run_siblings_undisturbed(self):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(BASE + Q3, batch_size=8,
                                         optimize=True)
        got = {}
        collect(rt, got, "OutA", "OutB", "OutC")
        rt.start()
        feed(rt, PH1)
        before_c = list(got["OutC"])
        out = m.detach_query("churn", "q3")
        assert out["name"] == "q3" and out["detach_ms"] > 0
        feed(rt, PH2)
        assert got["OutC"] == before_c, "detached q3 still produced output"
        rep = rt.optimizer_report
        assert all("q3" not in ms for ms in rep["group_members"].values())
        stats = rt.statistics_report()
        assert stats["splices"]["counts"]["out"] == 1
        m.shutdown()
        base = run_phases(BASE, (PH1, PH2), ("OutA", "OutB"), optimize=True)
        assert got["OutA"] == base["OutA"]
        assert got["OutB"] == base["OutB"]


class TestSpliceChaos:
    def test_mid_splice_failure_rolls_back(self, monkeypatch):
        """SIDDHI_FAULT_SPEC-driven chaos: the splice commit dies mid-way.
        The group must roll back to the EXACT pre-splice trace (same jit
        object — no retrace happened), q3 lands standalone (loud
        fallback), and every event is conserved bit-exactly."""
        monkeypatch.setenv("SIDDHI_FAULT_SPEC", "query:nth=1,exc=error")
        plan = parse_fault_spec(os.environ["SIDDHI_FAULT_SPEC"])["query"]
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(BASE, batch_size=8, optimize=True)
        got = {}
        collect(rt, got, "OutA", "OutB")
        rt.start()
        feed(rt, PH1)
        group = rt.shared_groups[0]
        members = list(group.members)
        step = group._step
        inject(group, "_splice_commit", plan)
        res = m.attach_query("churn", Q3)
        assert res["fused"] is False and "failed" in res
        assert list(group.members) == members
        assert group._step is step
        qr = rt.query_runtimes["q3"]
        assert qr._fused_group is None
        # both the rolled-back group and standalone q3 keep delivering:
        collect(rt, got, "OutC")
        feed(rt, PH2)
        base = run_phases(BASE, (PH1, PH2), ("OutA", "OutB"), optimize=True)
        assert got["OutA"] == base["OutA"]
        assert got["OutB"] == base["OutB"]
        scratch = run_phases(Q3_APP, (PH2,), ("OutC",), optimize=False)
        assert got["OutC"] == scratch["OutC"]
        stats = rt.statistics_report()
        assert stats["splices"]["counts"].get("failed") == 1
        assert rt.ctx.recorder.report()["triggers"].get(
            "splice_failure") == 1
        # the fault was one-shot (nth=1): the NEXT splice lands normally
        res2 = m.attach_query(
            "churn", "@info(name='q4') from S[volume > 20] select symbol "
                     "insert into OutD;")
        assert res2["fused"] is True
        assert stats["splices"]["counts"].get("failed") == 1
        m.shutdown()


TENANT_APP = ("@app:name('mt')\n"
              "@app:tenant(id='acme', device.ms='0.000001', window='0.4')\n"
              "@app:tenant(id='beta', queries='1')\n" + STREAM +
              "@info(name='a1') @tenant('acme') from S select symbol, "
              "sum(volume) as v group by symbol insert into A1;\n"
              "@info(name='b1') @tenant('beta') from S[price > 10.0] "
              "select symbol insert into B1;\n"
              "@info(name='free') from S select symbol, price "
              "insert into F1;\n")


class TestTenantQuotas:
    def test_breach_diverts_tenant_then_window_drain_resplices(self):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(TENANT_APP, batch_size=8,
                                         optimize=True)
        got = {}
        collect(rt, got, "A1", "B1", "F1")
        rt.start()
        feed(rt, PH1)  # metered; enforcement trips AFTER this flush
        len_a, len_b, len_f = (len(got[s]) for s in ("A1", "B1", "F1"))
        feed(rt, PH1)
        # acme diverted; beta and untenanted queries untouched
        assert len(got["A1"]) == len_a, "breached tenant still dispatched"
        assert len(got["B1"]) > len_b and len(got["F1"]) > len_f
        stats = rt.statistics_report()
        t = stats["tenants"]["acme"]
        assert t["diverting"] is True and t["breaches"] >= 1
        assert t["dominant_query"] == "a1"
        assert t["diverted_rows"] > 0
        assert stats["splices"]["tenant_breaches"]["acme"] >= 1
        a1 = rt.query_runtimes["a1"]
        assert a1.breaker is not None and a1.breaker.state == "open"
        assert a1.breaker.quota_tenant == "acme"
        assert a1._fused_group is None  # spliced OUT, siblings kept fused
        rep = rt.optimizer_report
        assert all("a1" not in ms for ms in rep["group_members"].values())
        assert rt.ctx.recorder.report()["triggers"].get(
            "tenant_quota_breach") == 1
        # let the rolling device-time window (0.4 s) drain → auto-recover
        time.sleep(0.5)
        rt.flush()
        t = rt.statistics_report()["tenants"]["acme"]
        assert t["diverting"] is False
        assert a1.breaker is None
        assert a1._fused_group is not None, "recovered query not re-spliced"
        len_a = len(got["A1"])
        feed(rt, PH2)
        assert len(got["A1"]) > len_a, "recovered tenant still diverted"
        m.shutdown()

    def test_query_count_quota_refuses_attach(self):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(TENANT_APP, batch_size=8,
                                         optimize=True)
        rt.start()
        n_elements = len(rt.app.execution_elements)
        recv = {id(j): len(j.receivers) for j in rt.junctions.values()}
        with pytest.raises(SiddhiAppCreationError, match="SL502"):
            m.attach_query(
                "mt", "@info(name='b2') @tenant('beta') from S "
                      "select symbol insert into B2;")
        # the failed attach unwound completely: no runtime, no plan change
        assert "b2" not in rt.query_runtimes
        assert len(rt.app.execution_elements) == n_elements
        assert {id(j): len(j.receivers)
                for j in rt.junctions.values()} == recv
        m.shutdown()


APP_A = ("@app:name('A')\n" + STREAM +
         "@info(name='qa1') from S[price > 0.0] select symbol "
         "insert into OA;\n"
         "@info(name='qa2') from S#window.length(64) select sum(price) "
         "as t insert into OB;\n")
APP_B = ("@app:name('B')\ndefine stream T (y double);\n"
         "@info(name='qb') from T[y > 0.0] select y insert into OC;\n")


class TestSpliceAdmission:
    def test_detach_frees_budget_and_admits_pending(self, monkeypatch):
        """Regression (ISSUE 18 small fix): a detach re-prices the fleet
        against the post-splice plan, so freed budget immediately admits
        a queued app — no manual admit_pending() poke needed."""
        cost_a = compute_cost(parse(APP_A)).state_bytes
        cost_b = compute_cost(parse(APP_B)).state_bytes
        assert cost_a > 0 and cost_b > 0
        monkeypatch.setenv("SIDDHI_STATE_BUDGET", str(cost_a + cost_b - 1))
        monkeypatch.setenv("SIDDHI_BUDGET_MODE", "queue")
        m = SiddhiManager()
        rt_a = m.create_siddhi_app_runtime(APP_A)
        assert rt_a is not None
        assert m.create_siddhi_app_runtime(APP_B) is None  # deferred
        assert [a.name for a, _ in m.pending_apps] == ["B"]
        rt_a.start()
        out = m.detach_query("A", "qa2")  # frees the window state
        assert out.get("admitted_pending") == ["B"]
        assert "B" in m.runtimes and not m.pending_apps
        m.shutdown()

    def test_over_budget_splice_refused_never_queued(self, monkeypatch):
        cost_a = compute_cost(parse(APP_A)).state_bytes
        monkeypatch.setenv("SIDDHI_STATE_BUDGET", str(cost_a + 8))
        monkeypatch.setenv("SIDDHI_BUDGET_MODE", "queue")
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(APP_A)
        rt.start()
        with pytest.raises(SiddhiAppCreationError, match="SL501"):
            m.attach_query(
                "A", "@info(name='qbig') from S#window.length(4096) "
                     "select sum(price) as t insert into OD;")
        # even in queue mode a splice RAISES — and leaves no trace behind
        assert "qbig" not in rt.query_runtimes
        assert m.pending_apps == []
        m.shutdown()


QS = ("@info(name='qs') from S select symbol, sum(volume) as v "
      "group by symbol insert into OV;")


class TestAttachWithState:
    def test_attach_carries_restored_state_into_splice(self):
        """attach_query(state=) seeds the new query through the
        element-mapped restore primitive BEFORE the splice, so the fused
        trace starts from the migrated tensors. The donor app must share
        the target's app name (snapshots are app-scoped)."""
        donor_mgr = SiddhiManager()
        donor = donor_mgr.create_siddhi_app_runtime(
            BASE + QS, batch_size=8, optimize=True)
        got_d = {}
        collect(donor, got_d, "OV")
        donor.start()
        feed(donor, PH1)
        blob = donor.snapshot()
        n_before = len(got_d["OV"])
        feed(donor, PH2)
        continuation = got_d["OV"][n_before:]

        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(BASE, batch_size=8, optimize=True)
        got = {}
        collect(rt, got, "OutA")
        rt.start()
        feed(rt, PH1)
        res = m.attach_query("churn", QS, state=blob)
        assert res["fused"] is True
        collect(rt, got, "OV")
        feed(rt, PH2)
        assert got["OV"] == continuation, \
            "restored aggregate state did not carry into the splice"
        m.shutdown()
        donor_mgr.shutdown()


@pytest.fixture()
def server():
    from siddhi_tpu.service import SiddhiService
    svc = SiddhiService()
    httpd = svc.make_server(port=0)  # ephemeral port
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", svc
    httpd.shutdown()


def _req(url, method="GET", body=None, ctype=None):
    data = body.encode() if isinstance(body, str) else body
    headers = {"Content-Type": ctype} if ctype else {}
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


class TestSpliceRest:
    def test_attach_detach_routes(self, server):
        base, _svc = server
        code, _ = _req(f"{base}/siddhi-apps", "POST", BASE)
        assert code == 201
        # raw SiddhiQL body
        code, out = _req(f"{base}/siddhi-apps/churn/queries", "POST", Q3)
        assert code == 201 and out["name"] == "q3"
        assert out["deploy_ms"] > 0
        # JSON body with an explicit name for an anonymous query
        q = "from S[price > 40.0] select symbol insert into OutE;"
        code, out = _req(f"{base}/siddhi-apps/churn/queries", "POST",
                         json.dumps({"query": q, "name": "q9"}),
                         ctype="application/json")
        assert code == 201 and out["name"] == "q9"
        code, out = _req(f"{base}/siddhi-apps/churn/queries/q9", "DELETE")
        assert code == 200 and out["name"] == "q9"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(f"{base}/siddhi-apps/churn/queries/nope", "DELETE")
        assert ei.value.code == 404
        _req(f"{base}/siddhi-apps/churn", "DELETE")
