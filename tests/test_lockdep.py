"""Lockdep runtime verification (siddhi_tpu/util/locks.py).

Covers the tracker itself — a deliberately seeded lock-order inversion is
detected without the deadlock ever firing, held-across-blocking hazards
report and respect allow-lists, RLock re-entrancy and Condition.wait's
full-release are modeled correctly — plus the zero-overhead contract
(factories return raw primitives when checks are off) and a
seed-reproducible regression test for the AsyncDecoder @OnError path's
bounded controller-lock acquire.
"""

import threading
import time

import pytest

from siddhi_tpu.util import locks


@pytest.fixture(autouse=True)
def clean_lockdep():
    """Tracked state on, clean graph; restore module flags afterwards."""
    prev_checks = locks.checks_enabled()
    prev_seed = locks.schedule_fuzz_seed()
    locks.enable_checks(True)
    locks.set_schedule_fuzz(None)
    locks.lockdep_reset()
    yield
    locks.lockdep_reset()
    locks.enable_checks(prev_checks)
    locks.set_schedule_fuzz(prev_seed)


class TestFactories:
    def test_disabled_returns_raw_primitives(self):
        locks.enable_checks(False)
        assert type(locks.named_lock("t.raw")) is type(threading.Lock())
        assert type(locks.named_rlock("t.raw")) is type(threading.RLock())
        assert isinstance(locks.named_condition("t.raw"),
                          threading.Condition)

    def test_enabled_registers_names(self):
        locks.named_lock("t.reg")
        locks.named_lock("t.reg")
        assert locks.lockdep_report()["locks"]["t.reg"] == 2


class TestCycleDetection:
    def test_seeded_inversion_is_detected_without_deadlocking(self):
        """A -> B in one place, B -> A in another: reported as a potential
        deadlock from the orderings alone — neither thread ever blocks."""
        a, b = locks.named_lock("t.a"), locks.named_lock("t.b")
        with a:
            with b:
                pass
        assert locks.lockdep_report()["cycles"] == []
        with b:
            with a:
                pass
        cycles = locks.lockdep_report()["cycles"]
        assert len(cycles) == 1
        c = cycles[0]
        assert c["kind"] == "lock-order-inversion"
        assert set(c["cycle"]) == {"t.a", "t.b"}
        assert c["this_site"]  # the stack that closed the cycle

    def test_same_cycle_reported_once(self):
        a, b = locks.named_lock("t.a"), locks.named_lock("t.b")
        for _ in range(3):
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
        assert len(locks.lockdep_report()["cycles"]) == 1

    def test_three_lock_cycle(self):
        a = locks.named_lock("t.a")
        b = locks.named_lock("t.b")
        c = locks.named_lock("t.c")
        for outer, inner in ((a, b), (b, c), (c, a)):
            with outer:
                with inner:
                    pass
        cycles = locks.lockdep_report()["cycles"]
        assert len(cycles) == 1
        assert set(cycles[0]["cycle"]) == {"t.a", "t.b", "t.c"}

    def test_consistent_order_stays_clean(self):
        a, b, c = (locks.named_lock(f"t.{x}") for x in "abc")
        for _ in range(5):
            with a:
                with b:
                    with c:
                        pass
        rep = locks.lockdep_report()
        assert rep["cycles"] == []
        assert ("t.a", "t.b") in [tuple(e) for e in rep["edges"]]

    def test_rlock_reentrancy_adds_no_edge(self):
        r = locks.named_rlock("t.re")
        with r:
            with r:
                pass
        rep = locks.lockdep_report()
        assert rep["edges"] == [] and rep["cycles"] == []

    def test_same_name_instances_do_not_self_cycle(self):
        """Two controller locks live during a blue-green swap share one
        digraph node: nesting them must not report a false inversion."""
        l1 = locks.named_rlock("t.controller")
        l2 = locks.named_rlock("t.controller")
        with l1:
            with l2:
                pass
        assert locks.lockdep_report()["cycles"] == []


class TestBlockingHazards:
    def test_held_lock_is_reported(self):
        g = locks.named_lock("t.guard")
        with g:
            locks.note_blocking("test.fsync")
        hz = locks.lockdep_report()["hazards"]
        assert len(hz) == 1
        assert hz[0]["blocking"] == "test.fsync"
        assert hz[0]["held"] == ["t.guard"]

    def test_allow_list_suppresses(self):
        g = locks.named_lock("t.guard")
        with g:
            locks.note_blocking("test.fsync", allow=("t.guard",))
        assert locks.lockdep_report()["hazards"] == []

    def test_reported_once_per_kind_and_held_set(self):
        g = locks.named_lock("t.guard")
        for _ in range(4):
            with g:
                locks.note_blocking("test.fsync")
        assert len(locks.lockdep_report()["hazards"]) == 1

    def test_no_lock_held_is_free(self):
        locks.note_blocking("test.fsync")
        assert locks.lockdep_report()["hazards"] == []


class TestCondition:
    def test_wait_releases_the_held_name(self):
        """Condition.wait fully releases its lock — while a thread waits,
        its held-stack must not pin the name (else every lock taken by the
        waker would grow false edges from the sleeper's frame)."""
        cv = locks.named_condition("t.cv")
        seen = []
        started = threading.Event()

        def sleeper():
            with cv:
                started.set()
                cv.wait(timeout=5)
                # restored after wake: blocking note sees the name again
                locks.note_blocking("t.probe")
                seen.append(True)

        t = threading.Thread(target=sleeper)
        t.start()
        started.wait(timeout=5)
        time.sleep(0.05)  # sleeper is inside wait(): name must be off-stack
        with cv:
            cv.notify_all()
        t.join(timeout=5)
        assert seen == [True]
        hz = locks.lockdep_report()["hazards"]
        assert any(h["held"] == ["t.cv"] for h in hz)

    def test_wait_for_roundtrip(self):
        cv = locks.named_condition("t.cv2")
        flag = []

        def waker():
            with cv:
                flag.append(1)
                cv.notify_all()

        with cv:
            threading.Timer(0.05, waker).start()
            assert cv.wait_for(lambda: flag, timeout=5)


class TestScheduleFuzz:
    def test_seed_roundtrip(self):
        locks.set_schedule_fuzz(42)
        assert locks.schedule_fuzz_seed() == 42
        assert locks.lockdep_report()["fuzz_seed"] == 42
        locks.set_schedule_fuzz(None)
        assert locks.schedule_fuzz_seed() is None

    def test_fuzzed_acquisitions_still_correct(self):
        """Preemption points perturb timing only: a counter guarded by a
        fuzzed lock stays exact across threads."""
        locks.set_schedule_fuzz(7)
        g = locks.named_lock("t.fuzzed")
        state = {"n": 0}

        def bump():
            for _ in range(200):
                with g:
                    state["n"] += 1

        threads = [threading.Thread(target=bump) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert state["n"] == 800
        assert locks.lockdep_report()["cycles"] == []


class TestAsyncDecoderBoundedAcquire:
    def test_error_path_survives_producer_holding_controller_lock(self):
        """Regression (found by lockdep + schedule fuzzing, seed 7): a
        producer that holds the controller lock while blocked on the
        decoder's bounded submit queue must not deadlock against the
        delivery thread's @OnError routing, which needs that same lock.
        The fix bounds the delivery-side acquire (timeout + log fallback),
        so the pipeline always drains and the producer's put completes."""
        import numpy as np

        from siddhi_tpu.core.stream import AsyncDecoder

        locks.set_schedule_fuzz(7)  # replayable pressure pattern
        controller = locks.named_rlock("app.controller")

        class Ctx:
            controller_lock = controller

        class Junction:
            ctx = Ctx()
            on_error_action = None
            routed = []

            @staticmethod
            def on_error(e, host):
                Junction.routed.append(repr(e))

        class Receiver:
            calls = 0

            @staticmethod
            def on_batch(host, now):
                Receiver.calls += 1
                raise ValueError("decode boom")

        # n must overflow queue(1) + fetch workers + reorder-buffer lag,
        # or the pipeline absorbs every submit and the put never blocks —
        # the deadlock needs the producer wedged INSIDE the bounded put
        dec = AsyncDecoder(maxsize=1)
        n = dec.N_FETCH + dec._max_lag + 8
        finished = threading.Event()

        def produce():
            # the hazardous shape: submit under the controller lock, queue
            # bounded at 1 — the put WILL block while the lock is held
            with controller:
                for i in range(n):
                    dec.submit(Receiver, np.arange(4, dtype=np.int64), i,
                               Junction)
            finished.set()

        t = threading.Thread(target=produce)
        t.start()
        # pre-fix this deadlocked: delivery waited forever on the
        # controller lock, the reorder buffer never drained, the producer's
        # put never returned
        assert finished.wait(timeout=30), \
            "producer deadlocked against the delivery thread"
        dec.stop()
        assert Receiver.calls == n  # every batch was attempted
        # every failure was routed: through @OnError once the lock freed,
        # or through the log while the producer still held it
        assert len(Junction.routed) <= n
        t.join(timeout=5)
