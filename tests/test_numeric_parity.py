"""Numeric-parity tolerance suite (SURVEY §4: "numeric-parity tests
Java-Siddhi never needed — float accumulation order").

The reference accumulates Java doubles SEQUENTIALLY per event
(SumAttributeAggregatorExecutor.java:132-154); this engine reduces in
parallel (segment scans, psum trees), so float results may differ in the
last ulps. Policy, documented here as the executable contract:

- DOUBLE attributes map to float32 by DEFAULT (f64 is software-emulated
  on TPU, ~10x slower — core/dtypes.py): parity with Java double to
  ~1e-5 relative on 2e4-event sums (pairwise f32 reduction loses LESS
  than sequential f32);
- `config.double_dtype = jnp.float64` restores ~1e-9 double parity;
- FLOAT attributes accumulate in float32 — parity to ~1e-4 relative;
- integer sums/counts are EXACT at any order;
- avg/stdDev inherit their component tolerances.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

N = 20_000


def run_agg(attr_type: str, values, extra_select=""):
    app = f"""
    define stream S (k string, v {attr_type});
    @info(name='q')
    from S#window.lengthBatch({len(values)})
    select sum(v) as s, avg(v) as a, count() as n{extra_select}
    insert into Out;
    """
    rt = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=4096)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(tuple(e) for e in evs))
    rt.start()
    h = rt.get_input_handler("S")
    for v in values:
        h.send(("a", v))
    rt.flush()
    rt.shutdown()
    return rows[-1]


class TestNumericParity:
    def test_double_default_f32_policy(self):
        rng = np.random.default_rng(11)
        vals = rng.uniform(-1000.0, 1000.0, N)
        seq = 0.0
        for v in vals:  # the reference's per-event accumulation order
            seq += float(v)
        s, a, n = run_agg("double", [float(v) for v in vals])
        assert n == N
        assert s == pytest.approx(seq, rel=1e-5)
        assert a == pytest.approx(seq / N, rel=1e-5)

    def test_double_f64_config_restores_double_parity(self):
        import jax.numpy as jnp

        from siddhi_tpu.core import dtypes
        rng = np.random.default_rng(11)
        vals = rng.uniform(-1000.0, 1000.0, N)
        seq = 0.0
        for v in vals:
            seq += float(v)
        prev = dtypes.config.double_dtype
        dtypes.config.double_dtype = jnp.float64
        try:
            s, a, n = run_agg("double", [float(v) for v in vals])
        finally:
            dtypes.config.double_dtype = prev
        assert s == pytest.approx(seq, rel=1e-9)
        assert a == pytest.approx(seq / N, rel=1e-9)

    def test_float_sum_matches_float64_reference_to_1e4(self):
        rng = np.random.default_rng(12)
        vals = rng.uniform(0.0, 100.0, N).astype(np.float32)
        ref = float(np.sum(vals.astype(np.float64)))
        s, a, n = run_agg("float", [float(v) for v in vals])
        assert s == pytest.approx(ref, rel=1e-4)
        assert a == pytest.approx(ref / N, rel=1e-4)

    def test_long_sum_exact(self):
        rng = np.random.default_rng(13)
        vals = [int(v) for v in rng.integers(-10**12, 10**12, N)]
        s, a, n = run_agg("long", vals)
        assert s == sum(vals)  # exact, any reduction order

    def test_stddev_double(self):
        rng = np.random.default_rng(14)
        vals = rng.uniform(-50.0, 50.0, 5000)
        s, a, n, sd = run_agg("double", [float(v) for v in vals],
                              extra_select=", stdDev(v) as sd")
        # reference computes population stdDev incrementally
        assert sd == pytest.approx(float(np.std(vals)), rel=1e-7)

    def test_stddev_sliding_window_removal(self):
        # stdDev must also be removal-capable (sliding windows)
        app = """
        define stream S (k string, v double);
        @info(name='q')
        from S#window.length(3)
        select stdDev(v) as sd
        insert into Out;
        """
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=4)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(tuple(e) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        vals = [1.0, 5.0, 9.0, 13.0]  # windows [1],[1,5],[1,5,9],[5,9,13]
        for v in vals:
            h.send(("a", v))
        rt.flush()
        rt.shutdown()
        expect = [np.std([1.0]), np.std([1.0, 5.0]),
                  np.std([1.0, 5.0, 9.0]), np.std([5.0, 9.0, 13.0])]
        got = [r[0] for r in rows]
        assert got == pytest.approx([float(e) for e in expect], rel=1e-5)

    def test_stddev_grouped(self):
        app = """
        define stream S (k string, v double);
        @info(name='q')
        from S#window.lengthBatch(6)
        select k, stdDev(v) as sd
        group by k
        insert into Out;
        """
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(tuple(e) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        data = [("a", 1.0), ("b", 10.0), ("a", 3.0), ("b", 30.0),
                ("a", 5.0), ("b", 50.0)]
        for k, v in data:
            h.send((k, v))
        rt.flush()
        rt.shutdown()
        final = {}
        for r in rows:
            final[r[0]] = r[1]
        assert final["a"] == pytest.approx(float(np.std([1.0, 3.0, 5.0])))
        assert final["b"] == pytest.approx(float(np.std([10.0, 30.0, 50.0])))
