"""A/B parity suite for the multi-query optimizer (ISSUE 9 correctness bar).

Every test builds the SAME app twice — optimize=False and optimize=True —
feeds byte-identical input, and requires BIT-IDENTICAL callback output for
every query. Fusion traces each member's unchanged step body inside one
jax.jit (core/shared.py), so any divergence is a real rewrite bug, not
float noise: the comparison is exact equality, no tolerances.

Covers the acceptance matrix: filters, projections, group-by aggregates,
correlated (multi-span) time windows, persistence round-trip across modes,
and the upgrade diff seeing the pre-optimization plan.
"""

import pytest

from siddhi_tpu import SiddhiManager

pytestmark = pytest.mark.smoke


def run_app(app: str, streams: dict, out_streams, *, optimize: bool,
            batch_size: int = 8, rt_hook=None):
    """Build + run one mode. `streams` maps stream id -> [(ts, row), ...];
    returns {out_stream: [row tuples]} from per-Event callbacks."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app, batch_size=batch_size,
                                     optimize=optimize)
    got = {s: [] for s in out_streams}
    for s in out_streams:
        rt.add_callback(s, lambda evs, s=s: got[s].extend(
            tuple(e.data) for e in evs))
    rt.start()
    for sid, rows in streams.items():
        h = rt.get_input_handler(sid)
        for ts, row in rows:
            h.send(row, timestamp=ts)
        rt.flush()
    rt.flush()
    if rt_hook is not None:
        rt_hook(rt)
    m.shutdown()
    return got


def ab_check(app: str, streams: dict, out_streams, *, batch_size: int = 8,
             expect_fused: int = 2):
    """The A/B harness: optimizer-on output must equal optimizer-off output
    exactly, and fusion must actually have engaged (expect_fused queries)."""
    off = run_app(app, streams, out_streams, optimize=False,
                  batch_size=batch_size)
    report = {}
    on = run_app(app, streams, out_streams, optimize=True,
                 batch_size=batch_size,
                 rt_hook=lambda rt: report.update(rt.optimizer_report or {}))
    assert on == off, f"optimizer changed output:\n on={on}\noff={off}"
    assert report.get("queries_fused", 0) >= expect_fused, report
    return off, report


def trades(n, *, t0=1000, dt=100):
    sym = ("IBM", "WSO2", "ORCL")
    return [(t0 + i * dt, (sym[i % 3], float((i * 7) % 50) + 0.25, i + 1))
            for i in range(n)]


STREAM = "define stream S (symbol string, price double, volume long);\n"


class TestFilterProjectionParity:
    def test_filters_and_projections(self):
        app = (STREAM +
               "@info(name='a') from S[price > 10.0] select symbol, price "
               "insert into OutA;\n"
               "@info(name='b') from S[price > 25.0] select symbol, volume "
               "insert into OutB;\n"
               "@info(name='c') from S select symbol, price * 2.0 as dbl "
               "insert into OutC;\n")
        out, rep = ab_check(app, {"S": trades(40)}, ("OutA", "OutB", "OutC"),
                            expect_fused=3)
        assert out["OutA"] and out["OutB"] and out["OutC"]
        assert rep["groups"] == 1

    def test_shared_subexpressions(self):
        # identical filter + projection expressions across members: the
        # canonicalizer must count them, fusion must not change results
        app = (STREAM +
               "@info(name='a') from S[price * 1.1 > 20.0] "
               "select symbol, price * 1.1 as adj insert into OutA;\n"
               "@info(name='b') from S[price * 1.1 > 20.0] "
               "select symbol, volume insert into OutB;\n")
        out, rep = ab_check(app, {"S": trades(32)}, ("OutA", "OutB"))
        assert out["OutA"]
        assert rep["cse_hits"] >= 1

    def test_heterogeneous_types(self):
        app = ("define stream S (sym string, price double, qty int, "
               "flag bool);\n"
               "@info(name='a') from S[flag == true] select sym, qty "
               "insert into OutA;\n"
               "@info(name='b') from S[qty > 5] select sym, price "
               "insert into OutB;\n")
        rows = [(1000 + i, (f"K{i % 4}", i * 1.5, i % 12, i % 3 == 0))
                for i in range(30)]
        ab_check(app, {"S": rows}, ("OutA", "OutB"))


class TestAggregateParity:
    def test_group_by_aggregates(self):
        app = (STREAM +
               "@info(name='a') from S select symbol, sum(price) as total "
               "group by symbol insert into OutA;\n"
               "@info(name='b') from S select symbol, count() as n, "
               "avg(volume) as av group by symbol insert into OutB;\n")
        out, _ = ab_check(app, {"S": trades(48)}, ("OutA", "OutB"))
        assert out["OutA"] and out["OutB"]

    def test_correlated_time_windows(self):
        # the factor-window shape: same stream + key, three window spans —
        # fused into one traced step (pane_candidates counts the overlap)
        app = ("@app:playback\n" + STREAM +
               "@info(name='w1') from S#window.time(1 sec) select symbol, "
               "sum(price) as s group by symbol insert into Out1;\n"
               "@info(name='w2') from S#window.time(5 sec) select symbol, "
               "sum(price) as s group by symbol insert into Out2;\n"
               "@info(name='w3') from S#window.time(20 sec) select symbol, "
               "sum(price) as s, count() as n group by symbol "
               "insert into Out3;\n")
        out, rep = ab_check(app, {"S": trades(60, dt=250)},
                            ("Out1", "Out2", "Out3"), expect_fused=3)
        assert out["Out1"] and out["Out2"] and out["Out3"]
        assert rep["pane_candidates"] >= 2

    def test_mixed_stateless_and_windowed(self):
        app = ("@app:playback\n" + STREAM +
               "@info(name='f') from S[price > 5.0] select symbol, price "
               "insert into OutF;\n"
               "@info(name='w') from S#window.time(2 sec) select symbol, "
               "max(price) as hi group by symbol insert into OutW;\n")
        ab_check(app, {"S": trades(40, dt=200)}, ("OutF", "OutW"))


class TestPushdownParity:
    def test_post_filter_pushdown(self):
        # paramless #window.batch() lowers to pass-through, so its
        # post-window filter is provably pushable ahead of the window
        app = (STREAM +
               "@info(name='a') from S#window.batch()[price > 12.0] "
               "select symbol, price, volume insert into OutA;\n"
               "@info(name='b') from S[volume > 3] select symbol "
               "insert into OutB;\n")
        off, rep = ab_check(app, {"S": trades(36)}, ("OutA", "OutB"))
        assert off["OutA"]
        assert rep["pushdowns"] >= 1


class TestPersistenceParity:
    APP = (STREAM +
           "@info(name='a') from S select symbol, sum(price) as total "
           "group by symbol insert into OutA;\n"
           "@info(name='b') from S select symbol, count() as n "
           "group by symbol insert into OutB;\n")

    def _runtime(self, optimize, got):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(self.APP, batch_size=4,
                                         optimize=optimize)
        for s in ("OutA", "OutB"):
            rt.add_callback(s, lambda evs, s=s: got[s].extend(
                tuple(e.data) for e in evs))
        rt.start()
        return rt

    @pytest.mark.parametrize("src,dst", [(True, False), (False, True),
                                         (True, True)])
    def test_snapshot_crosses_modes(self, src, dst):
        """Fused state lives per-query, so a snapshot taken under either
        mode restores into either mode — layout is identical."""
        got1 = {"OutA": [], "OutB": []}
        rt1 = self._runtime(src, got1)
        h = rt1.get_input_handler("S")
        for ts, row in trades(12):
            h.send(row, timestamp=ts)
        rt1.flush()
        blob = rt1.snapshot()
        assert got1["OutA"]

        got2 = {"OutA": [], "OutB": []}
        rt2 = self._runtime(dst, got2)
        rt2.restore(blob)
        h2 = rt2.get_input_handler("S")
        for ts, row in trades(6, t0=9000):
            h2.send(row, timestamp=ts)
        rt2.flush()

        # oracle: unfused runtime fed the full 18-row history
        got3 = {"OutA": [], "OutB": []}
        rt3 = self._runtime(False, got3)
        h3 = rt3.get_input_handler("S")
        for ts, row in trades(12) + trades(6, t0=9000):
            h3.send(row, timestamp=ts)
        rt3.flush()
        assert got2["OutA"] == got3["OutA"][len(got1["OutA"]):]
        assert got2["OutB"] == got3["OutB"][len(got1["OutB"]):]
        rt1.shutdown(); rt2.shutdown(); rt3.shutdown()


class TestUpgradeDiffParity:
    def test_plan_fingerprint_sees_unfused_layout(self):
        """rt.app stays the pre-optimization app: plan fingerprints (and so
        upgrade classification) are identical across modes."""
        from siddhi_tpu.analysis import element_fingerprints, plan_fingerprint
        app = (STREAM +
               "@info(name='a') from S[price > 1.0] select symbol "
               "insert into OutA;\n"
               "@info(name='b') from S[price > 2.0] select symbol "
               "insert into OutB;\n")
        m = SiddhiManager()
        rt_off = m.create_siddhi_app_runtime(app, optimize=False)
        rt_on = SiddhiManager().create_siddhi_app_runtime(app, optimize=True)
        assert plan_fingerprint(rt_on.app) == plan_fingerprint(rt_off.app)
        assert (element_fingerprints(rt_on.app)
                == element_fingerprints(rt_off.app))
        assert rt_on.optimizer_report["queries_fused"] == 2
        rt_off.shutdown(); rt_on.shutdown()

    def test_upgrade_diff_unchanged_under_optimizer(self):
        from siddhi_tpu.analysis import diff_apps
        from siddhi_tpu import compiler
        v1 = (STREAM +
              "@info(name='a') from S[price > 1.0] select symbol "
              "insert into OutA;\n"
              "@info(name='b') from S[price > 2.0] select symbol "
              "insert into OutB;\n")
        v2 = (STREAM +
              "@info(name='a') from S[price > 1.5] select symbol "
              "insert into OutA;\n"
              "@info(name='b') from S[price > 2.0] select symbol "
              "insert into OutB;\n")
        d = diff_apps(compiler.parse(v1), compiler.parse(v2))
        # the diff classifies query 'a' as changed whether or not a runtime
        # would fuse it — the optimizer never rewrites SiddhiApp objects
        assert "query:a" in d.changed
        assert "query:b" in d.migratable


class TestDispatchEquivalence:
    def test_partial_batches_and_flush_boundaries(self):
        # ragged feed: flush after every row → partial-lane batches take the
        # bucketed (or padded) path through the fused step
        app = (STREAM +
               "@info(name='a') from S[price > 10.0] select symbol, price "
               "insert into OutA;\n"
               "@info(name='b') from S select symbol, volume "
               "insert into OutB;\n")

        def run(optimize):
            m = SiddhiManager()
            rt = m.create_siddhi_app_runtime(app, batch_size=8,
                                             optimize=optimize)
            got = {"OutA": [], "OutB": []}
            for s in got:
                rt.add_callback(s, lambda evs, s=s: got[s].extend(
                    tuple(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i, (ts, row) in enumerate(trades(21)):
                h.send(row, timestamp=ts)
                if i % 3 == 0:
                    rt.flush()  # ragged partial batches
            rt.flush()
            m.shutdown()
            return got

        assert run(True) == run(False)

    def test_chained_streams_fuse_downstream(self):
        # fused group feeding a derived stream that itself hosts a fused
        # group: cascades must see written-back state (re-entrancy order)
        app = (STREAM +
               "@info(name='m1') from S[price > 5.0] select symbol, price "
               "insert into Mid;\n"
               "@info(name='m2') from S[price > 15.0] select symbol, price "
               "insert into Mid;\n"
               "@info(name='d1') from Mid[price > 20.0] select symbol "
               "insert into OutD1;\n"
               "@info(name='d2') from Mid select symbol, price "
               "insert into OutD2;\n")
        out, rep = ab_check(app, {"S": trades(30)}, ("OutD1", "OutD2"),
                            expect_fused=4)
        assert out["OutD2"]
        assert rep["groups"] == 2
