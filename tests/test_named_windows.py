"""Named window (`define window`) behavioral tests.

Mirrors the reference's window-definition suites
(modules/siddhi-core/src/test/java/io/siddhi/core/query/window/ —
WindowDefinitionTestCase-style: define window, insert via one query, consume
via `from W` in another, join against it, pull-query it).
"""

import pytest

from siddhi_tpu import SiddhiManager

STOCK = "define stream StockStream (symbol string, price float, volume long);\n"


def build(app_text, batch_size=8):
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(app_text, batch_size=batch_size)
    rt.start()
    return rt


def collect_callback(rt, query_name):
    got = []

    def cb(ts, in_events, remove_events):
        got.append((in_events, remove_events))

    rt.add_query_callback(query_name, cb)
    return got


class TestNamedWindowBasics:
    def test_insert_and_consume(self):
        rt = build(
            STOCK
            + "define window StockWindow (symbol string, price float, volume long) lengthBatch(2);\n"
            "from StockStream insert into StockWindow;\n"
            "@info(name='q2') from StockWindow select symbol, sum(price) as total "
            "insert into OutputStream;")
        got = collect_callback(rt, "q2")
        h = rt.get_input_handler("StockStream")
        for row in [("IBM", 10.0, 1), ("IBM", 20.0, 1)]:
            h.send(row)
        rt.flush()
        ins = [e for ins_, _ in got if ins_ for e in ins_]
        # lengthBatch(2) emits on the second arrival; running sum over emissions
        assert ins[-1].data[1] == pytest.approx(30.0)

    def test_length_window_expired_events(self):
        rt = build(
            STOCK
            + "define window W (symbol string, price float, volume long) length(2) output all events;\n"
            "from StockStream insert into W;\n"
            "@info(name='q2') from W select symbol, sum(price) as total "
            "insert into OutputStream;")
        got = collect_callback(rt, "q2")
        h = rt.get_input_handler("StockStream")
        for i, row in enumerate([("A", 10.0, 1), ("B", 20.0, 1), ("C", 40.0, 1)]):
            h.send(row)
            rt.flush()
        # after C arrives, A expires: running sum = 10+20+40-10 = 60
        ins = [e for ins_, _ in got if ins_ for e in ins_]
        assert ins[-1].data[1] == pytest.approx(60.0)

    def test_output_current_events_only(self):
        rt = build(
            STOCK
            + "define window W (symbol string, price float, volume long) length(1) output current events;\n"
            "from StockStream insert into W;\n"
            "@info(name='q2') from W select symbol, price insert into OutputStream;")
        got = collect_callback(rt, "q2")
        h = rt.get_input_handler("StockStream")
        for row in [("A", 10.0, 1), ("B", 20.0, 1)]:
            h.send(row)
            rt.flush()
        removes = [e for _, rem in got if rem for e in rem]
        assert removes == []  # expired emissions suppressed
        ins = [e for ins_, _ in got if ins_ for e in ins_]
        assert [e.data[0] for e in ins] == ["A", "B"]

    def test_positional_rename_on_insert(self):
        # query emits different attr names; insert matches positionally
        rt = build(
            STOCK
            + "define window W (sym string, p float) length(5);\n"
            "from StockStream select symbol, price insert into W;\n"
            "@info(name='q2') from W select sym, max(p) as maxP insert into Out;")
        got = collect_callback(rt, "q2")
        h = rt.get_input_handler("StockStream")
        h.send(("IBM", 12.5, 3))
        rt.flush()
        ins = [e for ins_, _ in got if ins_ for e in ins_]
        assert ins[0].data == ("IBM", pytest.approx(12.5))


class TestNamedWindowJoin:
    def test_stream_join_named_window(self):
        rt = build(
            STOCK
            + "define stream CheckStream (symbol string);\n"
            "define window StockWindow (symbol string, price float, volume long) length(10);\n"
            "from StockStream insert into StockWindow;\n"
            "@info(name='j') from CheckStream join StockWindow "
            "on CheckStream.symbol == StockWindow.symbol "
            "select CheckStream.symbol as symbol, StockWindow.price as price "
            "insert into OutStream;")
        got = collect_callback(rt, "j")
        rt.get_input_handler("StockStream").send(("IBM", 75.0, 100))
        rt.get_input_handler("StockStream").send(("WSO2", 55.0, 100))
        rt.flush()
        rt.get_input_handler("CheckStream").send(("IBM",))
        rt.flush()
        ins = [e for ins_, _ in got if ins_ for e in ins_]
        assert len(ins) == 1
        assert ins[0].data == ("IBM", pytest.approx(75.0))


class TestNamedWindowJoinFilter:
    def test_window_side_filter_applies_to_contents(self):
        rt = build(
            STOCK
            + "define stream CheckStream (symbol string);\n"
            "define window W (symbol string, price float, volume long) length(10);\n"
            "from StockStream insert into W;\n"
            "@info(name='j') from CheckStream join W[price > 60.0] "
            "on CheckStream.symbol == W.symbol "
            "select CheckStream.symbol as symbol, W.price as price "
            "insert into OutStream;")
        got = collect_callback(rt, "j")
        rt.get_input_handler("StockStream").send(("WSO2", 55.0, 10))
        rt.get_input_handler("StockStream").send(("WSO2", 75.0, 10))
        rt.flush()
        rt.get_input_handler("CheckStream").send(("WSO2",))
        rt.flush()
        ins = [e for ins_, _ in got if ins_ for e in ins_]
        assert [tuple(e.data) for e in ins] == [("WSO2", pytest.approx(75.0))]


class TestNamedWindowOnDemand:
    def test_pull_query_window_contents(self):
        rt = build(
            STOCK
            + "define window W (symbol string, price float, volume long) length(3);\n"
            "from StockStream insert into W;")
        h = rt.get_input_handler("StockStream")
        for row in [("A", 10.0, 1), ("B", 20.0, 2), ("C", 30.0, 3), ("D", 40.0, 4)]:
            h.send(row)
        rt.flush()
        events = rt.query("from W select symbol, price")
        rows = sorted(tuple(e.data) for e in events)
        # length(3): A has expired
        assert rows == [("B", pytest.approx(20.0)), ("C", pytest.approx(30.0)),
                        ("D", pytest.approx(40.0))]

    def test_pull_query_window_aggregate(self):
        rt = build(
            STOCK
            + "define window W (symbol string, price float, volume long) length(10);\n"
            "from StockStream insert into W;")
        h = rt.get_input_handler("StockStream")
        for row in [("A", 10.0, 1), ("A", 30.0, 2), ("B", 5.0, 3)]:
            h.send(row)
        rt.flush()
        events = rt.query("from W select symbol, sum(price) as total group by symbol")
        rows = sorted(tuple(e.data) for e in events)
        assert rows == [("A", pytest.approx(40.0)), ("B", pytest.approx(5.0))]


class TestNamedWindowPersistence:
    def test_snapshot_restore_window_state(self):
        app = (STOCK
               + "define window W (symbol string, price float, volume long) length(5);\n"
               "from StockStream insert into W;")
        rt = build(app)
        h = rt.get_input_handler("StockStream")
        h.send(("A", 1.0, 1))
        h.send(("B", 2.0, 2))
        rt.flush()
        blob = rt.snapshot()

        rt2 = build(app)
        rt2.restore(blob)
        events = rt2.query("from W select symbol, price")
        assert sorted(e.data[0] for e in events) == ["A", "B"]
