"""Checkpoint / restore tests.

Mirrors the reference's managment/PersistenceTestCase.java: run a stateful
query, persist, create a fresh runtime, restore, continue sending — aggregate
state must carry over.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.state.persistence import (
    FileSystemPersistenceStore,
    InMemoryPersistenceStore,
)


pytestmark = pytest.mark.smoke

APP = ("@app:name('PersistApp')\n"
       "define stream S (symbol string, price float);\n"
       "@info(name = 'q1')\n"
       "from S select symbol, sum(price) as total group by symbol "
       "insert into OutStream;")


def build(store, got):
    manager = SiddhiManager()
    manager.set_persistence_store(store)
    rt = manager.create_siddhi_app_runtime(APP, batch_size=4)
    rt.add_callback("OutStream", lambda evs: got.extend(e.data for e in evs))
    rt.start()
    return rt


class TestPersistRestore:
    def _roundtrip(self, store):
        got1 = []
        rt1 = build(store, got1)
        h = rt1.get_input_handler("S")
        h.send(("IBM", 10.0))
        h.send(("IBM", 20.0))
        rt1.flush()
        assert got1[-1] == ("IBM", 30.0)
        rev = rt1.persist()
        assert rev

        # fresh runtime: state restored, aggregation continues from 30.0
        got2 = []
        rt2 = build(store, got2)
        restored = rt2.restore_last_revision()
        assert restored == rev
        rt2.get_input_handler("S").send(("IBM", 5.0))
        rt2.flush()
        assert got2[-1] == ("IBM", 35.0)

    def test_in_memory_store(self):
        self._roundtrip(InMemoryPersistenceStore())

    def test_filesystem_store(self, tmp_path):
        self._roundtrip(FileSystemPersistenceStore(str(tmp_path)))

    def test_snapshot_restore_bytes(self):
        got = []
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(APP, batch_size=4)
        rt.add_callback("OutStream", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("A", 1.0))
        rt.flush()
        blob = rt.snapshot()
        h.send(("A", 2.0))
        rt.flush()
        assert got[-1] == ("A", 3.0)
        rt.restore(blob)  # back to sum=1.0
        h.send(("A", 2.0))
        rt.flush()
        assert got[-1] == ("A", 3.0)

    def test_window_state_persisted(self):
        app = ("@app:name('WinApp')\n"
               "define stream S (k string, v int);\n"
               "from S#window.lengthBatch(3) select sum(v) as s "
               "insert into OutStream;")
        store = InMemoryPersistenceStore()
        got1 = []
        manager = SiddhiManager()
        manager.set_persistence_store(store)
        rt1 = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt1.add_callback("OutStream", lambda evs: got1.extend(e.data for e in evs))
        rt1.start()
        h = rt1.get_input_handler("S")
        h.send(("a", 1)); h.send(("b", 2))
        rt1.flush()
        assert got1 == []  # batch of 3 not complete
        rt1.persist()

        got2 = []
        manager2 = SiddhiManager()
        manager2.set_persistence_store(store)
        rt2 = manager2.create_siddhi_app_runtime(app, batch_size=4)
        rt2.add_callback("OutStream", lambda evs: got2.extend(e.data for e in evs))
        rt2.start()
        rt2.restore_last_revision()
        rt2.get_input_handler("S").send(("c", 4))
        rt2.flush()
        # flush emits per-event running sums over the restored window: the
        # final lane is 1+2 (restored) + 4
        assert got2[-1] == (7,)

    def test_pattern_snapshot_without_armed0_ts_restores(self):
        # round-3 builds pickled PatternState without the armed0_ts field;
        # restore must tolerate the missing leaf (re-armed from the current
        # runtime build) instead of failing (advisor round-4 low finding)
        import pickle

        from siddhi_tpu.core.pattern_runtime import PatternState

        app = ("define stream A (x int); define stream B (x int);\n"
               "@info(name='p') from e1=A -> e2=B "
               "select e1.x as ax, e2.x as bx insert into Out;")
        got = []
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        rt.get_input_handler("A").send((1,))
        rt.flush()
        blob = rt.snapshot()

        # simulate the round-3 wire format: no armed0_ts / gate0_seq on
        # PatternState and no origin on the PendingTables
        from siddhi_tpu.core.pattern_runtime import PendingTable
        snap = pickle.loads(blob)
        st = snap["queries"]["p"]
        assert isinstance(st, PatternState)
        old_pending = tuple(PendingTable(*tuple(p)[:8]) for p in st.pending)
        snap["queries"]["p"] = PatternState(
            old_pending, *tuple(st)[1:5])
        assert snap["queries"]["p"].armed0_ts is None
        assert snap["queries"]["p"].gate0_seq is None
        assert old_pending[0].origin is None
        old_blob = pickle.dumps(snap)

        rt.restore(old_blob)
        rt.get_input_handler("B").send((2,))
        rt.flush()
        assert got[-1] == (1, 2)

    def test_last_revision_after_multiple_persists_and_torn_tmp(
            self, tmp_path):
        """restore_last_revision must pick the NEWEST whole revision even
        when a crash left a torn tmp file behind (FileSystemPersistenceStore
        writes fsync'd tmp+rename; an abandoned `.tmp` is never a
        candidate)."""
        store = FileSystemPersistenceStore(str(tmp_path))
        got1 = []
        rt1 = build(store, got1)
        h = rt1.get_input_handler("S")
        h.send(("IBM", 10.0))
        rt1.flush()
        rev1 = rt1.persist()
        h.send(("IBM", 20.0))
        rt1.flush()
        rev2 = rt1.persist()
        assert rev2 > rev1
        # simulate a crash mid-save AFTER rev2: a torn tmp with a name that
        # would sort last if it were ever considered
        d = tmp_path / "PersistApp"
        (d / ".9999999999999_PersistApp.tmp").write_bytes(b"half a snap")
        got2 = []
        rt2 = build(store, got2)
        assert rt2.restore_last_revision() == rev2
        rt2.get_input_handler("S").send(("IBM", 5.0))
        rt2.flush()
        assert got2[-1] == ("IBM", 35.0)  # rev2's 30.0 + 5.0

    def test_save_replaces_tmp_atomically(self, tmp_path):
        store = FileSystemPersistenceStore(str(tmp_path))
        store.save("A", "1_A", b"snap")
        assert sorted(f for f in (tmp_path / "A").iterdir()) == \
            [tmp_path / "A" / "1_A"]  # no tmp residue
        assert store.load("A", "1_A") == b"snap"

    def test_wrong_app_rejected(self):
        from siddhi_tpu.errors import CannotRestoreStateError
        manager = SiddhiManager()
        rt = manager.create_siddhi_app_runtime(APP, batch_size=4)
        other = manager.create_siddhi_app_runtime(
            "@app:name('Other')\ndefine stream S (x int);\n"
            "from S select x insert into Out2;", batch_size=4)
        blob = other.snapshot()
        with pytest.raises(CannotRestoreStateError):
            rt.restore(blob)


class TestIncrementalFileSystemStore:
    """Reference: IncrementalFileSystemPersistenceStore.java:37 — delta
    revisions with periodic full re-base."""

    def _build(self, manager, app):
        rt = manager.create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        return rt

    def test_delta_chain_restores(self, tmp_path):
        from siddhi_tpu.state.persistence import IncrementalFileSystemPersistenceStore
        app = ("@app:name('IncApp')\n"
               "define stream S (k string, v long);\n"
               "@info(name='q') from S select k, sum(v) as total group by k "
               "insert into Out;")
        store = IncrementalFileSystemPersistenceStore(str(tmp_path))
        manager = SiddhiManager()
        manager.set_persistence_store(store)
        rt = self._build(manager, app)
        h = rt.get_input_handler("S")
        revs = []
        for i in range(4):
            h.send(("a", i + 1))
            rt.flush()
            revs.append(rt.persist())
        # later revisions are deltas: strictly smaller than the full base
        import os
        d = tmp_path / "IncApp"
        sizes = {r: os.path.getsize(d / r) for r in revs}
        assert sizes[revs[1]] < sizes[revs[0]]

        rt2 = self._build(SiddhiManager(), app)
        rt2.persistence_store = store
        rt2.restore_revision(revs[3])
        got = []
        rt2.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        rt2.get_input_handler("S").send(("a", 10))
        rt2.flush()
        # restored running sum 1+2+3+4 = 10, plus 10
        assert got[-1].data[1] == 20

    def test_intermediate_revision_restores(self, tmp_path):
        from siddhi_tpu.state.persistence import IncrementalFileSystemPersistenceStore
        app = ("@app:name('IncApp2')\n"
               "define stream S (k string, v long);\n"
               "@info(name='q') from S select k, sum(v) as total group by k "
               "insert into Out;")
        store = IncrementalFileSystemPersistenceStore(str(tmp_path))
        manager = SiddhiManager()
        manager.set_persistence_store(store)
        rt = self._build(manager, app)
        h = rt.get_input_handler("S")
        revs = []
        for i in range(3):
            h.send(("a", i + 1))
            rt.flush()
            revs.append(rt.persist())
        rt2 = self._build(SiddhiManager(), app)
        rt2.persistence_store = store
        rt2.restore_revision(revs[1])  # middle delta: base + one delta
        got = []
        rt2.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        rt2.get_input_handler("S").send(("a", 0))
        rt2.flush()
        assert got[-1].data[1] == 3  # 1+2 restored

    def test_full_rebase_every_n(self, tmp_path):
        from siddhi_tpu.state.persistence import IncrementalFileSystemPersistenceStore
        app = ("@app:name('IncApp3')\n"
               "define stream S (k string, v long);\n"
               "from S select k, sum(v) as t group by k insert into Out;")
        store = IncrementalFileSystemPersistenceStore(str(tmp_path), full_every=2)
        manager = SiddhiManager()
        manager.set_persistence_store(store)
        rt = self._build(manager, app)
        h = rt.get_input_handler("S")
        import pickle
        revs = []
        for i in range(4):
            h.send(("a", 1))
            rt.flush()
            revs.append(rt.persist())
        kinds = []
        for r in revs:
            with open(tmp_path / "IncApp3" / r, "rb") as f:
                kinds.append(pickle.load(f)["kind"])
        assert kinds == ["full", "delta", "full", "delta"]


class TestDeviceDeltaPersist:
    """VERDICT r3 item 7 (first half): persist() must not re-read device
    state that no batch touched — object identity of the state pytrees is
    the change log (every jitted step replaces its state)."""

    APP = ("define stream S (sym string, v long);\n"
           "@info(name='q') from S#window.length(100) "
           "select sym, sum(v) as total group by sym insert into Out;")

    def _runtime(self, store):
        rt = SiddhiManager().create_siddhi_app_runtime(self.APP, batch_size=8)
        rt.persistence_store = store
        rt.start()
        return rt

    def test_idle_persist_fetches_nothing_and_ships_no_leaves(self, tmp_path):
        import pickle

        import siddhi_tpu.state.persistence as P
        store = P.IncrementalFileSystemPersistenceStore(str(tmp_path))
        rt = self._runtime(store)
        h = rt.get_input_handler("S")
        h.send(("a", 1))
        h.send(("b", 2))
        rt.flush()
        rt.persist()

        calls = []
        orig = P._to_host
        P._to_host = lambda t: (calls.append(1), orig(t))[1]
        try:
            rev2 = rt.persist()  # nothing ran since the last persist
        finally:
            P._to_host = orig
        assert calls == [], "idle persist still fetched device state"
        app_dir = tmp_path / rt.app.name
        payload = pickle.loads((app_dir / rev2).read_bytes())
        assert payload["kind"] == "delta"
        assert payload["leaves"] == {}

    def test_active_persist_fetches_and_restores(self, tmp_path):
        import siddhi_tpu.state.persistence as P
        store = P.IncrementalFileSystemPersistenceStore(str(tmp_path))
        rt = self._runtime(store)
        h = rt.get_input_handler("S")
        h.send(("a", 1))
        rt.flush()
        rt.persist()
        h.send(("a", 9))
        rt.flush()
        rev2 = rt.persist()  # state changed: delta carries the new leaves

        rt2 = self._runtime(store)
        rt2.restore_revision(rev2)
        got = []
        rt2.add_query_callback("q", lambda ts, i, r: got.extend(
            tuple(e.data) for e in i or []))
        rt2.get_input_handler("S").send(("a", 5))
        rt2.flush()
        assert got == [("a", 15)]
