"""Trigger behavioral tests (reference: core/trigger/ — PeriodicTrigger,
CronTrigger, StartTrigger; trigger streams carry one `triggered_time long`).

Uses @app:playback so the clock is event/heartbeat-driven and deterministic.
"""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.trigger import CronSchedule


def build(app_text, batch_size=8):
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(app_text, batch_size=batch_size)
    return rt


class TestStartTrigger:
    def test_fires_once_on_start(self):
        rt = build(
            "@app:playback\n"
            "define trigger InitTrigger at 'start';\n"
            "from InitTrigger select triggered_time insert into Out;")
        got = []
        rt.add_callback("Out", lambda events: got.extend(events))
        rt.start()
        rt.flush()
        assert len(got) == 1
        rt.heartbeat(10_000)
        assert len(got) == 1  # start trigger fires exactly once


class TestPeriodicTrigger:
    def test_every_interval_fires(self):
        rt = build(
            "@app:playback\n"
            "define trigger T at every 1 sec;\n"
            "from T select triggered_time insert into Out;")
        got = []
        rt.add_callback("Out", lambda events: got.extend(events))
        rt.start()  # playback clock starts at 0 → next fire at 1000
        rt.heartbeat(5_000)
        assert [e.data[0] for e in got] == [1000, 2000, 3000, 4000, 5000]
        rt.heartbeat(6_500)
        assert [e.data[0] for e in got][-1] == 6000

    def test_trigger_feeds_time_window_query(self):
        rt = build(
            "@app:playback\n"
            "define trigger T at every 500 milliseconds;\n"
            "from T select count() as fires insert into Out;")
        got = []
        rt.add_callback("Out", lambda events: got.extend(events))
        rt.start()
        rt.heartbeat(2_000)
        assert got[-1].data[0] == 4  # fires at 500,1000,1500,2000


class TestCronSchedule:
    def test_every_minute(self):
        cs = CronSchedule("0 * * * * ?")
        # after 00:00:30 the next fire is 00:01:00
        import datetime
        base = int(datetime.datetime(2026, 1, 5, 0, 0, 30).timestamp() * 1000)
        nxt = cs.next_fire_ms(base)
        assert nxt == int(datetime.datetime(2026, 1, 5, 0, 1, 0).timestamp() * 1000)

    def test_specific_hour_range(self):
        cs = CronSchedule("0 0 9-17 * * MON-FRI")
        import datetime
        # Friday 17:30 → next fire Monday 09:00
        base = int(datetime.datetime(2026, 1, 9, 17, 30, 0).timestamp() * 1000)
        nxt = cs.next_fire_ms(base)
        assert nxt == int(datetime.datetime(2026, 1, 12, 9, 0, 0).timestamp() * 1000)

    def test_step_seconds(self):
        cs = CronSchedule("*/15 * * * * ?")
        import datetime
        base = int(datetime.datetime(2026, 1, 5, 10, 0, 1).timestamp() * 1000)
        nxt = cs.next_fire_ms(base)
        assert nxt == int(datetime.datetime(2026, 1, 5, 10, 0, 15).timestamp() * 1000)

    def test_wrap_around_range(self):
        cs = CronSchedule("0 0 22-2 * * ?")
        import datetime
        base = int(datetime.datetime(2026, 1, 5, 20, 0, 0).timestamp() * 1000)
        nxt = cs.next_fire_ms(base)
        assert nxt == int(datetime.datetime(2026, 1, 5, 22, 0, 0).timestamp() * 1000)
        base2 = int(datetime.datetime(2026, 1, 5, 23, 30, 0).timestamp() * 1000)
        nxt2 = cs.next_fire_ms(base2)
        assert nxt2 == int(datetime.datetime(2026, 1, 6, 0, 0, 0).timestamp() * 1000)

    def test_unsatisfiable_field_rejected(self):
        import pytest
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError):
            CronSchedule("0 61 * * * ?")  # minute out of range

    def test_cron_trigger_runtime(self):
        rt = build(
            "@app:playback\n"
            "define trigger T at '*/2 * * * * ?';\n"
            "from T select triggered_time insert into Out;")
        got = []
        rt.add_callback("Out", lambda events: got.extend(events))
        rt.start()  # playback time 0 = epoch; every-2-seconds cron
        rt.heartbeat(10_000)
        assert [e.data[0] for e in got] == [2000, 4000, 6000, 8000, 10000]
