"""SLO engine tests (siddhi_tpu/telemetry/slo.py).

The burn-rate math runs entirely on a virtual clock: a fake cumulative
reader plays the role of the telemetry histograms/counters and the test
drives `SloEngine.tick(now=...)` across simulated hours in microseconds
of wall time — breach, recovery, flapping, the multi-window guard
(a fast-window blip that the slow window refuses to confirm), the rate
floor's boot guard, and the error-ratio kind. The annotation-binding
half checks `@app:slo` / per-query `@slo` parsing against real runtimes
and the surfaces: statistics_report()["slo"], the siddhi_slo_* families,
and GET /slo's payload shape.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError
from siddhi_tpu.telemetry.metrics import N_BUCKETS, bucket_index
from siddhi_tpu.telemetry.slo import (
    BREACHED, OK, Objective, SloEngine, frac_over_threshold)

pytestmark = pytest.mark.smoke

S = "define stream S (symbol string, price float);\n"


class FakeHist:
    """Cumulative (count, buckets) source shaped like Histogram.snapshot."""

    def __init__(self):
        self.buckets = [0] * N_BUCKETS
        self.n = 0

    def observe_ms(self, ms, n=1):
        self.buckets[bucket_index(int(ms * 1e6))] += n
        self.n += n

    def read(self):
        return (self.n, tuple(self.buckets))


def latency_objective(hist, target_ms=10.0, **kw):
    kw.setdefault("quantile", 0.99)
    return Objective("stream:S:p99.ms", "latency", "stream", "S",
                     target=target_ms, reader=hist.read, **kw)


class TestFracOverThreshold:
    def test_empty_is_zero(self):
        assert frac_over_threshold([0] * N_BUCKETS, 0, 10**6) == 0.0

    def test_all_above_and_all_below(self):
        h = FakeHist()
        h.observe_ms(100.0, 50)
        cnt, b = h.read()
        assert frac_over_threshold(list(b), cnt, int(1e6)) == 1.0
        h2 = FakeHist()
        h2.observe_ms(0.5, 50)
        cnt, b = h2.read()
        # 0.5ms observations against a 100ms threshold: nothing above
        assert frac_over_threshold(list(b), cnt, int(100e6)) == 0.0

    def test_interpolates_in_owning_bucket(self):
        # threshold mid-bucket: the owning bucket's mass splits linearly
        h = FakeHist()
        h.observe_ms(1.5, 100)  # bucket (1.024ms, 2.048ms]
        cnt, b = h.read()
        frac = frac_over_threshold(list(b), cnt, int(1.536e6))  # midpoint
        assert 0.3 < frac < 0.7


class TestBurnRateLifecycle:
    def test_breach_recover_flap(self):
        h = FakeHist()
        eng = SloEngine("t", clock=lambda: 0.0)
        o = eng.add(latency_objective(h))
        # healthy traffic
        h.observe_ms(1.0, 100)
        assert eng.tick(now=10.0) == []
        assert o.state == OK
        # sustained badness: 50% over a 1% budget on both windows
        h.observe_ms(100.0, 100)
        evs = eng.tick(now=20.0)
        assert [e["to"] for e in evs] == [BREACHED]
        assert o.state == BREACHED and o.breaches == 1
        assert eng.breaching()
        # windows roll past the incident -> recovery
        h.observe_ms(1.0, 100)
        evs = eng.tick(now=20.0 + 3700.0)
        assert [e["to"] for e in evs] == [OK]
        assert o.recoveries == 1 and not eng.breaching()
        # flap: breach again counts a second breach
        h.observe_ms(100.0, 100)
        evs = eng.tick(now=20.0 + 3720.0)
        assert [e["to"] for e in evs] == [BREACHED]
        assert o.breaches == 2

    def test_slow_window_vetoes_fast_blip(self):
        # an hour of healthy history, then one bad burst: the fast window
        # burns hot but the slow window refuses to confirm -> no breach
        h = FakeHist()
        eng = SloEngine("t", clock=lambda: 0.0)
        o = eng.add(latency_objective(h))
        for i in range(60):  # a good tick per simulated minute
            h.observe_ms(1.0, 100)
            eng.tick(now=(i + 1) * 60.0)
        assert o.state == OK
        h.observe_ms(100.0, 30)  # blip: 30 bad out of 6030 in the hour
        eng.tick(now=3601.0)
        assert o.last_fast["burn_rate"] >= 1.0
        assert o.last_slow["burn_rate"] < 1.0
        assert o.state == OK
        # sustain it: keep the badness flowing until the slow window burns
        for i in range(10):
            h.observe_ms(100.0, 30)
            eng.tick(now=3601.0 + (i + 1) * 60.0)
        assert o.state == BREACHED

    def test_min_samples_gate(self):
        h = FakeHist()
        eng = SloEngine("t", clock=lambda: 0.0)
        o = eng.add(latency_objective(h, min_samples=50))
        h.observe_ms(100.0, 10)  # 100% bad but under the sample floor
        eng.tick(now=5.0)
        assert o.state == OK
        h.observe_ms(100.0, 90)
        eng.tick(now=10.0)
        assert o.state == BREACHED


class TestRateAndErrorKinds:
    def test_rate_floor_boot_guard_then_breach(self):
        count = [0]
        o = Objective("stream:S:min.rate", "rate", "stream", "S",
                      target=100.0, reader=lambda: count[0])
        eng = SloEngine("t", clock=lambda: 0.0)
        eng.add(o)
        # sub-second history: never judged (boot must not read as outage)
        assert eng.tick(now=0.5) == []
        assert o.state == OK
        # healthy: 200 ev/s
        count[0] += 2000
        eng.tick(now=10.0)
        assert o.state == OK
        # throughput collapses on the fast window
        count[0] += 1
        evs = eng.tick(now=310.0)
        assert [e["to"] for e in evs] == [BREACHED]
        assert o.last_fast["rate_eps"] < 100.0
        # and recovers once the floor holds again
        count[0] += 200_000
        evs = eng.tick(now=620.0)
        assert [e["to"] for e in evs] == [OK]

    def test_error_ratio(self):
        bad, total = [0], [0]
        o = Objective("stream:S:error.ratio", "error_ratio", "stream", "S",
                      target=0.01, reader=lambda: (bad[0], total[0]))
        eng = SloEngine("t", clock=lambda: 0.0)
        eng.add(o)
        total[0] = 1000
        eng.tick(now=10.0)
        assert o.state == OK
        bad[0] += 100  # 10% bad against a 1% target on both windows
        total[0] += 100
        eng.tick(now=20.0)
        assert o.state == BREACHED
        assert o.report()["fast"]["burn_rate"] >= 1.0


class TestAnnotationBinding:
    def _rt(self, app, **kw):
        rt = SiddhiManager().create_siddhi_app_runtime(app, **kw)
        rt.start()
        return rt

    def test_app_and_query_annotations_build_objectives(self):
        rt = self._rt(
            "@app:name('SloApp')\n"
            "@app:slo(stream='S', p99.ms='50', min.rate='10', "
            "error.ratio='0.05')\n" + S
            + "@slo(p95.ms='5')\n@info(name='q1') "
            "from S select symbol insert into Out;")
        eng = rt.slo_engine
        assert eng is not None
        ids = {o.id for o in eng.objectives}
        assert ids == {"stream:S:p99.ms", "stream:S:min.rate",
                       "stream:S:error.ratio", "query:q1:p95.ms"}
        rep = rt.statistics_report()["slo"]
        assert set(rep["objectives"]) == ids
        assert rep["breaching"] is False
        rt.shutdown()

    def test_no_annotations_means_no_engine(self):
        rt = self._rt(S + "from S select symbol insert into Out;")
        assert rt.slo_engine is None
        assert "slo" not in rt.statistics_report()
        rt.shutdown()

    def test_windows_and_threshold_elements(self):
        rt = self._rt(
            "@app:slo(stream='S', p99.ms='50', fast.window='60 sec', "
            "slow.window='10 min', burn.threshold='2.0', "
            "min.samples='7')\n" + S
            + "from S select symbol insert into Out;")
        (o,) = rt.slo_engine.objectives
        assert (o.fast_window_s, o.slow_window_s) == (60.0, 600.0)
        assert o.burn_threshold == 2.0 and o.min_samples == 7
        rt.shutdown()

    def test_bad_values_and_empty_annotation_raise(self):
        with pytest.raises(SiddhiAppCreationError):
            self._rt("@app:slo(stream='S', p99.ms='fast')\n" + S
                     + "from S select symbol insert into Out;")
        with pytest.raises(SiddhiAppCreationError):
            self._rt("@app:slo(stream='S')\n" + S
                     + "from S select symbol insert into Out;")

    def test_disabled_telemetry_disables_slo(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TELEMETRY", "0")
        rt = self._rt("@app:slo(stream='S', p99.ms='50')\n" + S
                      + "from S select symbol insert into Out;")
        assert rt.slo_engine is None
        rt.shutdown()

    def test_live_latency_objective_sees_traffic(self):
        rt = self._rt("@app:slo(stream='S', p99.ms='10000')\n" + S
                      + "from S select symbol insert into Out;")
        h = rt.get_input_handler("S")
        for i in range(20):
            h.send(("A", float(i)))
        rt.flush()
        eng = rt.slo_engine
        eng.tick()
        (o,) = eng.objectives
        assert o.last_fast["samples"] > 0
        assert o.state == OK  # 10s p99 target: nothing breaches on CPU
        rt.shutdown()

    def test_prometheus_families_render(self):
        from siddhi_tpu.telemetry import prometheus
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('PromSlo')\n"
            "@app:slo(stream='S', p99.ms='50')\n" + S
            + "from S select symbol insert into Out;")
        rt.start()
        rt.get_input_handler("S").send(("A", 1.0))
        rt.flush()
        rt.slo_engine.tick()
        body = prometheus.render_manager(mgr)
        assert prometheus.validate_exposition(body) == []
        for fam in ("siddhi_slo_compliance_ratio", "siddhi_slo_burn_rate",
                    "siddhi_slo_breaches_total", "siddhi_build_info",
                    "siddhi_app_uptime_seconds"):
            assert fam in body, fam
        rt.shutdown()
