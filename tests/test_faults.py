"""Fault-injection harness + sink robustness tests.

The acceptance bar: a seeded flaky sink (fail-Nth + fail-for-duration)
delivers every event via retry or dead-letters it to the ErrorStore — ZERO
silent drops — with retry/dead-letter counts visible in statistics_report().
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.extension.registry import ExtensionKind
from siddhi_tpu.io.sink import Sink
from siddhi_tpu.io.source import ConnectionUnavailableException
from siddhi_tpu.state.error_store import InMemoryErrorStore
from siddhi_tpu.util.faults import (
    FaultPlan,
    InjectedFault,
    apply_fault_spec,
    inject,
    parse_fault_spec,
    restore,
)

pytestmark = pytest.mark.smoke


class CaptureSink(Sink):
    """Test transport: records every published payload on the class."""

    def init(self, stream_definition, options, mapper, ctx) -> None:
        super().init(stream_definition, options, mapper, ctx)
        self.captured = []

    def publish(self, payload) -> None:
        self.captured.append(payload)


def _build(app_body, *, max_retries="3", on_error="WAIT"):
    mgr = SiddhiManager()
    mgr.set_error_store(InMemoryErrorStore())
    mgr.registry.register(ExtensionKind.SINK, "", "capture", CaptureSink)
    app = ("@app:name('FaultApp')\n"
           "define stream S (v long);\n"
           f"@sink(type='capture', on.error='{on_error}', "
           f"max.retries='{max_retries}')\n"
           "define stream Out (v long);\n" + app_body)
    rt = mgr.create_siddhi_app_runtime(app, batch_size=4)
    rt.start()
    sink = rt.sinks[0]
    # virtual clock: backoff sleeps advance it instead of wall time
    clk = {"t": 0.0}
    sink._sleep = lambda s: clk.__setitem__("t", clk["t"] + s)
    return mgr, rt, sink, clk


# --------------------------------------------------------------------------- #
# FaultPlan scheduling
# --------------------------------------------------------------------------- #


class TestFaultPlan:
    def test_fail_nth(self):
        plan = FaultPlan(nth=(2, 4), exc=InjectedFault)
        hits = []
        for i in range(5):
            try:
                plan.check()
                hits.append(i)
            except InjectedFault:
                pass
        assert hits == [0, 2, 4]  # calls 2 and 4 (1-based) failed
        assert plan.fired == 2 and plan.calls == 5

    def test_fail_for_duration_virtual_clock(self):
        clk = {"t": 0.0}
        plan = FaultPlan(after=2, for_s=1.0, exc=InjectedFault,
                         clock=lambda: clk["t"])
        plan.check()
        plan.check()  # calls 1-2 fine
        with pytest.raises(InjectedFault):
            plan.check()  # window opens at call 3
        clk["t"] = 0.5
        with pytest.raises(InjectedFault):
            plan.check()  # still inside the window
        clk["t"] = 1.5
        plan.check()  # window expired

    def test_probability_is_seeded_deterministic(self):
        def run(seed):
            plan = FaultPlan(p=0.3, seed=seed, exc=InjectedFault)
            out = []
            for _ in range(50):
                try:
                    plan.check()
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        a, b = run(7), run(7)
        assert a == b and sum(a) > 0  # same seed -> identical schedule
        assert run(8) != a  # different seed -> different schedule

    def test_inject_and_restore(self):
        store = InMemoryErrorStore()
        plan = inject(store, "discard", FaultPlan(nth=(1,),
                                                  exc=InjectedFault))
        with pytest.raises(InjectedFault):
            store.discard(1)
        store.discard(1)  # call 2 passes through
        restore(store, "discard")
        store.discard(1)
        assert plan.calls == 2  # restored method no longer consults the plan


# --------------------------------------------------------------------------- #
# spec grammar
# --------------------------------------------------------------------------- #


class TestFaultSpec:
    def test_parse(self):
        plans = parse_fault_spec(
            "sink:nth=3+7,exc=connection;store:p=0.01,seed=7;"
            "source:after=10,for=0.5")
        assert plans["sink"].nth == frozenset({3, 7})
        assert plans["sink"].exc is ConnectionUnavailableException
        assert plans["store"].p == 0.01
        assert plans["source"].after == 10 and plans["source"].for_s == 0.5

    @pytest.mark.parametrize("bad", [
        "gateway:nth=1",          # unknown target
        "sink:nth",               # param without value
        "sink:warp=9",            # unknown param
        "sink:exc=kaboom",        # unknown exception name
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)

    def test_apply_to_runtime_via_env(self, monkeypatch):
        mgr, rt, sink, _clk = _build("from S select v insert into Out;")
        monkeypatch.setenv("SIDDHI_FAULT_SPEC", "sink:nth=1,exc=error")
        plans = apply_fault_spec(rt)
        rt.get_input_handler("S").send((1,))
        rt.flush()  # injected failure -> LOG? no: WAIT + non-connection
        assert plans["sink"].fired == 1
        rt.shutdown()

    def test_no_spec_is_noop(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_FAULT_SPEC", raising=False)
        mgr, rt, _sink, _clk = _build("from S select v insert into Out;")
        assert apply_fault_spec(rt) == {}
        rt.shutdown()


# --------------------------------------------------------------------------- #
# flaky sink: the acceptance-criterion scenario
# --------------------------------------------------------------------------- #


class TestFlakySink:
    def test_fail_nth_and_duration_zero_silent_drops(self):
        """Seeded fail-Nth + fail-for-duration on a WAIT sink: every event
        is delivered via retry or dead-lettered — none vanish."""
        mgr, rt, sink, clk = _build("from S select v insert into Out;")
        plan = inject(sink, "publish", FaultPlan(
            nth=(2,), after=6, for_s=0.04,
            exc=ConnectionUnavailableException, clock=lambda: clk["t"]))
        h = rt.get_input_handler("S")
        n = 12
        for i in range(n):
            h.send((i,))
            rt.flush()
        rep = rt.statistics_report()
        delivered = {p[0] for p in sink.captured}
        dead = {row[0] for e in mgr.error_store.load("FaultApp")
                for _ts, row in e.events}
        assert delivered | dead == set(range(n))  # zero silent drops
        assert rep["sink_retries"]["Out"] > 0
        assert rep["sink_dropped"] == {}
        assert plan.fired > 0
        rt.shutdown()

    def test_exhausted_retries_dead_letter_then_replay(self):
        """A fault outlasting every backoff retry dead-letters the in-flight
        remainder as ONE replayable entry; replay after the fault clears
        delivers everything."""
        mgr, rt, sink, clk = _build("from S select v insert into Out;",
                                    max_retries="2")
        inject(sink, "publish", FaultPlan(
            for_s=1e9, exc=ConnectionUnavailableException,
            clock=lambda: clk["t"]))
        h = rt.get_input_handler("S")
        h.send_batch([(i,) for i in range(4)])  # one delivery batch
        rt.flush()
        rep = rt.statistics_report()
        assert sink.captured == []
        assert rep["sink_dead_letters"]["Out"] == 4
        assert rep["sink_retries"]["Out"] == 2  # max.retries, then give up
        entries = mgr.error_store.load("FaultApp", "Out")
        assert len(entries) == 1  # the whole exhausted batch, one entry
        assert [row for _ts, row in entries[0].events] == \
            [(i,) for i in range(4)]

        restore(sink, "publish")  # fault clears
        mgr.error_store.replay(entries[0], rt)
        rt.flush()
        assert sorted(p[0] for p in sink.captured) == list(range(4))
        assert mgr.error_store.load("FaultApp") == []
        rt.shutdown()

    def test_on_error_log_counts_drops(self):
        """Default LOG policy: a non-connection failure logs + counts the
        drop and the REST of the batch still publishes (no mid-batch
        abandonment)."""
        mgr, rt, sink, _clk = _build("from S select v insert into Out;",
                                     on_error="LOG")
        inject(sink, "publish", FaultPlan(nth=(2,), exc=InjectedFault))
        h = rt.get_input_handler("S")
        h.send_batch([(i,) for i in range(4)])
        rt.flush()
        assert sorted(p[0] for p in sink.captured) == [0, 2, 3]
        assert rt.statistics_report()["sink_dropped"]["Out"] == 1
        rt.shutdown()

    def test_on_error_stream_routes_to_fault_stream(self):
        """on.error=STREAM: the failed event + error message lands on the
        stream's `!fault` stream (requires @OnError(action='STREAM'))."""
        mgr = SiddhiManager()
        mgr.registry.register(ExtensionKind.SINK, "", "capture", CaptureSink)
        app = ("@app:name('FaultApp2')\n"
               "define stream S (v long);\n"
               "@sink(type='capture', on.error='STREAM')\n"
               "@OnError(action='STREAM')\n"
               "define stream Out (v long);\n"
               "from S select v insert into Out;")
        rt = mgr.create_siddhi_app_runtime(app, batch_size=4)
        rt.start()
        sink = rt.sinks[0]
        inject(sink, "publish", FaultPlan(nth=(1,), exc=InjectedFault))
        faulted = []
        rt.add_callback("!Out", lambda evs: faulted.extend(evs))
        rt.get_input_handler("S").send((7,))
        rt.flush()
        assert [p[0] for p in sink.captured] == []
        assert len(faulted) == 1
        assert faulted[0].data[0] == 7
        assert "injected fault" in faulted[0].data[1]
        rt.shutdown()

    def test_bad_on_error_rejected(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        mgr = SiddhiManager()
        mgr.registry.register(ExtensionKind.SINK, "", "capture", CaptureSink)
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime(
                "define stream S (v long);\n"
                "@sink(type='capture', on.error='EXPLODE')\n"
                "define stream Out (v long);\n"
                "from S select v insert into Out;")


class TestSourceFaults:
    def test_injected_source_fault_then_recovers(self):
        """Faults inject into Source.on_payload: the scheduled call raises
        to the transport, later payloads flow normally."""
        from siddhi_tpu.io.broker import InMemoryBroker
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('SrcApp')\n"
            "@source(type='inMemory', topic='ft')\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        # inject BEFORE start(): transports capture the on_payload callback
        # when they connect (apply_fault_spec documents the same ordering)
        plan = inject(rt.sources[0], "on_payload",
                      FaultPlan(nth=(1,), exc=InjectedFault))
        rt.start()
        with pytest.raises(InjectedFault):
            InMemoryBroker.publish("ft", (1,))
        InMemoryBroker.publish("ft", (2,))
        rt.flush()
        assert got == [(2,)]
        assert plan.fired == 1
        rt.shutdown()

    def test_connect_retries_are_counted(self):
        """A flapping transport's reconnect attempts surface as
        source_retries in statistics_report()."""
        from siddhi_tpu.io.source import ConnectionUnavailableException
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('SrcApp2')\n"
            "@source(type='inMemory', topic='ft2')\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        source = rt.sources[0]
        inject(source, "connect", FaultPlan(
            nth=(1, 2), exc=ConnectionUnavailableException))
        source.connect_with_retry(sleep=lambda _s: None)  # 3rd attempt wins
        assert rt.statistics_report()["source_retries"]["S"] == 2
        rt.shutdown()
