"""Pattern & sequence NFA tests.

Mirrors the reference suites (modules/siddhi-core/src/test/java/io/siddhi/core/
query/pattern/ — PatternTestCase, EveryPatternTestCase, AbsentPatternTestCase,
CountPatternTestCase, LogicalPatternTestCase — and query/sequence/).
"""

import pytest

from siddhi_tpu import SiddhiManager

TWO = ("define stream S1 (symbol string, price float);\n"
       "define stream S2 (symbol string, price float);\n")


def make(app, batch_size=8):
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(app, batch_size=batch_size)
    got = []
    rt.add_callback("OutStream", lambda evs: got.extend(e.data for e in evs))
    rt.start()
    return rt, got


class TestBasicPattern:
    def test_two_stream_pattern(self):
        app = (TWO +
               "from e1=S1[price > 20.0] -> e2=S2[price > 30.0] "
               "select e1.symbol as s1, e2.symbol as s2 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("IBM", 25.0))
        rt.flush()
        rt.get_input_handler("S2").send(("WSO2", 35.0))
        rt.flush()
        assert got == [("IBM", "WSO2")]

    def test_non_every_matches_once(self):
        app = (TWO +
               "from e1=S1[price > 20.0] -> e2=S2[price > 30.0] "
               "select e1.price as p1, e2.price as p2 insert into OutStream;")
        rt, got = make(app)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("A", 25.0)); rt.flush()
        s1.send(("B", 26.0)); rt.flush()  # start state consumed: ignored
        s2.send(("C", 35.0)); rt.flush()
        s2.send(("D", 36.0)); rt.flush()  # pattern done: ignored
        assert got == [(25.0, 35.0)]

    def test_every_rearms(self):
        app = (TWO +
               "from every e1=S1[price > 20.0] -> e2=S2[price > 30.0] "
               "select e1.price as p1, e2.price as p2 insert into OutStream;")
        rt, got = make(app)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("A", 25.0)); rt.flush()
        s1.send(("B", 26.0)); rt.flush()
        s2.send(("C", 35.0)); rt.flush()
        # both pendings complete on the first qualifying S2
        assert sorted(got) == [(25.0, 35.0), (26.0, 35.0)]
        s1.send(("E", 27.0)); rt.flush()
        s2.send(("F", 37.0)); rt.flush()
        assert sorted(got) == [(25.0, 35.0), (26.0, 35.0), (27.0, 37.0)]

    def test_condition_referencing_earlier_event(self):
        app = (TWO +
               "from every e1=S1 -> e2=S2[price > e1.price] "
               "select e1.price as p1, e2.price as p2 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 50.0)); rt.flush()
        rt.get_input_handler("S2").send(("B", 40.0)); rt.flush()  # not > 50
        assert got == []
        rt.get_input_handler("S2").send(("C", 60.0)); rt.flush()
        assert got == [(50.0, 60.0)]

    def test_intra_batch_chain(self):
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v == 1] -> e2=S[v == 2] "
               "select e1.k as k1, e2.k as k2 insert into OutStream;")
        rt, got = make(app, batch_size=8)
        h = rt.get_input_handler("S")
        h.send(("a", 1))
        h.send(("b", 2))  # same micro-batch: chain must still complete
        rt.flush()
        assert got == [("a", "b")]

    def test_three_stage(self):
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v == 1] -> e2=S[v == 2] -> e3=S[v == 3] "
               "select e1.k as k1, e2.k as k2, e3.k as k3 insert into OutStream;")
        rt, got = make(app)
        h = rt.get_input_handler("S")
        for row in [("a", 1), ("x", 9), ("b", 2), ("c", 3)]:
            h.send(row)
            rt.flush()
        assert got == [("a", "b", "c")]


class TestWithin:
    def test_within_expires_partial(self):
        # @app:playback: virtual clock driven by event timestamps (reference:
        # PlaybackTestCase pattern for time-sensitive tests)
        app = ("@app:playback\n" + TWO +
               "from every e1=S1 -> e2=S2 within 1 sec "
               "select e1.price as p1, e2.price as p2 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 1.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 2.0), timestamp=5000)
        rt.flush()
        assert got == []  # partial expired (4s > 1s)

    def test_within_allows_fast_match(self):
        app = ("@app:playback\n" + TWO +
               "from every e1=S1 -> e2=S2 within 10 sec "
               "select e1.price as p1, e2.price as p2 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 1.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 2.0), timestamp=5000)
        rt.flush()
        assert got == [(1.0, 2.0)]


class TestLogical:
    def test_and_pattern(self):
        app = (TWO +
               "define stream S3 (symbol string, price float);\n"
               "from e1=S1 -> e2=S2 and e3=S3 "
               "select e1.price as p1, e2.price as p2, e3.price as p3 "
               "insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 1.0)); rt.flush()
        rt.get_input_handler("S3").send(("C", 3.0)); rt.flush()
        assert got == []  # and needs both legs
        rt.get_input_handler("S2").send(("B", 2.0)); rt.flush()
        assert got == [(1.0, 2.0, 3.0)]

    def test_or_pattern(self):
        app = (TWO +
               "define stream S3 (symbol string, price float);\n"
               "from e1=S1 -> e2=S2 or e3=S3 "
               "select e1.price as p1, e2.price as p2, e3.price as p3 "
               "insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 1.0)); rt.flush()
        rt.get_input_handler("S3").send(("C", 3.0)); rt.flush()
        # or completes on either leg; missing leg is null (numeric -> 0.0)
        assert got == [(1.0, 0.0, 3.0)]

    def test_or_is_null(self):
        app = (TWO +
               "define stream S3 (symbol string, price float);\n"
               "from e1=S1 -> e2=S2 or e3=S3 "
               "select e1.symbol as s, e2.symbol as s2 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 1.0)); rt.flush()
        rt.get_input_handler("S3").send(("C", 3.0)); rt.flush()
        assert got == [("A", None)]  # e2 leg missing -> null string


class TestCount:
    def test_exact_count(self):
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v > 0]<2> -> e2=S[v == 9] "
               "select e1[0].k as k0, e1[1].k as k1, e2.k as k2 "
               "insert into OutStream;")
        rt, got = make(app)
        h = rt.get_input_handler("S")
        for row in [("a", 1), ("b", 2), ("x", 9)]:
            h.send(row); rt.flush()
        assert ("a", "b", "x") in got


class TestAbsent:
    def test_absent_detected(self):
        app = ("@app:playback\n" + TWO +
               "from every e1=S1 -> not S2 for 1 sec "
               "select e1.price as p1 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 5.0), timestamp=1000)
        rt.flush()
        rt.heartbeat(now=2500)  # 1.5s later, no S2: absence fires
        assert got == [(5.0,)]

    def test_absent_killed_by_event(self):
        app = ("@app:playback\n" + TWO +
               "from every e1=S1 -> not S2 for 1 sec "
               "select e1.price as p1 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 5.0), timestamp=1000)
        rt.flush()
        rt.get_input_handler("S2").send(("B", 9.0), timestamp=1500)
        rt.flush()
        rt.heartbeat(now=2500)
        assert got == []


class TestSequence:
    def test_strict_sequence_match(self):
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v == 1], e2=S[v == 2] "
               "select e1.k as k1, e2.k as k2 insert into OutStream;")
        rt, got = make(app)
        h = rt.get_input_handler("S")
        h.send(("a", 1)); rt.flush()
        h.send(("b", 2)); rt.flush()
        assert got == [("a", "b")]

    def test_strict_sequence_broken(self):
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v == 1], e2=S[v == 2] "
               "select e1.k as k1, e2.k as k2 insert into OutStream;")
        rt, got = make(app)
        h = rt.get_input_handler("S")
        h.send(("a", 1)); rt.flush()
        h.send(("x", 7)); rt.flush()  # intervening event kills the partial
        h.send(("b", 2)); rt.flush()
        assert got == []


class TestMultiStreamSequence:
    """Sequences across DIFFERENT streams (reference: query/sequence/
    SequenceTestCase — e1=Stream1, e2=Stream2): strict contiguity over the
    merged send-order arrival stream."""

    APP = (TWO +
           "from every e1=S1[price > 20.0], e2=S2[price > 30.0] "
           "select e1.symbol as s1, e2.symbol as s2 insert into OutStream;")

    def test_cross_stream_match(self):
        rt, got = make(self.APP)
        rt.get_input_handler("S1").send(("IBM", 25.0))
        rt.get_input_handler("S2").send(("WSO2", 35.0))
        rt.flush()
        assert got == [("IBM", "WSO2")]

    def test_intervening_event_breaks(self):
        rt, got = make(self.APP)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("IBM", 25.0))
        s1.send(("DOX", 26.0))   # S1 event intervenes: kills the partial,
        s2.send(("WSO2", 35.0))  # ...but itself starts a new partial
        rt.flush()
        assert got == [("DOX", "WSO2")]

    def test_non_matching_next_kills(self):
        rt, got = make(self.APP)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("IBM", 25.0))
        s2.send(("BAD", 5.0))    # next arrival fails e2's filter: killed
        s2.send(("WSO2", 35.0))
        rt.flush()
        assert got == []

    def test_every_rearms_across_streams(self):
        rt, got = make(self.APP)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("A", 21.0)); s2.send(("B", 31.0))
        s1.send(("C", 22.0)); s2.send(("D", 32.0))
        rt.flush()
        assert got == [("A", "B"), ("C", "D")]

    def test_interleave_within_one_flush(self):
        # true per-event interleave inside a single micro-batch window —
        # per-junction batching alone would see S1:[A,C] then S2:[B,D]
        rt, got = make(self.APP, batch_size=16)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("A", 25.0))
        s2.send(("B", 35.0))
        s2.send(("X", 36.0))   # consecutive S2: no live partial, ignored
        s1.send(("C", 27.0))
        s2.send(("D", 37.0))
        rt.flush()
        assert got == [("A", "B"), ("C", "D")]

    def test_three_streams(self):
        app = (TWO +
               "define stream S3 (symbol string, price float);\n"
               "from every e1=S1[price > 20.0], e2=S2[price > 30.0], "
               "e3=S3[price > 40.0] "
               "select e1.symbol as s1, e2.symbol as s2, e3.symbol as s3 "
               "insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 25.0))
        rt.get_input_handler("S2").send(("B", 35.0))
        rt.get_input_handler("S3").send(("C", 45.0))
        rt.flush()
        assert got == [("A", "B", "C")]

    def test_condition_referencing_earlier_stream(self):
        app = (TWO +
               "from every e1=S1[price > 20.0], e2=S2[price > e1.price] "
               "select e1.price as p1, e2.price as p2 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 25.0))
        rt.get_input_handler("S2").send(("B", 26.0))
        rt.flush()
        assert got == [(25.0, 26.0)]


class TestLogicalSequences:
    """Logical (and/or) positions inside sequences — the next TWO events
    must satisfy the two legs, in either order (reference: query/sequence/
    LogicalSequenceTestCase)."""

    APP = (TWO +
           "define stream S3 (symbol string, price float);\n"
           "from every e1=S1[price > 20.0], e2=S2[price > 30.0] "
           "and e3=S3[price > 40.0] "
           "select e1.symbol as s1, e2.symbol as s2, e3.symbol as s3 "
           "insert into OutStream;")

    def _handlers(self, rt):
        return (rt.get_input_handler("S1"), rt.get_input_handler("S2"),
                rt.get_input_handler("S3"))

    def test_and_completes_in_either_order(self):
        rt, got = make(self.APP)
        s1, s2, s3 = self._handlers(rt)
        s1.send(("A", 25.0)); s2.send(("B", 35.0)); s3.send(("C", 45.0))
        rt.flush()
        assert got == [("A", "B", "C")]
        del got[:]
        s1.send(("D", 25.0)); s3.send(("E", 45.0)); s2.send(("F", 35.0))
        rt.flush()
        assert got == [("D", "F", "E")]

    def test_non_matching_intervening_event_kills(self):
        rt, got = make(self.APP)
        s1, s2, s3 = self._handlers(rt)
        s1.send(("A", 25.0))
        s2.send(("X", 5.0))   # fails BOTH remaining legs: partial killed
        s2.send(("B", 35.0)); s3.send(("C", 45.0))
        rt.flush()
        assert got == []

    def test_or_completes_on_first_matching_leg(self):
        app = (TWO +
               "from every e1=S1[price > 20.0], e2=S2[price > 30.0] "
               "or e3=S1[price > 90.0] "
               "select e1.symbol as s1, e2.symbol as s2 "
               "insert into OutStream;")
        rt, got = make(app)
        s1, s2 = rt.get_input_handler("S1"), rt.get_input_handler("S2")
        s1.send(("A", 25.0)); s2.send(("B", 35.0))
        rt.flush()
        assert got == [("A", "B")]


class TestLogicalPatternWithFilters:
    def test_and_pattern_leg_filters_evaluate_on_arrivals(self):
        # regression: logical positions capture their own legs in the pending
        # table; leg filters must evaluate on the ARRIVING event, not the
        # (empty) capture
        app = (TWO +
               "define stream S3 (symbol string, price float);\n"
               "from e1=S1[price > 20.0] -> e2=S2[price > 30.0] "
               "and e3=S3[price > 40.0] "
               "select e1.symbol as s1, e2.symbol as s2, e3.symbol as s3 "
               "insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 25.0)); rt.flush()
        rt.get_input_handler("S2").send(("B", 35.0)); rt.flush()
        rt.get_input_handler("S3").send(("C", 45.0)); rt.flush()
        assert got == [("A", "B", "C")]

    def test_and_pattern_filter_rejects(self):
        app = (TWO +
               "define stream S3 (symbol string, price float);\n"
               "from e1=S1[price > 20.0] -> e2=S2[price > 30.0] "
               "and e3=S3[price > 40.0] "
               "select e1.symbol as s1 insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("S1").send(("A", 25.0)); rt.flush()
        rt.get_input_handler("S2").send(("B", 5.0)); rt.flush()   # fails
        rt.get_input_handler("S3").send(("C", 45.0)); rt.flush()
        assert got == []


class TestLogicalInBatchOrdering:
    def test_and_pattern_opposite_order_single_batch(self):
        # both legs inside ONE batch, reversed relative to leg order: the
        # reference's logical AND accepts the events in either order
        app = (TWO +
               "define stream S3 (symbol string, price float);\n"
               "from e1=S1[price > 20.0] -> e2=S2[price > 30.0] "
               "and e3=S3[price > 40.0] "
               "select e2.symbol as s2, e3.symbol as s3 "
               "insert into OutStream;")
        rt, got = make(app, batch_size=16)
        rt.get_input_handler("S1").send(("A", 25.0))
        rt.get_input_handler("S3").send(("C", 45.0))  # e3 BEFORE e2
        rt.get_input_handler("S2").send(("B", 35.0))
        rt.flush()
        assert got == [("B", "C")]

    def test_sequence_breaker_after_first_leg_same_batch(self):
        # A, B(matches e2), X(breaker) all in one batch: the partial must die
        app = (TWO +
               "define stream S3 (symbol string, price float);\n"
               "from every e1=S1[price > 20.0], e2=S2[price > 30.0] "
               "and e3=S3[price > 40.0] "
               "select e1.symbol as s1 insert into OutStream;")
        rt, got = make(app, batch_size=16)
        s1, s2, s3 = (rt.get_input_handler(s) for s in ("S1", "S2", "S3"))
        s1.send(("A", 25.0))
        s2.send(("B", 35.0))   # matches e2
        s2.send(("X", 5.0))    # next arrival fails remaining leg: breaker
        s3.send(("C", 45.0))
        rt.flush()
        assert got == []


class TestUnboundedCounts:
    """Unbounded counts are expanded to min +
    config.pattern_unbounded_count_extra positions with a plan-time
    warning — documented divergence from the reference's unbounded
    CountPreStateProcessor (PARITY.md "Known gaps")."""

    def test_cap_warns_and_matches_up_to_bound(self):
        import warnings as _w

        from siddhi_tpu.core import dtypes as _dt
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v > 0]<2:> -> e2=S[v == 0] "
               "select e1[0].k as k0, e1[last].k as kl, e2.k as k2 "
               "insert into OutStream;")
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            rt, got = make(app)
        assert any("unbounded pattern count" in str(r.message) for r in rec)
        h = rt.get_input_handler("S")
        n = 2 + _dt.config.pattern_unbounded_count_extra + 10  # 20 events
        for i in range(n):
            h.send((f"a{i}", 1)); rt.flush()
        h.send(("z", 0)); rt.flush()
        # the capped expansion captured at most lo+extra occurrences:
        # e1[last] resolves to the newest CAPTURED one, not the 20th
        assert got, "capped count still matches"
        cap = 2 + _dt.config.pattern_unbounded_count_extra
        # the REAL contract: each entry captures at most `cap` consecutive
        # occurrences, so e1[last] sits within cap of that entry's e1[0]
        for k0, kl, _ in got:
            assert int(kl[1:]) - int(k0[1:]) < cap, (k0, kl)
        # deep captures beyond the minimum ARE used (near the cap)
        assert any(int(kl[1:]) - int(k0[1:]) >= cap - 2
                   for k0, kl, _ in got)

    def test_sequence_plus_matches(self):
        # sequence regex `+`: one-or-more, greedy up to the cap
        import warnings as _w
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v > 0]+, e2=S[v == 0] "
               "select e1[0].k as k0, e1[last].k as kl, e2.k as k2 "
               "insert into OutStream;")
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt, got = make(app)
        h = rt.get_input_handler("S")
        for row in [("a", 1), ("b", 2), ("z", 0)]:
            h.send(row); rt.flush()
        assert ("a", "b", "z") in got

    def test_sequence_star_allows_zero(self):
        import warnings as _w
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v > 5]*, e2=S[v == 0] "
               "select e2.k as k2 insert into OutStream;")
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            rt, got = make(app)
        h = rt.get_input_handler("S")
        h.send(("z", 0)); rt.flush()  # zero e1 occurrences: still matches
        assert ("z",) in got

    def test_sequence_question_optional(self):
        app = ("define stream S (k string, v int);\n"
               "from every e1=S[v > 5]?, e2=S[v == 0] "
               "select e2.k as k2 insert into OutStream;")
        rt, got = make(app)
        h = rt.get_input_handler("S")
        h.send(("z", 0)); rt.flush()
        assert ("z",) in got
        h.send(("a", 9)); rt.flush()
        h.send(("y", 0)); rt.flush()
        assert ("y",) in got
