"""`define function` script tests (reference: core/function/Script.java,
query/extension/ script-function test cases — here with the python/jax engine)."""

import pytest

from siddhi_tpu import SiddhiManager

S = "define stream S (symbol string, price double, volume long);\n"


def build(app, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=batch_size)
    rt.start()
    return rt


class TestPythonScriptFunctions:
    def test_expression_body(self):
        rt = build(
            "define function sq[python] return double { args[0] * args[0] };\n"
            + S +
            "@info(name='q') from S select symbol, sq(price) as p2 insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        rt.get_input_handler("S").send(("A", 3.0, 1))
        rt.flush()
        assert got[0].data == ("A", pytest.approx(9.0))

    def test_statement_body_with_jnp(self):
        rt = build(
            "define function clip10[jax] return double {\n"
            "  x = jnp.minimum(args[0], 10.0)\n"
            "  return jnp.maximum(x, 0.0)\n"
            "};\n" + S +
            "@info(name='q') from S select clip10(price) as c insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        h.send(("A", 25.0, 1))
        h.send(("B", -5.0, 1))
        h.send(("C", 7.5, 1))
        rt.flush()
        assert [e.data[0] for e in got] == [
            pytest.approx(10.0), pytest.approx(0.0), pytest.approx(7.5)]

    def test_two_args_and_filter_use(self):
        rt = build(
            "define function addmul[python] return double { (args[0] + args[1]) * 2.0 };\n"
            + S +
            "@info(name='q') from S[addmul(price, volume) > 20.0] "
            "select symbol insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        h.send(("A", 9.0, 2))   # (9+2)*2 = 22 > 20
        h.send(("B", 1.0, 2))   # 6 < 20
        rt.flush()
        assert [e.data[0] for e in got] == ["A"]

    def test_unknown_language_rejected(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError, match="script engine"):
            build("define function f[ruby] return double { args[0] };\n" + S
                  + "from S select f(price) as p insert into Out;")

    def test_function_scoped_per_app(self):
        manager = SiddhiManager()
        rt1 = manager.create_siddhi_app_runtime(
            "@app:name('a1')\n"
            "define function g[python] return double { args[0] + 1.0 };\n"
            + S + "from S select g(price) as p insert into Out;")
        # second app on the SAME manager must not see g
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError, match="no function extension"):
            manager.create_siddhi_app_runtime(
                "@app:name('a2')\n" + S
                + "from S select g(price) as p insert into Out;")


class TestCustomExtensionRegistration:
    def test_set_extension_scalar_function(self):
        import jax.numpy as jnp

        from siddhi_tpu.extension.registry import ExtensionKind
        from siddhi_tpu.ops.expr_compile import ScalarFunction
        from siddhi_tpu.query_api.definition import AttributeType

        manager = SiddhiManager()
        manager.set_extension(
            "custom:double", ScalarFunction(
                make=lambda arg_types: (lambda x: x * 2, AttributeType.DOUBLE)),
            kind=ExtensionKind.FUNCTION)
        rt = manager.create_siddhi_app_runtime(
            S + "@info(name='q') from S select custom:double(price) as d "
            "insert into Out;")
        rt.start()
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        rt.get_input_handler("S").send(("A", 4.0, 1))
        rt.flush()
        assert got[0].data[0] == pytest.approx(8.0)
