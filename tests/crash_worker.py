"""Subprocess worker for tests/test_crash_recovery.py.

Driven line-by-line over stdin so the parent controls EXACTLY which events
were accepted before it delivers SIGKILL: the worker acknowledges every
command and then blocks on the next read, so a kill issued after "OK n" can
never race an in-flight send. Commands:

    send <i>    send event i (deterministic value, original timestamp
                1000+i), flush, reply "OK <i>"
    persist     persist to the filesystem store, reply "PERSISTED <rev>"
    recover     restore last revision + WAL replay, reply
                "RECOVERED <rev> <n_replayed>"
    result      flush, reply "RESULT <count> <sum>" (last Out emission)
    stats       reply "STATS <recoveries> <wal_replayed>"
    exit        clean shutdown, reply "BYE"
"""

import os
import sys


def value(i: int) -> int:
    return (i * 7 + 3) % 101


WINDOW = 8

APP = ("@app:name('CrashApp')\n"
       "define stream S (k string, v long);\n"
       "@info(name='q') from S#window.length(8) "
       "select count() as c, sum(v) as s insert into Out;")


def main() -> None:
    base = sys.argv[1]
    # env-var platform overrides are not enough in some images (see
    # tests/conftest.py) — force CPU through jax.config like the suite does
    from siddhi_tpu.util.platform import force_cpu_platform
    force_cpu_platform(1)
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.state.persistence import FileSystemPersistenceStore

    mgr = SiddhiManager()
    mgr.set_persistence_store(
        FileSystemPersistenceStore(os.path.join(base, "snap")))
    rt = mgr.create_siddhi_app_runtime(
        APP, batch_size=4, wal_dir=os.path.join(base, "wal"))
    out = []
    rt.add_callback("Out", lambda evs: out.extend(tuple(e.data) for e in evs))
    rt.start()
    h = rt.get_input_handler("S")
    print("READY", flush=True)
    for line in sys.stdin:
        cmd, *args = line.split()
        if cmd == "send":
            i = int(args[0])
            h.send(("k", value(i)), timestamp=1_000 + i)
            rt.flush()
            print(f"OK {i}", flush=True)
        elif cmd == "persist":
            print(f"PERSISTED {rt.persist()}", flush=True)
        elif cmd == "recover":
            res = rt.recover()
            print(f"RECOVERED {res['revision']} {res['wal_replayed']}",
                  flush=True)
        elif cmd == "result":
            rt.flush()
            c, s = out[-1]
            print(f"RESULT {c} {s}", flush=True)
        elif cmd == "stats":
            rep = rt.statistics_report()["recovery"]
            print(f"STATS {rep['recoveries']} {rep['wal_replayed']}",
                  flush=True)
        elif cmd == "exit":
            rt.shutdown()
            print("BYE", flush=True)
            return


if __name__ == "__main__":
    main()
