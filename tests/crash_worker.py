"""Subprocess worker for tests/test_crash_recovery.py.

Driven line-by-line over stdin so the parent controls EXACTLY which events
were accepted before it delivers SIGKILL: the worker acknowledges every
command and then blocks on the next read, so a kill issued after "OK n" can
never race an in-flight send. Commands:

    send <i>    send event i (deterministic value, original timestamp
                1000+i), flush, reply "OK <i>"
    persist     persist to the filesystem store, reply "PERSISTED <rev>"
    recover     restore last revision + WAL replay, reply
                "RECOVERED <rev> <n_replayed>"
    upgrade     blue-green hot-swap to APP_V2 (adds a 'mirror' query), reply
                "UPGRADED <classification>" — with SIDDHI_UPGRADE_CRASH set
                the process SIGKILLs itself at the seeded point instead
    result      flush, reply "RESULT <count> <sum>" (last Out emission)
    stats       reply "STATS <recoveries> <wal_replayed>"
    exit        clean shutdown, reply "BYE"

Every command after an upgrade re-resolves the runtime through
``mgr.runtimes`` — a committed swap replaces the registered runtime, and the
migrated "Out" callback keeps feeding the same ``out`` list across versions.
"""

import os
import sys


def value(i: int) -> int:
    return (i * 7 + 3) % 101


WINDOW = 8

APP = ("@app:name('CrashApp')\n"
       "define stream S (k string, v long);\n"
       "@info(name='q') from S#window.length(8) "
       "select count() as c, sum(v) as s insert into Out;")

# v2 ADDS a query (SL305, state-compatible): the upgrade must carry q's
# window state across and keep the Out stream byte-identical to v1
APP_V2 = ("@app:name('CrashApp')\n"
          "define stream S (k string, v long);\n"
          "@info(name='q') from S#window.length(8) "
          "select count() as c, sum(v) as s insert into Out;\n"
          "@info(name='mirror') from S select k, v insert into Mirror;")


def main() -> None:
    base = sys.argv[1]
    # env-var platform overrides are not enough in some images (see
    # tests/conftest.py) — force CPU through jax.config like the suite does
    from siddhi_tpu.util.platform import force_cpu_platform
    force_cpu_platform(1)
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.state.persistence import FileSystemPersistenceStore

    mgr = SiddhiManager()
    mgr.set_persistence_store(
        FileSystemPersistenceStore(os.path.join(base, "snap")))
    rt = mgr.create_siddhi_app_runtime(
        APP, batch_size=4, wal_dir=os.path.join(base, "wal"))
    out = []
    rt.add_callback("Out", lambda evs: out.extend(tuple(e.data) for e in evs))
    rt.start()
    from siddhi_tpu.util.faults import apply_fault_spec
    apply_fault_spec(rt)  # no-op unless SIDDHI_FAULT_SPEC seeds chaos (CI)
    h = rt.get_input_handler("S")
    print("READY", flush=True)
    for line in sys.stdin:
        # a committed hot-swap replaces the registered runtime in place
        rt = mgr.runtimes.get("CrashApp", rt)
        cmd, *args = line.split()
        if cmd == "send":
            i = int(args[0])
            h.send(("k", value(i)), timestamp=1_000 + i)
            rt.flush()
            print(f"OK {i}", flush=True)
        elif cmd == "upgrade":
            summary = mgr.upgrade(APP_V2)
            h = mgr.runtimes["CrashApp"].get_input_handler("S")
            print(f"UPGRADED {summary['classification']}", flush=True)
        elif cmd == "persist":
            print(f"PERSISTED {rt.persist()}", flush=True)
        elif cmd == "recover":
            res = rt.recover()
            print(f"RECOVERED {res['revision']} {res['wal_replayed']}",
                  flush=True)
        elif cmd == "result":
            rt.flush()
            c, s = out[-1]
            print(f"RESULT {c} {s}", flush=True)
        elif cmd == "stats":
            rep = rt.statistics_report()["recovery"]
            print(f"STATS {rep['recoveries']} {rep['wal_replayed']}",
                  flush=True)
        elif cmd == "exit":
            rt.shutdown()
            print("BYE", flush=True)
            return


if __name__ == "__main__":
    main()
