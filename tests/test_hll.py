"""hll:distinctCount accuracy tests (BASELINE.md config 3 names the HLL
sketch variant; exact distinctCount stays the default)."""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager


def run(app, rows, out="Out", batch_size=4096):
    rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=batch_size)
    got = []
    rt.add_callback(out, lambda evs: got.extend(tuple(e) for e in evs))
    rt.start()
    h = rt.get_input_handler("S")
    for r in rows:
        h.send(r)
    rt.flush()
    rt.shutdown()
    return got


class TestHLLDistinctCount:
    def test_accuracy_within_standard_error(self):
        # 1024 registers -> ~3.3% std error; assert within 4 sigma (13%)
        app = """
        define stream S (v long);
        @info(name='q')
        from S select hll:distinctCount(v) as d insert into Out;
        """
        rng = np.random.default_rng(21)
        true_n = 50_000
        vals = rng.choice(10**12, true_n, replace=False)
        rows = [(int(v),) for v in np.repeat(vals, 2)]  # duplicates collapse
        got = run(app, rows)
        est = got[-1][0]
        assert est == pytest.approx(true_n, rel=0.13)

    def test_small_cardinality_linear_counting_is_tight(self):
        app = """
        define stream S (v int);
        @info(name='q')
        from S select hll:distinctCount(v) as d insert into Out;
        """
        rows = [(i % 37,) for i in range(500)]
        got = run(app, rows, batch_size=512)
        # linear-counting regime: near-exact for tiny cardinalities
        assert got[-1][0] == pytest.approx(37, abs=2)

    def test_grouped_and_string_args(self):
        app = """
        define stream S (k string, v string);
        @info(name='q')
        from S#window.lengthBatch(600)
        select k, hll:distinctCount(v) as d
        group by k
        insert into Out;
        """
        rng = np.random.default_rng(22)
        rows = []
        for _ in range(300):
            rows.append(("a", f"u{int(rng.integers(0, 50))}"))
            rows.append(("b", f"u{int(rng.integers(0, 200))}"))
        got = run(app, rows, batch_size=600)
        final = {}
        for k, d in got:
            final[k] = d
        assert final["a"] == pytest.approx(50, abs=5)
        assert final["b"] == pytest.approx(
            len({r[1] for r in rows if r[0] == "b"}), rel=0.13)

    def test_reset_clears_sketch_between_batches(self):
        app = """
        define stream S (v int);
        @info(name='q')
        from S#window.lengthBatch(100)
        select hll:distinctCount(v) as d insert into Out;
        """
        rows = [(i,) for i in range(100)] + [(0,)] * 100
        got = run(app, rows, batch_size=100)
        # first flush ~100 distinct; second flush: sketch reset, 1 distinct
        assert got[-1][0] == 1
        assert got[99][0] == pytest.approx(100, abs=10)

    def test_multiple_flushes_in_one_chunk(self):
        # regression: two lengthBatch flushes sharing one device chunk must
        # not merge into one sketch
        app = """
        define stream S (v int);
        @info(name='q')
        from S#window.lengthBatch(3)
        select hll:distinctCount(v) as d insert into Out;
        """
        rows = [(v,) for v in (1, 2, 3, 101, 102, 103)]
        got = run(app, rows, batch_size=8)
        # the second batch's final estimate reflects ONLY its own 3 values
        assert got[-1][0] == 3
