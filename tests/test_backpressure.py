"""Bounded ingress & backpressure tests (reference: @async ring buffers,
Source.pause/resume:113-153, StreamJunction OnError fault routing).

The acceptance bar: under overload the staged depth never exceeds the
configured bound, every admitted event is delivered exactly once, and the
drop/divert count in statistics_report() matches the oracle EXACTLY for each
overflow policy; watermark crossings pause and resume attached sources with
exact counts."""

import threading

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu import native as native_mod
from siddhi_tpu.errors import SiddhiAppCreationError
from siddhi_tpu.io.broker import InMemoryBroker
from siddhi_tpu.io.source import ConnectionUnavailableException
from siddhi_tpu.state.error_store import InMemoryErrorStore
from siddhi_tpu.util.faults import (
    FaultPlan,
    SourceFlapPlan,
    apply_fault_spec,
    inject,
    inject_source_flap,
)

pytestmark = pytest.mark.smoke


def _build(policy, cap=8, *, stream_anns="", error_store=None):
    mgr = SiddhiManager()
    if error_store is not None:
        mgr.set_error_store(error_store)
    app = ("@app:name('BP')\n"
           f"@Async(buffer.size='4', overflow.policy='{policy}', "
           f"max.staged='{cap}')\n" + stream_anns +
           "define stream S (v long);\n"
           "@info(name='q') from S select v insert into Out;")
    rt = mgr.create_siddhi_app_runtime(app)
    got: list = []
    rt.add_callback("Out", lambda evs: got.extend(e.data[0] for e in evs))
    return mgr, rt, got


class TestOverflowPolicies:
    """Unstarted runtime = no feeder thread: admission decisions are fully
    deterministic, so the oracles are exact equalities."""

    def test_drop_new_sheds_arrivals_past_capacity(self):
        _mgr, rt, got = _build("drop.new", cap=8)
        h = rt.get_input_handler("S")
        for i in range(20):
            h.send((i,))
        rt.flush()
        rep = rt.statistics_report()
        assert got == list(range(8))  # first 8 admitted, delivered once
        assert rep["ingress_dropped"] == {"S": {"drop.new": 12}}
        assert rep["backpressure"]["queue_hwm"]["S"] == 8

    def test_drop_old_evicts_oldest_staged(self):
        _mgr, rt, got = _build("drop.old", cap=8)
        h = rt.get_input_handler("S")
        for i in range(20):
            h.send((i,))
        rt.flush()
        rep = rt.statistics_report()
        assert got == list(range(12, 20))  # newest 8 survive
        assert rep["ingress_dropped"] == {"S": {"drop.old": 12}}

    def test_fault_policy_diverts_to_error_store(self):
        store = InMemoryErrorStore()
        _mgr, rt, got = _build("fault", cap=8, error_store=store)
        h = rt.get_input_handler("S")
        for i in range(20):
            h.send((i,))
        rt.flush()
        rep = rt.statistics_report()
        assert got == list(range(8))
        assert rep["ingress_dropped"] == {"S": {"fault": 12}}
        entries = store.load("BP", "S", kind="overflow")
        diverted = [row[0] for e in entries for _ts, row in e.events]
        assert sorted(diverted) == list(range(8, 20))  # replayable, not lost

    def test_fault_policy_routes_to_fault_stream(self):
        # @OnError(action='STREAM') declares the `!S` fault junction; the
        # fault overflow policy prefers it over the error store
        _mgr, rt, got = _build("fault", cap=8,
                               stream_anns="@OnError(action='STREAM')\n")
        faulted: list = []
        rt.add_callback("!S", lambda evs: faulted.extend(evs))
        h = rt.get_input_handler("S")
        for i in range(20):
            h.send((i,))
        rt.flush()
        assert got == list(range(8))
        assert [e.data[0] for e in faulted] == list(range(8, 20))
        assert all("overflow" in e.data[1] for e in faulted)

    def test_block_policy_unstarted_delivers_inline(self):
        # block is the default and keeps the pre-existing behavior: without
        # a feeder the sender thread flushes at batch-size — nothing drops
        _mgr, rt, got = _build("block", cap=8)
        h = rt.get_input_handler("S")
        for i in range(20):
            h.send((i,))
        rt.flush()
        assert got == list(range(20))
        assert rt.statistics_report()["ingress_dropped"] == {}

    def test_send_batch_admission_is_counted_identically(self):
        _mgr, rt, got = _build("drop.new", cap=8)
        rt.get_input_handler("S").send_batch([(i,) for i in range(20)])
        rt.flush()
        assert got == list(range(8))
        assert rt.statistics_report()["ingress_dropped"] == \
            {"S": {"drop.new": 12}}

    @pytest.mark.parametrize("ann", [
        "@Async(buffer.size='4', overflow.policy='explode')",
        "@Async(buffer.size='4', overflow.policy='drop.new', "
        "max.staged='2')",  # max.staged < buffer.size
        "@Async(buffer.size='4', high.watermark='0.2', low.watermark='0.8')",
    ])
    def test_bad_annotations_rejected(self, ann):
        with pytest.raises(SiddhiAppCreationError):
            SiddhiManager().create_siddhi_app_runtime(
                ann + "\ndefine stream S (v long);\n"
                "from S select v insert into Out;")


class TestPauseResume:
    def test_watermarks_pause_and_resume_attached_source(self):
        """HWM crossing pauses the inMemory source (payloads buffer), the
        post-flush LWM crossing resumes it (buffered payloads re-deliver) —
        exact pause/resume counts, no losses, order preserved."""
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('BPsrc')\n"
            "@source(type='inMemory', topic='bp')\n"
            "@Async(buffer.size='2', overflow.policy='drop.new', "
            "max.staged='4', high.watermark='0.75', low.watermark='0.25')\n"
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;")
        got: list = []
        rt.add_callback("Out", lambda evs: got.extend(e.data[0] for e in evs))
        src = rt.sources[0]
        src.connect()  # subscribe without start(): no feeder, deterministic
        try:
            for i in range(3):  # depth 1,2,3 -> 3 >= 0.75*4 pauses
                InMemoryBroker.publish("bp", (i,))
            assert src.paused
            for i in range(3, 5):  # arrive paused: buffer at the source
                InMemoryBroker.publish("bp", (i,))
            rt.flush()  # drains to 0 <= 0.25*4 -> resume, pending re-enters
            assert not src.paused
            rt.flush()
            rep = rt.statistics_report()
            assert got == list(range(5))  # nothing lost, order preserved
            assert rep["backpressure"]["pauses"] == {"S": 1}
            assert rep["backpressure"]["resumes"] == {"S": 1}
            assert rep["backpressure"]["queue_hwm"]["S"] == 3
            assert rep["ingress_dropped"] == {}
        finally:
            src.disconnect()

    def test_source_flap_injection_loses_nothing(self):
        """Seeded source flapping (util/faults.py): pause every 3rd payload,
        resume after 2 more — every payload still arrives, in order."""
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('Flap')\n"
            "@source(type='inMemory', topic='flap')\n"
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;")
        got: list = []
        rt.add_callback("Out", lambda evs: got.extend(e.data[0] for e in evs))
        plan = inject_source_flap(rt.sources[0], SourceFlapPlan(every=3, down=2))
        rt.start()
        try:
            for i in range(8):
                InMemoryBroker.publish("flap", (i,))
            rt.flush()
            assert got == list(range(8))
            assert plan.flaps == 2 and plan.calls == 8
        finally:
            rt.shutdown()

    @pytest.mark.skipif(native_mod.native is None,
                        reason="native ring unavailable")
    def test_block_timeout_bounds_the_wait(self):
        """block policy + block.timeout: a producer facing a full ring (the
        drainer is wedged behind the controller lock) waits at most the
        timeout per row, then sheds + counts — conservation still holds."""
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('BT')\n"
            "@Async(buffer.size='4', block.timeout='50')\n"
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;")
        got: list = []
        rt.add_callback("Out", lambda evs: got.extend(e.data[0] for e in evs))
        rt.start()
        n = rt.junctions["S"]._ring_cap + 16
        h = rt.get_input_handler("S")

        def produce():
            for i in range(n):
                h.send((i,))

        with rt.ctx.controller_lock:  # wedge the feeder: ring cannot drain
            t = threading.Thread(target=produce)
            t.start()
            t.join(timeout=30)
            assert not t.is_alive(), "block.timeout failed to bound the wait"
        rt.flush()
        rt.shutdown()
        rep = rt.statistics_report()
        dropped = rep["ingress_dropped"].get("S", {}).get("block.timeout", 0)
        assert dropped >= 1
        assert len(got) + dropped == n  # shed rows are counted, never silent


class TestChaosConservation:
    def test_overload_under_env_fault_spec(self):
        """The CI chaos-smoke scenario: a started bounded drop.old stream
        under a fast producer, with whatever SIDDHI_FAULT_SPEC the
        environment injects (slow consumer etc.). Whatever the interleaving,
        conservation must hold: sent == delivered + dropped + discarded."""
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('Chaos')\n"
            "@Async(buffer.size='64', overflow.policy='drop.old', "
            "max.staged='256')\n"
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;")
        delivered = [0]
        rt.add_callback("Out", lambda blk: delivered.__setitem__(
            0, delivered[0] + blk.count), columnar=True)
        plans = apply_fault_spec(rt)  # no-op unless the env sets a spec
        rt.start()
        h = rt.get_input_handler("S")
        sent = 0
        rows = [(i,) for i in range(64)]
        for _ in range(200):
            h.send_batch(rows)
            sent += 64
        rt.flush()
        rt.shutdown()
        rep = rt.statistics_report()
        dropped = sum(rep["ingress_dropped"].get("S", {}).values())
        discarded = rep["recovery"]["shutdown_discarded"]
        assert delivered[0] + dropped + discarded == sent
        for plan in plans.values():  # the spec really injected
            assert plan.calls > 0


class TestSourceReconnect:
    def test_retry_counter_escalates_then_resets_on_success(self):
        """The per-source BackoffRetryCounter persists across
        connect_with_retry calls (flaps escalate) and resets on success."""
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('Reconn')\n"
            "@source(type='inMemory', topic='rc')\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        src = rt.sources[0]
        sleeps: list = []
        inject(src, "connect", FaultPlan(
            nth=(1, 2), exc=ConnectionUnavailableException))
        src.connect_with_retry(sleep=sleeps.append)
        # two failures: 5 ms then 50 ms backoff, then success resets
        assert sleeps == [0.005, 0.05]
        assert src._retry_counter.get_time_interval_ms() == 5
        assert rt.statistics_report()["source_retries"]["S"] == 2
        src.disconnect()

    def test_pending_buffer_is_bounded_while_paused(self):
        """A paused source cannot become the unbounded buffer the junction
        bound removed: past pause.buffer.size the oldest payload sheds."""
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('Pend')\n"
            "@source(type='inMemory', topic='pend', pause.buffer.size='4')\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        got: list = []
        rt.add_callback("Out", lambda evs: got.extend(e.data[0] for e in evs))
        src = rt.sources[0]
        src.connect()
        try:
            src.pause()
            for i in range(7):
                InMemoryBroker.publish("pend", (i,))
            src.resume()
            rt.flush()
            assert got == [3, 4, 5, 6]  # newest 4 kept
            assert rt.statistics_report()["ingress_dropped"] == \
                {"S": {"source.pending": 3}}
        finally:
            src.disconnect()


class TestBrokerPublish:
    def test_subscribe_during_delivery_is_safe(self):
        """publish() snapshots the subscriber list under the broker lock and
        delivers outside it: a subscriber mutating subscriptions from inside
        on_message neither deadlocks nor corrupts the iteration."""
        got: list = []
        try:
            def cb(msg):
                InMemoryBroker.subscribe_fn("bk2", got.append)
                got.append(("bk1", msg))

            InMemoryBroker.subscribe_fn("bk1", cb)
            InMemoryBroker.publish("bk1", 1)
            InMemoryBroker.publish("bk2", 2)
            assert got == [("bk1", 1), 2]
        finally:
            InMemoryBroker.clear()
