"""Flight-recorder tests (siddhi_tpu/telemetry/recorder.py).

A second FlightRecorder with an injectable clock is attached to a real
runtime so the de-dup / rate-limit gates run on virtual time: per-kind
cooldown, the global min-interval, force bypass, keep_last pruning, the
dead-letter rolling-window burst detector, and the always-on log tail.
Bundle contents round-trip through doctor.load_bundle (the consumer),
which also pins the on-disk schema: six sections, versioned manifest.
"""

import json
import logging
import os

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.doctor import BundleError, load_bundle
from siddhi_tpu.telemetry.recorder import (
    DEAD_LETTER_BURST, DEAD_LETTER_WINDOW_S, SCHEMA_VERSION, FlightRecorder)

pytestmark = pytest.mark.smoke

S = "define stream S (symbol string, price float);\n"
APP = ("@app:name('RecApp')\n" + S
       + "@info(name='q') from S select symbol insert into Out;")


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture
def rt():
    runtime = SiddhiManager().create_siddhi_app_runtime(APP)
    runtime.start()
    yield runtime
    runtime.shutdown()


@pytest.fixture
def rec(rt, tmp_path):
    clock = Clock()
    r = FlightRecorder(rt, bundle_dir=str(tmp_path / "diag"),
                       cooldown_s=300.0, min_interval_s=30.0,
                       keep_last=16, clock=clock)
    r.clock_handle = clock
    yield r
    r.close()


class TestGates:
    def test_per_kind_cooldown_dedups(self, rec):
        assert rec.trigger("slo_breach", reason="first") is not None
        assert rec.trigger("slo_breach", reason="again") is None
        rep = rec.report()
        assert rep["bundles_written"] == 1
        assert rep["triggers"] == {"slo_breach": 2}
        assert rep["suppressed"] == {"slo_breach": 1}
        # past the cooldown the same kind records again
        rec.clock_handle.t = 301.0
        assert rec.trigger("slo_breach", reason="later") is not None
        assert rec.report()["bundles_written"] == 2

    def test_global_min_interval_rate_limits_across_kinds(self, rec):
        assert rec.trigger("slo_breach") is not None
        rec.clock_handle.t = 10.0  # different kind, inside min-interval
        assert rec.trigger("breaker_open") is None
        assert rec.report()["suppressed"] == {"breaker_open": 1}
        rec.clock_handle.t = 45.0  # past it
        assert rec.trigger("breaker_open") is not None

    def test_force_bypasses_both_gates(self, rec):
        assert rec.trigger("manual", force=True) is not None
        assert rec.trigger("manual", force=True) is not None
        rep = rec.report()
        assert rep["bundles_written"] == 2
        assert rep["suppressed"] == {}

    def test_keep_last_prunes_oldest(self, rt, tmp_path):
        clock = Clock()
        r = FlightRecorder(rt, bundle_dir=str(tmp_path / "d"),
                           keep_last=2, clock=clock)
        try:
            for i in range(4):
                clock.t = i * 1000.0
                assert r.trigger("manual", force=True) is not None
            names = sorted(os.listdir(r.bundle_dir))
            assert names == ["RecApp-manual-0003", "RecApp-manual-0004"]
        finally:
            r.close()


class TestDeadLetterBurst:
    def test_burst_trips_once_window_crosses_threshold(self, rec):
        assert rec.on_dead_letter(DEAD_LETTER_BURST // 2) is None
        path = rec.on_dead_letter(DEAD_LETTER_BURST // 2)
        assert path is not None
        man = json.load(open(os.path.join(path, "manifest.json")))
        assert man["trigger"]["kind"] == "dead_letter_burst"

    def test_window_expiry_resets_the_count(self, rec):
        rec.on_dead_letter(DEAD_LETTER_BURST - 1)
        rec.clock_handle.t = DEAD_LETTER_WINDOW_S + 1.0
        # the earlier rows rolled out of the window: no trigger
        assert rec.on_dead_letter(1) is None
        assert rec.report()["bundles_written"] == 0


class TestBundleSchema:
    def test_round_trip_through_doctor_loader(self, rec, rt):
        h = rt.get_input_handler("S")
        for i in range(10):
            h.send(("A", float(i)))
        rt.flush()
        path = rec.trigger("manual", reason="round-trip", force=True)
        assert sorted(os.listdir(path)) == [
            "config.json", "logs.json", "manifest.json", "plan.json",
            "stats.json", "traces.json"]
        bundle = load_bundle(path)
        man = bundle["manifest"]
        assert man["schema_version"] == SCHEMA_VERSION
        assert man["app"] == "RecApp"
        assert man["trigger"] == {"kind": "manual", "reason": "round-trip"}
        assert bundle["stats"]["uptime_seconds"] > 0
        assert bundle["stats"]["latency"]["streams"]["S"]["e2e"]["count"] > 0
        assert bundle["traces"]["recent"], "frozen traces missing"
        assert bundle["plan"]["fingerprint"]
        assert bundle["config"]["env"].get("JAX_PLATFORMS") == "cpu"

    def test_unknown_schema_version_is_rejected(self, rec, tmp_path):
        path = rec.trigger("manual", force=True)
        man_path = os.path.join(path, "manifest.json")
        man = json.load(open(man_path))
        man["schema_version"] = 99
        json.dump(man, open(man_path, "w"))
        with pytest.raises(BundleError, match="schema version"):
            load_bundle(path)
        with pytest.raises(BundleError, match="not a diagnostic bundle"):
            load_bundle(str(tmp_path))  # no manifest at all


class TestLogTailAndWiring:
    def test_warning_tail_captures_context_fields(self, rec):
        logging.getLogger("siddhi_tpu").warning(
            "sink exploded", extra={"app": "RecApp", "stream": "Out",
                                    "batch_id": 7})
        entry = list(rec.log_tail)[-1]
        assert entry["message"] == "sink exploded"
        assert entry["level"] == "WARNING"
        assert (entry["app"], entry["stream"], entry["batch_id"]) == (
            "RecApp", "Out", 7)

    def test_runtime_wires_recorder_and_manual_api(self, rt, tmp_path,
                                                   monkeypatch):
        assert rt.ctx.recorder is not None
        monkeypatch.setattr(rt.ctx.recorder, "bundle_dir",
                            str(tmp_path / "api"))
        out = rt.diagnostics(reason="ops request")
        assert out["bundle"] and os.path.isdir(out["bundle"])
        assert out["recorder"]["bundles_written"] == 1
        rep = rt.statistics_report()
        assert rep["recorder"]["triggers"] == {"manual": 1}
