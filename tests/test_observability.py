"""Statistics / debugger / playback idle-time tests (reference:
managment/StatisticsTestCase, debugger/TestDebugger, managment/PlaybackTestCase)."""

import pytest

from siddhi_tpu import SiddhiManager

S = "define stream S (symbol string, price float);\n"


def build(app, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=batch_size)
    rt.start()
    return rt


class TestStatistics:
    def test_basic_level_counts(self):
        rt = build("@app:statistics('true')\n" + S
                   + "@info(name='q') from S select symbol insert into Out;")
        h = rt.get_input_handler("S")
        for i in range(5):
            h.send(("A", float(i)))
        rt.flush()
        rep = rt.statistics_report()
        assert rep["level"] == "BASIC"
        assert rep["events_in"]["S"] == 5
        assert "query_latency_ms" not in rep  # DETAIL only

    def test_detail_level_memory_and_latency(self):
        rt = build("@app:statistics('DETAIL')\n" + S
                   + "@info(name='q') from S#window.length(4) "
                   "select symbol, sum(price) as t insert into Out;")
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send(("A", float(i)))
        rt.flush()
        rep = rt.statistics_report()
        assert rep["query_latency_ms"]["q"] > 0
        assert rep["state_memory_bytes"]["q"] > 0
        assert rep["buffered_events"]["S"] == 0

    def test_runtime_switchable(self):
        rt = build(S + "from S select symbol insert into Out;")
        assert not rt.statistics.enabled
        rt.set_statistics_level("BASIC")
        rt.get_input_handler("S").send(("A", 1.0))
        rt.flush()
        assert rt.statistics_report()["events_in"]["S"] == 1
        rt.set_statistics_level("OFF")
        assert not rt.statistics.enabled


class TestDebugger:
    def test_in_terminal_capture_and_play(self):
        from siddhi_tpu.core.debugger import QueryTerminal, SiddhiDebugger
        rt = SiddhiManager().create_siddhi_app_runtime(
            S + "@info(name='q') from S[price > 1.0] select symbol insert into Out;")
        dbg = rt.debug()
        seen = []

        def cb(events, qname, terminal, debugger):
            seen.append((qname, terminal, [tuple(e.data) for e in events]))
            return SiddhiDebugger.NEXT

        dbg.set_debugger_callback(cb)
        dbg.acquire_break_point("q", QueryTerminal.IN)
        h = rt.get_input_handler("S")
        h.send(("A", 2.0))
        rt.flush()
        h.send(("B", 3.0))
        rt.flush()
        # NEXT keeps the breakpoint armed: both batches captured at IN
        assert [s[0] for s in seen] == ["q", "q"]
        assert seen[0][1] == QueryTerminal.IN

    def test_out_terminal_sees_filtered_output(self):
        from siddhi_tpu.core.debugger import QueryTerminal, SiddhiDebugger
        rt = SiddhiManager().create_siddhi_app_runtime(
            S + "@info(name='q') from S[price > 1.0] select symbol insert into Out;")
        dbg = rt.debug()
        seen = []
        dbg.set_debugger_callback(
            lambda evs, q, t, d: seen.extend(tuple(e.data) for e in evs)
            or SiddhiDebugger.PLAY)
        dbg.acquire_break_point("q", QueryTerminal.OUT)
        h = rt.get_input_handler("S")
        h.send(("A", 2.0))
        h.send(("B", 0.5))  # filtered out
        rt.flush()
        assert seen == [("A",)]
        # PLAY keeps the breakpoint armed (reference: play() continues and
        # stops at the next hit; releasing is explicit)
        h.send(("C", 5.0))
        rt.flush()
        assert seen == [("A",), ("C",)]
        dbg.release_break_point("q", QueryTerminal.OUT)
        h.send(("D", 6.0))
        rt.flush()
        assert seen == [("A",), ("C",)]


class TestPlaybackIdle:
    def test_idle_heartbeat_advances_virtual_clock(self):
        rt = build(
            "@app:playback(idle.time='100 millisecond', increment='2 sec')\n"
            + S +
            "@info(name='q') from S#window.timeBatch(2 sec) "
            "select symbol, count() as n insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        h.send(("A", 1.0), timestamp=100)
        h.send(("B", 1.0), timestamp=200)
        rt.flush()
        assert got == []  # bucket not closed yet
        rt.heartbeat()  # idle bump: +2 sec virtual → bucket closes
        assert [e.data[1] for e in got] == [1, 2]  # per-event running counts


class TestInteractiveDebugger:
    """Blocking step/next/play protocol (reference:
    SiddhiDebugger.checkBreakPoint:133 blocks the sender thread until
    next():182 / play():190 arrive from the debugger thread)."""

    def _build(self):
        from siddhi_tpu.core.debugger import QueryTerminal
        rt = SiddhiManager().create_siddhi_app_runtime(
            S + "@info(name='q') from S select symbol insert into Out;",
            batch_size=8)
        dbg = rt.debug()
        dbg.acquire_break_point("q", QueryTerminal.IN)
        return rt, dbg

    def test_next_steps_one_event_at_a_time(self):
        import threading
        import time

        rt, dbg = self._build()
        held = []
        dbg.set_debugger_callback(
            lambda evs, q, t, d: held.append(evs[0].data) or None)
        h = rt.get_input_handler("S")
        for sym in "abc":
            h.send((sym, 1.0))

        done = threading.Event()
        t = threading.Thread(target=lambda: (rt.flush(), done.set()))
        t.start()
        deadline = time.time() + 10
        # step through all three events one by one
        for i in (1, 2, 3):
            while len(held) < i and time.time() < deadline:
                time.sleep(0.005)
            assert len(held) == i  # controller is HELD at event i
            assert not done.is_set() or i == 3
            dbg.next()
        t.join(timeout=10)
        assert done.is_set()
        assert [d[0] for d in held] == ["a", "b", "c"]

    def test_play_releases_rest_of_batch(self):
        import threading
        import time

        rt, dbg = self._build()
        held = []
        dbg.set_debugger_callback(
            lambda evs, q, t, d: held.append(evs[0].data) or None)
        h = rt.get_input_handler("S")
        for sym in "abc":
            h.send((sym, 1.0))
        done = threading.Event()
        t = threading.Thread(target=lambda: (rt.flush(), done.set()))
        t.start()
        deadline = time.time() + 10
        while not held and time.time() < deadline:
            time.sleep(0.005)
        dbg.play()  # first event held, rest of the batch flows
        t.join(timeout=10)
        assert done.is_set()
        assert [d[0] for d in held] == ["a"]

    def test_callback_calling_next_inline_does_not_block(self):
        rt, dbg = self._build()
        held = []

        def cb(evs, q, t, d):
            held.append(evs[0].data)
            d.next()  # posts the action before the block: no deadlock
            return None

        dbg.set_debugger_callback(cb)
        h = rt.get_input_handler("S")
        for sym in "ab":
            h.send((sym, 1.0))
        rt.flush()
        assert [d[0] for d in held] == ["a", "b"]
