"""Statistics / debugger / playback idle-time tests (reference:
managment/StatisticsTestCase, debugger/TestDebugger, managment/PlaybackTestCase)."""

import pytest

from siddhi_tpu import SiddhiManager

S = "define stream S (symbol string, price float);\n"


def build(app, batch_size=8):
    rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=batch_size)
    rt.start()
    return rt


class TestStatistics:
    def test_basic_level_counts(self):
        rt = build("@app:statistics('true')\n" + S
                   + "@info(name='q') from S select symbol insert into Out;")
        h = rt.get_input_handler("S")
        for i in range(5):
            h.send(("A", float(i)))
        rt.flush()
        rep = rt.statistics_report()
        assert rep["level"] == "BASIC"
        assert rep["events_in"]["S"] == 5
        assert "query_latency_ms" not in rep  # DETAIL only

    def test_detail_level_memory_and_latency(self):
        rt = build("@app:statistics('DETAIL')\n" + S
                   + "@info(name='q') from S#window.length(4) "
                   "select symbol, sum(price) as t insert into Out;")
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send(("A", float(i)))
        rt.flush()
        rep = rt.statistics_report()
        assert rep["query_latency_ms"]["q"] > 0
        assert rep["state_memory_bytes"]["q"] > 0
        assert rep["buffered_events"]["S"] == 0

    def test_runtime_switchable(self):
        rt = build(S + "from S select symbol insert into Out;")
        assert not rt.statistics.enabled
        rt.set_statistics_level("BASIC")
        rt.get_input_handler("S").send(("A", 1.0))
        rt.flush()
        assert rt.statistics_report()["events_in"]["S"] == 1
        rt.set_statistics_level("OFF")
        assert not rt.statistics.enabled


class TestDebugger:
    def test_in_terminal_capture_and_play(self):
        from siddhi_tpu.core.debugger import QueryTerminal, SiddhiDebugger
        rt = SiddhiManager().create_siddhi_app_runtime(
            S + "@info(name='q') from S[price > 1.0] select symbol insert into Out;")
        dbg = rt.debug()
        seen = []

        def cb(events, qname, terminal, debugger):
            seen.append((qname, terminal, [tuple(e.data) for e in events]))
            return SiddhiDebugger.NEXT

        dbg.set_debugger_callback(cb)
        dbg.acquire_break_point("q", QueryTerminal.IN)
        h = rt.get_input_handler("S")
        h.send(("A", 2.0))
        rt.flush()
        h.send(("B", 3.0))
        rt.flush()
        # NEXT keeps the breakpoint armed: both batches captured at IN
        assert [s[0] for s in seen] == ["q", "q"]
        assert seen[0][1] == QueryTerminal.IN

    def test_out_terminal_sees_filtered_output(self):
        from siddhi_tpu.core.debugger import QueryTerminal, SiddhiDebugger
        rt = SiddhiManager().create_siddhi_app_runtime(
            S + "@info(name='q') from S[price > 1.0] select symbol insert into Out;")
        dbg = rt.debug()
        seen = []
        dbg.set_debugger_callback(
            lambda evs, q, t, d: seen.extend(tuple(e.data) for e in evs)
            or SiddhiDebugger.PLAY)
        dbg.acquire_break_point("q", QueryTerminal.OUT)
        h = rt.get_input_handler("S")
        h.send(("A", 2.0))
        h.send(("B", 0.5))  # filtered out
        rt.flush()
        assert seen == [("A",)]
        # PLAY released the breakpoint
        h.send(("C", 5.0))
        rt.flush()
        assert seen == [("A",)]


class TestPlaybackIdle:
    def test_idle_heartbeat_advances_virtual_clock(self):
        rt = build(
            "@app:playback(idle.time='100 millisecond', increment='2 sec')\n"
            + S +
            "@info(name='q') from S#window.timeBatch(2 sec) "
            "select symbol, count() as n insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        h.send(("A", 1.0), timestamp=100)
        h.send(("B", 1.0), timestamp=200)
        rt.flush()
        assert got == []  # bucket not closed yet
        rt.heartbeat()  # idle bump: +2 sec virtual → bucket closes
        assert [e.data[1] for e in got] == [1, 2]  # per-event running counts
