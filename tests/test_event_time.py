"""Out-of-order event time (core/event_time.py + docs/EVENT_TIME.md):
the @app:eventTime gate — watermarks, allowed lateness, sorted release
with per-event-time delivery batching — plus the late-event side output
(ErrorStore kind="late" → /errors/replay corrections), idle/end-of-stream
drains, telemetry families, the doctor's late-burst finding, the SL116
lint interplay, and the shuffled-replay determinism oracle."""

import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.analysis import analyze
from siddhi_tpu.errors import SiddhiAppCreationError
from siddhi_tpu.state.error_store import InMemoryErrorStore

pytestmark = pytest.mark.smoke

# epoch-ms base: real enough for the telemetry plausibility window
T0 = 1_700_000_000_000

APP = """
@app:name('etapp')
@app:eventTime(timestamp='ts', allowed.lateness='100')
define stream S (ts long, price double);
@info(name='q') from S select ts, price * 2.0 as d insert into Out;
"""


def _mk(app=APP, store=None, **kw):
    mgr = SiddhiManager()
    if store is not None:
        mgr.set_error_store(store)
    rt = mgr.create_siddhi_app_runtime(app, **kw)
    rt.start()
    return mgr, rt


def _collect(rt, sid="Out"):
    got = []
    rt.add_callback(sid, lambda evs: got.extend(
        (e.timestamp, tuple(e.data)) for e in evs))
    return got


class TestGateSemantics:
    def test_disorder_within_lateness_emits_in_event_time_order(self):
        mgr, rt = _mk()
        got = _collect(rt)
        h = rt.get_input_handler("S")
        # arrival order scrambled, displacement < 100 ms
        for off in (30, 0, 60, 40, 90, 150, 120):
            h.send((T0 + off, float(off)), timestamp=T0 + 999)
            rt.flush()
        rt.release_watermarks()
        assert [ts for ts, _ in got] == sorted(ts for ts, _ in got)
        assert [row[0] for _, row in got] == \
            [T0, T0 + 30, T0 + 40, T0 + 60, T0 + 90, T0 + 120, T0 + 150]
        # released rows are re-timestamped WITH their event time
        assert all(ts == row[0] for ts, row in got)
        rt.shutdown()

    def test_same_ts_rows_deliver_as_one_batch_per_event_time(self):
        """The determinism keystone: all rows carrying one event time
        release at the same watermark crossing, as ONE delivery batch,
        in every lateness-bounded arrival order."""
        mgr, rt = _mk()
        batches = []
        rt.add_callback("Out", lambda evs: batches.append(
            [e.timestamp for e in evs]))
        h = rt.get_input_handler("S")
        for off in (10, 0, 10, 0, 20, 10, 300):
            h.send((T0 + off, 1.0), timestamp=T0)
            rt.flush()
        rt.release_watermarks()
        assert [b[0] for b in batches] == \
            [T0, T0 + 10, T0 + 20, T0 + 300]
        assert [len(b) for b in batches] == [2, 3, 1, 1]
        assert all(len(set(b)) == 1 for b in batches)
        rt.shutdown()

    def test_watermark_snapshot_and_conservation(self):
        mgr, rt = _mk()
        h = rt.get_input_handler("S")
        for off in (0, 50, 200):
            h.send((T0 + off, 1.0))
            rt.flush()
        h.send((T0 + 90, 1.0))  # < wm (T0+100): late
        rt.flush()
        wm = rt.statistics_report()["watermarks"]["S"]
        assert wm["attr"] == "ts" and wm["lateness_ms"] == 100
        assert wm["watermark"] == T0 + 100
        assert wm["max_event_ts"] == T0 + 200
        assert wm["late"] == 1
        assert wm["admitted"] == \
            wm["released"] + wm["late"] + wm["buffered"]
        rt.shutdown()

    def test_annotation_requires_timestamp_attr(self):
        bad = APP.replace("timestamp='ts', ", "")
        with pytest.raises(SiddhiAppCreationError, match="timestamp"):
            SiddhiManager().create_siddhi_app_runtime(bad)

    def test_annotation_rejects_non_integer_attr(self):
        bad = APP.replace("timestamp='ts'", "timestamp='price'")
        with pytest.raises(SiddhiAppCreationError, match="INT or LONG"):
            SiddhiManager().create_siddhi_app_runtime(bad)

    def test_annotation_must_match_some_stream(self):
        bad = APP.replace("timestamp='ts'", "timestamp='nope'")
        with pytest.raises(SiddhiAppCreationError):
            SiddhiManager().create_siddhi_app_runtime(bad)


class TestLateSideOutput:
    def test_late_row_diverts_to_error_store_then_replays_once(self):
        store = InMemoryErrorStore()
        mgr, rt = _mk(store=store)
        got = _collect(rt)
        h = rt.get_input_handler("S")
        for off in (0, 300):
            h.send((T0 + off, 1.0))
            rt.flush()
        h.send((T0 + 10, 7.0))  # wm is T0+200: diverted, never dropped
        rt.flush()
        rt.release_watermarks()
        assert [ts for ts, _ in got] == [T0, T0 + 300]
        entries = store.load("etapp")
        assert len(entries) == 1 and entries[0].kind == "late"
        assert entries[0].events == [(T0 + 10, (T0 + 10, 7.0))]
        # /errors/replay semantics: the correction flows through the gate's
        # bypass exactly once and the entry is discarded
        store.replay(entries[0], rt)
        assert [r for _, r in got].count((T0 + 10, 14.0)) == 1
        assert store.load("etapp") == []
        snap = rt.statistics_report()["watermarks"]["S"]
        assert snap["late"] == 1 and snap["bypassed"] == 1
        assert snap["admitted"] == snap["released"] + snap["late"]
        stats = rt.statistics_report()
        assert stats["late_events"] == {"S": 1}
        rt.shutdown()

    def test_late_without_store_counts_and_warns(self, caplog):
        import logging
        mgr, rt = _mk()
        h = rt.get_input_handler("S")
        h.send((T0 + 300, 1.0))
        rt.flush()
        with caplog.at_level(logging.WARNING, logger="siddhi_tpu"):
            h.send((T0, 1.0))
            rt.flush()
        assert any("late" in r.message for r in caplog.records)
        assert rt.statistics_report()["watermarks"]["S"]["late"] == 1
        rt.shutdown()

    def test_unreadable_event_time_diverts(self):
        store = InMemoryErrorStore()
        mgr, rt = _mk(store=store)
        h = rt.get_input_handler("S")
        h.send((None, 1.0))  # event time unreadable: side output, not crash
        rt.flush()
        entries = store.load("etapp")
        assert len(entries) == 1 and entries[0].kind == "late"
        rt.shutdown()


class TestDrains:
    def test_release_watermarks_drains_in_order(self):
        mgr, rt = _mk()
        got = _collect(rt)
        h = rt.get_input_handler("S")
        for off in (50, 20, 80):
            h.send((T0 + off, 1.0))
        rt.flush()
        assert got == []  # all inside the lateness horizon: held
        rt.release_watermarks()
        assert [ts for ts, _ in got] == [T0 + 20, T0 + 50, T0 + 80]
        # stragglers after the forced release classify late, never emit
        # out of order behind delivered rows
        h.send((T0, 9.0))
        rt.flush()
        assert [ts for ts, _ in got] == [T0 + 20, T0 + 50, T0 + 80]
        assert rt.statistics_report()["watermarks"]["S"]["late"] == 1
        rt.shutdown()

    def test_shutdown_drain_releases_buffered_rows(self):
        mgr, rt = _mk()
        got = _collect(rt)
        rt.get_input_handler("S").send((T0, 3.0))
        rt.flush()
        rt.shutdown()  # drain=True path calls release_watermarks()
        assert got == [(T0, (T0, 6.0))]

    def test_idle_timeout_releases_via_heartbeat(self):
        app = APP.replace("allowed.lateness='100'",
                          "allowed.lateness='100', idle.timeout='10'")
        mgr, rt = _mk(app=app)
        got = _collect(rt)
        rt.get_input_handler("S").send((T0, 3.0))
        rt.flush()
        assert got == []
        time.sleep(0.05)  # > idle.timeout (10 ms) with no admissions
        rt.heartbeat()
        assert got == [(T0, (T0, 6.0))]
        rt.shutdown()


class TestTelemetry:
    def test_watermark_and_late_families(self):
        store = InMemoryErrorStore()
        mgr, rt = _mk(store=store)
        tele = rt.ctx.telemetry
        h = rt.get_input_handler("S")
        for off in (0, 500):
            h.send((T0 + off, 1.0))
            rt.flush()
        h.send((T0 + 10, 1.0))
        rt.flush()
        fams = {f.name for f in tele.registry.collect()}
        assert "siddhi_watermark_lag_seconds" in fams
        assert "siddhi_late_events_total" in fams
        assert tele.late_counter.labels("S").value() == 1
        # watermark lag ≈ wall − (T0+400); just assert it was sampled
        assert tele.wm_gauge.labels("S").value() > 0
        # frozen-lag fix: delivery lag re-samples at watermark advance,
        # so the gauge carries the newest event ts even while every row
        # is still buffered (nothing delivered yet)
        assert tele.lag_gauge.labels("S").value() > 0
        rt.shutdown()

    def test_scrape_exports_families_when_off(self, monkeypatch):
        """Watermark/late families are ALWAYS-ON (correctness signals,
        like the sink families) — exported even with SIDDHI_METRICS=off."""
        from siddhi_tpu.telemetry.prometheus import (ALWAYS_ON_FAMILIES,
                                                     render_manager)
        monkeypatch.setenv("SIDDHI_METRICS", "off")
        assert "siddhi_watermark_lag_seconds" in ALWAYS_ON_FAMILIES
        assert "siddhi_late_events_total" in ALWAYS_ON_FAMILIES
        mgr, rt = _mk()
        h = rt.get_input_handler("S")
        for off in (0, 500, 10):  # the 10 is late: counter increments
            h.send((T0 + off, 1.0))
            rt.flush()
        text = render_manager(mgr)
        assert "siddhi_late_events_total" in text
        rt.shutdown()


class TestDoctor:
    def test_late_burst_finding(self):
        from siddhi_tpu import doctor
        from siddhi_tpu.telemetry.recorder import SCHEMA_VERSION

        def bundle(late, admitted):
            return {"manifest": {"schema_version": SCHEMA_VERSION,
                                 "app": "t",
                                 "trigger": {"kind": "manual",
                                             "reason": ""}},
                    "stats": {"watermarks": {"S": {
                        "late": late, "admitted": admitted,
                        "lateness_ms": 100}}},
                    "traces": {}, "logs": [], "plan": None, "config": None}

        burst = [f for f in doctor.analyze(bundle(50, 1000))
                 if "late-event burst" in f["title"]]
        assert burst and burst[0]["severity"] == "warning"
        assert "allowed.lateness" in burst[0]["evidence"]
        trickle = doctor.analyze(bundle(1, 1000))
        assert any("late events diverted" in f["title"] and
                   f["severity"] == "info" for f in trickle)
        assert not any("burst" in f["title"] for f in trickle)


class TestLintInterplay:
    # deliberately-hazardous fixture: built from line fragments so the
    # zero-false-positive sweep in test_lint.py (which collects every
    # triple-quoted app string that BUILDS) skips it — SL116 is an ERROR
    # on an app that does build, by design
    RACY = "\n".join([
        "@app:name('L')",
        "@Async(buffer.size='64', workers='4')",
        "define stream S (ts long, v double);",
        "from S#window.externalTime(ts, 1 sec) select v insert into Out;",
    ])

    def test_sl116_fires_without_lateness(self):
        assert "SL116" in analyze(self.RACY).rule_counts()

    def test_sl116_silent_with_lateness_declared(self):
        cured = ("@app:eventTime(timestamp='ts', allowed.lateness='2 sec')"
                 + self.RACY)
        assert "SL116" not in analyze(cured).rule_counts()


class TestShuffledOracle:
    def _arrivals(self, n=60):
        import random
        rng = random.Random(7)
        return [("S", T0 + (i // 3) * 10,
                 (T0 + (i // 3) * 10, round(rng.uniform(0, 9), 2)))
                for i in range(n)]

    def test_bounded_shuffle_respects_displacement_bound(self):
        from siddhi_tpu.core.upgrade import _bounded_shuffle
        ordered = sorted(self._arrivals(), key=lambda a: a[1])
        for seed in range(8):
            shuf = _bounded_shuffle(ordered, 100, seed)
            assert sorted(shuf) == sorted(ordered)
            # every row is emitted within lateness of the oldest pending
            seen_max = None
            for _sid, ts, _row in shuf:
                if seen_max is not None:
                    assert ts >= seen_max - 100
                seen_max = ts if seen_max is None else max(seen_max, ts)

    def test_digest_bit_identical_across_16_seeds(self):
        mgr = SiddhiManager()
        r = mgr.shuffled_replay(APP, seeds=16, arrivals=self._arrivals())
        assert r["matched"] is True
        assert r["violations"] == []
        assert len(r["runs"]) == 16
        assert all(run["digest"] == r["oracle_digest"]
                   for run in r["runs"])
        assert sum(run["permuted"] for run in r["runs"]) > 0
        assert r["events"] == 60
        mgr.shutdown()

    def test_oracle_from_wal_round_trip(self, tmp_path):
        """End to end on the production read path: journal a disordered
        send sequence, then certify the journal."""
        from siddhi_tpu.core.upgrade import _bounded_shuffle
        mgr, rt = _mk(wal_dir=str(tmp_path))
        h = rt.get_input_handler("S")
        ordered = sorted(self._arrivals(30), key=lambda a: a[1])
        for _sid, ts, row in _bounded_shuffle(ordered, 100, seed=3):
            h.send(row, timestamp=ts)
            rt.flush()
        rt.shutdown()
        mgr2 = SiddhiManager()
        r = mgr2.shuffled_replay(APP, str(tmp_path), seeds=4)
        assert r["matched"] is True and r["events"] == 30
        mgr2.shutdown()

    def test_requires_lateness_budget(self):
        app = APP.replace(", allowed.lateness='100'", "")
        mgr = SiddhiManager()
        with pytest.raises(ValueError, match="allowed.lateness"):
            mgr.shuffled_replay(app, arrivals=self._arrivals(6))
        mgr.shutdown()
