"""Error-handling tests (reference:
modules/siddhi-core/src/test/java/io/siddhi/core/stream/FaultStreamTestCase,
ExceptionHandlerTestCase — @OnError LOG/STREAM/STORE, `!stream` fault streams,
error store save/replay)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.state.error_store import InMemoryErrorStore

APP_BASE = (
    "define stream S (symbol string, price float);\n"
    "@OnError(action='{action}')\n"
    "define stream Out (symbol string, price float);\n"
    "from S select symbol, price insert into Out;\n")


class _Boom(Exception):
    pass


def _raising_callback(events):
    raise _Boom("downstream exploded")


class TestFaultStream:
    def test_on_error_stream_routes_to_fault_stream(self):
        rt = SiddhiManager().create_siddhi_app_runtime(
            APP_BASE.format(action="STREAM")
            + "@info(name='fq') from !Out select symbol, _error insert into FOut;")
        rt.start()
        rt.add_callback("Out", _raising_callback)
        got = []
        rt.add_query_callback("fq", lambda ts, i, r: got.extend(i or []))
        rt.get_input_handler("S").send(("IBM", 75.0))
        rt.flush()
        assert len(got) == 1
        assert got[0].data[0] == "IBM"
        assert "downstream exploded" in got[0].data[1]

    def test_fault_callback_via_bang_name(self):
        rt = SiddhiManager().create_siddhi_app_runtime(APP_BASE.format(action="STREAM"))
        rt.start()
        rt.add_callback("Out", _raising_callback)
        got = []
        rt.add_callback("!Out", lambda events: got.extend(events))
        rt.get_input_handler("S").send(("IBM", 75.0))
        rt.flush()
        assert len(got) == 1

    def test_no_on_error_propagates(self):
        rt = SiddhiManager().create_siddhi_app_runtime(
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        rt.start()
        rt.add_callback("Out", _raising_callback)
        rt.get_input_handler("S").send((1,))
        with pytest.raises(_Boom):
            rt.flush()


class TestOnErrorLog:
    def test_log_swallows_and_continues(self, caplog):
        import logging
        rt = SiddhiManager().create_siddhi_app_runtime(APP_BASE.format(action="LOG"))
        rt.start()
        rt.add_callback("Out", _raising_callback)
        with caplog.at_level(logging.ERROR, logger="siddhi_tpu"):
            rt.get_input_handler("S").send(("IBM", 75.0))
            rt.flush()  # no raise
        assert any("error processing" in r.message for r in caplog.records)


class TestErrorStore:
    def test_store_and_replay(self):
        manager = SiddhiManager()
        store = InMemoryErrorStore()
        manager.set_error_store(store)
        rt = manager.create_siddhi_app_runtime(
            "@app:name('errapp')\n" + APP_BASE.format(action="STORE"))
        rt.start()
        boom = {"on": True}

        def flaky(events):
            if boom["on"]:
                raise _Boom("transient")

        rt.add_callback("Out", flaky)
        rt.get_input_handler("S").send(("IBM", 75.0))
        rt.flush()
        entries = store.load("errapp")
        assert len(entries) == 1
        assert entries[0].stream_name == "Out"
        assert [row for _ts, row in entries[0].events] == [("IBM", 75.0)]

        # replay after the fault clears: events flow again, entry discarded
        boom["on"] = False
        got = []
        rt.add_callback("Out", lambda events: got.extend(events))
        store.replay(entries[0], rt)
        rt.flush()
        assert [tuple(e.data) for e in got] == [("IBM", pytest.approx(75.0))]
        assert store.load("errapp") == []

    def test_store_replay_roundtrip_under_injected_junction_faults(self):
        """@OnError(action='STORE') under a seeded junction fault: every
        event the faulty subscriber rejected round-trips store → replay →
        delivery once the fault schedule clears. Nothing is lost, nothing
        is double-stored."""
        from siddhi_tpu.core.stream import FunctionStreamCallback
        from siddhi_tpu.util.faults import FaultPlan, inject

        manager = SiddhiManager()
        store = InMemoryErrorStore()
        manager.set_error_store(store)
        rt = manager.create_siddhi_app_runtime(
            "@app:name('jfault')\n" + APP_BASE.format(action="STORE"),
            batch_size=1)  # one event per delivery: per-event fault schedule
        rt.start()
        got = []
        cb = FunctionStreamCallback(
            lambda events: got.extend(tuple(e.data) for e in events))
        rt.add_callback("Out", cb)
        # receive 2 and 4 fail (then the schedule is exhausted)
        inject(cb, "receive", FaultPlan(nth=(2, 4), exc=_Boom))
        h = rt.get_input_handler("S")
        for i in range(5):
            h.send((f"S{i}", float(i)))
            rt.flush()
        entries = store.load("jfault", "Out")
        assert [row for e in entries for _ts, row in e.events] == \
            [("S1", 1.0), ("S3", 3.0)]
        assert sorted(r[0] for r in got) == ["S0", "S2", "S4"]
        for e in list(entries):
            store.replay(e, rt)
        rt.flush()
        assert sorted(r[0] for r in got) == [f"S{i}" for i in range(5)]
        assert store.load("jfault") == []
        rt.shutdown()

    def test_replay_keeps_entry_when_send_fails(self):
        """Atomic-ish replay: an exception mid-replay leaves the WHOLE entry
        in the store (all-or-nothing discard), so no half-loss."""
        from siddhi_tpu.util.faults import FaultPlan, InjectedFault, inject

        manager = SiddhiManager()
        store = InMemoryErrorStore()
        manager.set_error_store(store)
        rt = manager.create_siddhi_app_runtime(
            "@app:name('replayapp')\n" + APP_BASE.format(action="STORE"))
        rt.start()
        entry = store.save("replayapp", "S",
                           [(1, ("A", 1.0)), (2, ("B", 2.0))], "boom")
        h = rt.get_input_handler("S")
        inject(h, "send_batch", FaultPlan(nth=(1,), exc=InjectedFault))
        with pytest.raises(InjectedFault):
            store.replay(entry, rt)
        assert [e.id for e in store.load("replayapp")] == [entry.id]
        store.replay(entry, rt)  # schedule exhausted: succeeds
        assert store.load("replayapp") == []
        rt.shutdown()

    def test_replay_uses_one_batched_send(self):
        """Replay stages all rows in ONE send_batch call with their original
        timestamps (not N per-row sends)."""
        calls = []
        manager = SiddhiManager()
        store = InMemoryErrorStore()
        rt = manager.create_siddhi_app_runtime(
            "@app:name('batchapp')\n" + APP_BASE.format(action="STORE"))
        rt.start()
        entry = store.save("batchapp", "S",
                           [(10, ("A", 1.0)), (20, ("B", 2.0))], "x")
        h = rt.get_input_handler("S")
        orig = h.send_batch
        h.send_batch = lambda rows, timestamps=None: (
            calls.append((list(rows), list(timestamps))),
            orig(rows, timestamps=timestamps))[1]
        store.replay(entry, rt)
        assert calls == [([("A", 1.0), ("B", 2.0)], [10, 20])]
        rt.shutdown()


class TestBoundedErrorStore:
    def test_drop_oldest_eviction_and_counter(self):
        store = InMemoryErrorStore(max_entries=2)
        e1 = store.save("app", "S", [(1, ("a",))], "c1")
        e2 = store.save("app", "S", [(2, ("b",))], "c2")
        e3 = store.save("app", "S", [(3, ("c",))], "c3")
        assert [e.id for e in store.load("app")] == [e2.id, e3.id]
        assert store.dropped_count("app") == 1
        assert store.dropped_count("other") == 0
        assert e1.id not in {e.id for e in store.load("app")}

    def test_dropped_counter_surfaces_in_statistics(self):
        manager = SiddhiManager()
        store = InMemoryErrorStore(max_entries=1)
        manager.set_error_store(store)
        rt = manager.create_siddhi_app_runtime(
            "@app:name('boundapp')\n" + APP_BASE.format(action="STORE"))
        rt.start()
        rt.add_callback("Out", _raising_callback)
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send((f"S{i}", float(i)))
            rt.flush()
        rep = rt.statistics_report()
        assert rep["error_store"]["dropped_error_entries"] == 2
        assert rep["error_store"]["entries"] == 1
        rt.shutdown()

    def test_rejects_nonpositive_bound(self):
        with pytest.raises(ValueError):
            InMemoryErrorStore(max_entries=0)
