"""Error-handling tests (reference:
modules/siddhi-core/src/test/java/io/siddhi/core/stream/FaultStreamTestCase,
ExceptionHandlerTestCase — @OnError LOG/STREAM/STORE, `!stream` fault streams,
error store save/replay)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.state.error_store import InMemoryErrorStore

APP_BASE = (
    "define stream S (symbol string, price float);\n"
    "@OnError(action='{action}')\n"
    "define stream Out (symbol string, price float);\n"
    "from S select symbol, price insert into Out;\n")


class _Boom(Exception):
    pass


def _raising_callback(events):
    raise _Boom("downstream exploded")


class TestFaultStream:
    def test_on_error_stream_routes_to_fault_stream(self):
        rt = SiddhiManager().create_siddhi_app_runtime(
            APP_BASE.format(action="STREAM")
            + "@info(name='fq') from !Out select symbol, _error insert into FOut;")
        rt.start()
        rt.add_callback("Out", _raising_callback)
        got = []
        rt.add_query_callback("fq", lambda ts, i, r: got.extend(i or []))
        rt.get_input_handler("S").send(("IBM", 75.0))
        rt.flush()
        assert len(got) == 1
        assert got[0].data[0] == "IBM"
        assert "downstream exploded" in got[0].data[1]

    def test_fault_callback_via_bang_name(self):
        rt = SiddhiManager().create_siddhi_app_runtime(APP_BASE.format(action="STREAM"))
        rt.start()
        rt.add_callback("Out", _raising_callback)
        got = []
        rt.add_callback("!Out", lambda events: got.extend(events))
        rt.get_input_handler("S").send(("IBM", 75.0))
        rt.flush()
        assert len(got) == 1

    def test_no_on_error_propagates(self):
        rt = SiddhiManager().create_siddhi_app_runtime(
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        rt.start()
        rt.add_callback("Out", _raising_callback)
        rt.get_input_handler("S").send((1,))
        with pytest.raises(_Boom):
            rt.flush()


class TestOnErrorLog:
    def test_log_swallows_and_continues(self, caplog):
        import logging
        rt = SiddhiManager().create_siddhi_app_runtime(APP_BASE.format(action="LOG"))
        rt.start()
        rt.add_callback("Out", _raising_callback)
        with caplog.at_level(logging.ERROR, logger="siddhi_tpu"):
            rt.get_input_handler("S").send(("IBM", 75.0))
            rt.flush()  # no raise
        assert any("error processing" in r.message for r in caplog.records)


class TestErrorStore:
    def test_store_and_replay(self):
        manager = SiddhiManager()
        store = InMemoryErrorStore()
        manager.set_error_store(store)
        rt = manager.create_siddhi_app_runtime(
            "@app:name('errapp')\n" + APP_BASE.format(action="STORE"))
        rt.start()
        boom = {"on": True}

        def flaky(events):
            if boom["on"]:
                raise _Boom("transient")

        rt.add_callback("Out", flaky)
        rt.get_input_handler("S").send(("IBM", 75.0))
        rt.flush()
        entries = store.load("errapp")
        assert len(entries) == 1
        assert entries[0].stream_name == "Out"
        assert [row for _ts, row in entries[0].events] == [("IBM", 75.0)]

        # replay after the fault clears: events flow again, entry discarded
        boom["on"] = False
        got = []
        rt.add_callback("Out", lambda events: got.extend(events))
        store.replay(entries[0], rt)
        rt.flush()
        assert [tuple(e.data) for e in got] == [("IBM", pytest.approx(75.0))]
        assert store.load("errapp") == []
