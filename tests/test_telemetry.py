"""End-to-end telemetry tests (siddhi_tpu/telemetry/).

Covers the four pillars of docs/OBSERVABILITY.md: the lock-free metrics
registry (histogram math checked against numpy on seeded data), batch
tracing (monotone IDs minted at ingress surviving to delivery, per-stage
spans, slow-batch exemplars), the Prometheus text exposition (rendered
body must pass the conformance validator, always-on families must be
present even before traffic), and the profiling hooks. Plus the overhead
guard: telemetry-on throughput must stay within 5% of telemetry-off on
the CPU smoke config.
"""

import json
import logging
import threading
import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.telemetry import prometheus
from siddhi_tpu.telemetry.logs import JsonLogFormatter, configure_logging
from siddhi_tpu.telemetry.metrics import (
    BUCKET_BOUNDS_S, N_BUCKETS, Counter, Histogram, MetricsRegistry,
    bucket_index, quantile_from_buckets)

pytestmark = pytest.mark.smoke

S = "define stream S (symbol string, price float);\n"


def build(app, **kw):
    rt = SiddhiManager().create_siddhi_app_runtime(app, **kw)
    rt.start()
    return rt


# --------------------------------------------------------------- histograms

class TestBucketMath:
    def test_boundaries_are_half_open_powers_of_two(self):
        assert bucket_index(0) == 0
        assert bucket_index(1) == 0
        assert bucket_index(1000) == 0          # exactly 1 µs -> bucket 0
        assert bucket_index(1001) == 1
        assert bucket_index(2000) == 1          # exactly 2 µs -> bucket 1
        assert bucket_index(2001) == 2
        for i in range(1, N_BUCKETS - 1):
            ns = (1 << i) * 1000
            assert bucket_index(ns) == i, i     # upper bound inclusive
            assert bucket_index(ns + 1) == min(i + 1, N_BUCKETS - 1)
        # way past the last finite bound -> +Inf bucket, no overflow
        assert bucket_index(10**15) == N_BUCKETS - 1

    def test_bounds_match_bucket_index(self):
        # BUCKET_BOUNDS_S (the `le` values /metrics emits) must agree with
        # bucket_index: a duration exactly at bound i lands in bucket i
        for i, bound_s in enumerate(BUCKET_BOUNDS_S):
            ns = round(bound_s * 1e9)
            assert bucket_index(ns) == i

    def test_percentiles_against_numpy(self):
        # log-uniform latencies spanning 2 µs .. 1 s: the interpolated
        # quantile must land within one x2 bucket of numpy's exact answer
        rng = np.random.default_rng(42)
        samples_ns = np.exp(rng.uniform(np.log(2e3), np.log(1e9),
                                        5000)).astype(np.int64)
        h = Histogram()
        for ns in samples_ns:
            h.observe_ns(int(ns))
        buckets, count, total = h.snapshot()
        assert count == len(samples_ns)
        assert total == int(samples_ns.sum())
        for q in (0.5, 0.95, 0.99, 0.999):
            est = quantile_from_buckets(buckets, count, q)
            exact = float(np.quantile(samples_ns, q))
            # estimate and truth must share a bucket neighbourhood: the
            # log2 scheme bounds relative error by the bucket ratio (x2)
            assert exact / 2 <= est <= exact * 2, (q, est, exact)

    def test_percentiles_exact_when_single_bucket(self):
        # all mass in one bucket: interpolation stays inside its bounds
        h = Histogram()
        for _ in range(100):
            h.observe_ns(3000)  # (2 µs, 4 µs] bucket
        p = h.percentiles((0.5,))
        assert 2e-3 <= p[0.5] <= 4e-3  # ms

    def test_summary_shape(self):
        h = Histogram()
        assert h.summary() == {"count": 0}
        h.observe_ns(5_000_000)
        s = h.summary()
        assert s["count"] == 1
        assert set(s) == {"count", "mean_ms", "p50_ms", "p95_ms", "p99_ms",
                          "p999_ms"}
        assert s["mean_ms"] == pytest.approx(5.0)

    def test_counter_sums_across_threads(self):
        c = Counter()
        n_threads, per = 8, 10_000

        def worker():
            for _ in range(per):
                c.inc()

        ts = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value() == n_threads * per

    def test_histogram_merges_thread_shards(self):
        h = Histogram()

        def worker(ns):
            for _ in range(500):
                h.observe_ns(ns)

        ts = [threading.Thread(target=worker, args=(3000 * (i + 1),))
              for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.count() == 2000

    def test_family_schema_conflict_rejected(self):
        r = MetricsRegistry()
        r.counter("x_total", "x", ("a",))
        with pytest.raises(ValueError):
            r.histogram("x_total", "x", ("a",))
        with pytest.raises(ValueError):
            r.counter("x_total", "x", ("b",))


# ------------------------------------------------------------ batch tracing

class TestBatchTracing:
    APP = ("@app:name('tr')\n" + S
           + "@sink(type='inMemory', topic='tr-out', "
             "@map(type='passThrough'))\n"
             "define stream Out (symbol string);\n"
             "@info(name='q') from S select symbol insert into Out;")

    def _run(self, n=40, batch_size=16):
        rt = build(self.APP, batch_size=batch_size)
        h = rt.get_input_handler("S")
        for i in range(n):
            h.send((f"A{i % 4}", float(i)))
        rt.flush()
        return rt

    def test_ingress_ids_propagate_to_delivery(self):
        # a trace minted at batch FORMATION carries the exact row count;
        # an on-the-fly trace minted at delivery has size None. Seeing the
        # right sizes on stream S proves the ingress-minted trace (and its
        # ID) survived staging -> EventBatch -> junction delivery.
        rt = self._run(n=40, batch_size=16)
        tele = rt.ctx.telemetry
        s_traces = [t for t in tele.recent_summaries()
                    if t["stream"] == "S"]
        assert s_traces, "no ingress traces retired"
        assert sum(t["batch_size"] for t in s_traces) == 40
        ids = [t["batch_id"] for t in s_traces]
        assert ids == sorted(ids) and len(set(ids)) == len(ids)
        # the query step attributed its span to the ingress trace
        assert any("q" in t["queries"] for t in s_traces)
        rt.shutdown()

    def test_sink_span_attributed_to_output_stream(self):
        rt = self._run()
        tele = rt.ctx.telemetry
        out_traces = [t for t in tele.recent_summaries()
                      if t["stream"] == "Out"]
        assert out_traces, "no derived-stream traces retired"
        assert any(t["stages_ms"]["sink"] > 0 for t in out_traces)
        # and the sink histogram family saw it too
        fams = {f.name: f for f in tele.registry.collect()}
        sink_hist = fams["siddhi_sink_latency_seconds"]
        assert any(h.count() > 0 for _, h in sink_hist.samples())
        assert tele.sink_events.labels("Out").value() == 40
        rt.shutdown()

    def test_stage_spans_and_counters(self):
        rt = self._run(n=40, batch_size=16)
        tele = rt.ctx.telemetry
        assert tele.events.labels("S").value() == 40
        assert tele.batches.labels("S").value() >= 3  # ceil(40/16)
        snap = tele.latency_snapshot()
        stages = snap["streams"]["S"]
        for stage in ("stage", "h2d", "device", "e2e"):
            assert stages[stage]["count"] > 0, stage
        assert snap["queries"]["q"]["count"] >= 3
        rt.shutdown()

    def test_statistics_report_carries_latency_and_slow_batches(self):
        rt = self._run()
        rep = rt.statistics_report()
        assert "latency" in rep and "slow_batches" in rep
        slow = rep["slow_batches"]
        assert slow and len(slow) <= 8
        # slowest first, each with the full stage breakdown
        e2es = [b["e2e_ms"] for b in slow]
        assert e2es == sorted(e2es, reverse=True)
        assert set(slow[0]["stages_ms"]) == {"stage", "h2d", "device",
                                             "sink"}
        assert json.dumps(rep)  # report stays JSON-serializable
        rt.shutdown()

    def test_disabled_telemetry_records_nothing(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TELEMETRY", "0")
        rt = self._run()
        tele = rt.ctx.telemetry
        assert not tele.on
        assert not tele.recent
        assert tele.latency_snapshot() == {"streams": {}, "queries": {},
                                           "event_time_lag_s": {}}
        rep = rt.statistics_report()
        assert rep["slow_batches"] == []
        rt.shutdown()


class TestPipelineTracing:
    APP = ("@app:name('ptr')\n"
           "@Async(buffer.size='64', workers='2')\n"
           "define stream TradeStream (symbol string, price double, "
           "volume long);\n"
           "@info(name='q') from TradeStream[price < 100000.0] "
           "select symbol, price, volume insert into OutStream;")

    def _feed(self, rt, n=256):
        rows = [(f"S{i % 7}", float(i), i) for i in range(n)]
        h = rt.get_input_handler("TradeStream")
        h.send_batch(rows, timestamps=np.arange(1, n + 1, dtype=np.int64))
        rt.flush()
        rt.drain()

    def test_pipeline_mints_ingress_traces(self):
        rt = build(self.APP)
        try:
            self._feed(rt)
            tele = rt.ctx.telemetry
            traces = [t for t in tele.recent_summaries()
                      if t["stream"] == "TradeStream"]
            assert traces, "pipeline feeder minted no traces"
            # formation-minted: exact sizes, monotone IDs
            assert sum(t["batch_size"] for t in traces) == 256
            ids = [t["batch_id"] for t in traces]
            assert len(set(ids)) == len(ids)
            assert tele.events.labels("TradeStream").value() == 256
        finally:
            rt.shutdown()

    def test_stage_ms_cells_are_structured(self):
        # satellite: stage_ms evolved from flat ms totals to
        # {total_ms, batches, mean_ms} cells
        rt = build(self.APP)
        try:
            self._feed(rt)
            p = rt.junctions["TradeStream"]._pipeline
            assert p is not None
            stage = p.stats_snapshot()["stage_ms"]
            assert set(stage) == {"decode", "intern", "h2d", "device"}
            for name, cell in stage.items():
                assert set(cell) == {"total_ms", "batches", "mean_ms"}, name
                assert cell["total_ms"] >= 0
                if cell["batches"]:
                    assert cell["mean_ms"] == pytest.approx(
                        cell["total_ms"] / cell["batches"], rel=1e-6)
        finally:
            rt.shutdown()


# --------------------------------------------------------- /metrics renderer

class TestExposition:
    def test_empty_manager_exposes_schema(self):
        text = prometheus.render_manager(SiddhiManager())
        assert prometheus.validate_exposition(text) == []
        for fam in prometheus.ALWAYS_ON_FAMILIES:
            assert f"# TYPE {fam} " in text, fam

    def test_running_app_exposition_is_valid(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('mx')\n" + S
            + "@info(name='q') from S select symbol insert into Out;")
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(30):
            h.send(("A", float(i)))
        rt.flush()
        text = prometheus.render_manager(mgr)
        rt.shutdown()
        assert prometheus.validate_exposition(text) == []
        for fam in prometheus.ALWAYS_ON_FAMILIES:
            assert f"# TYPE {fam} " in text, fam
        assert 'siddhi_app_up{app="mx"} 1' in text
        assert 'siddhi_events_total{app="mx",stream="S"} 30' in text
        # per-query latency series with a full bucket ladder
        assert ('siddhi_query_latency_seconds_bucket{app="mx",query="q",'
                'le="+Inf"}') in text
        assert 'siddhi_query_latency_seconds_count{app="mx",query="q"}' \
            in text

    def test_label_escaping(self):
        from siddhi_tpu.telemetry.prometheus import _escape_label
        assert _escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'

    def test_validator_flags_broken_expositions(self):
        v = prometheus.validate_exposition
        ok = ('# TYPE x_total counter\n'
              'x_total{a="1"} 5\n')
        assert v(ok) == []
        assert v('# TYPE x_total counter\nx_total 1')  # no trailing newline
        assert v('x_total 1\n')                        # sample w/o TYPE
        assert v('# TYPE x_total counter\n'
                 '# TYPE x_total counter\n')           # duplicate TYPE
        assert v('# TYPE x_total counter\n'
                 'x_total{a="1"} 5\nx_total{a="1"} 6\n')  # duplicate sample
        assert v('# TYPE x_total counter\nx_total{a="1"} notanumber\n')
        # histogram: missing +Inf
        assert v('# TYPE h histogram\nh_bucket{le="1"} 1\nh_count 1\n')
        # histogram: non-cumulative buckets
        assert v('# TYPE h histogram\n'
                 'h_bucket{le="1"} 5\n'
                 'h_bucket{le="+Inf"} 3\n'
                 'h_sum 1.0\nh_count 3\n')
        # histogram: _count disagrees with +Inf bucket
        assert v('# TYPE h histogram\n'
                 'h_bucket{le="1"} 1\n'
                 'h_bucket{le="+Inf"} 2\n'
                 'h_sum 1.0\nh_count 9\n')

    def test_rendered_histogram_buckets_are_cumulative(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('hx')\n" + S
            + "@info(name='q') from S select symbol insert into Out;")
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(10):
            h.send(("A", float(i)))
        rt.flush()
        text = prometheus.render_manager(mgr)
        rt.shutdown()
        rows = [ln for ln in text.splitlines()
                if ln.startswith('siddhi_query_latency_seconds_bucket')
                and 'query="q"' in ln]
        counts = [float(ln.rsplit(" ", 1)[1]) for ln in rows]
        assert counts == sorted(counts)
        assert rows[-1].endswith(f" {int(counts[-1])}")
        assert 'le="+Inf"' in rows[-1]


# ------------------------------------------------------------ overhead guard

class TestOverheadGuard:
    # the rows path with a string column: decode + interning + H2D + step,
    # the same per-batch work profile as the e2e smoke configs
    APP = ("@app:name('ov')\n"
           "define stream S (symbol string, k long, v double);\n"
           "@info(name='q') from S[v >= 0.0] "
           "select symbol, k, v insert into Out;")
    ROUNDS = 10
    N = 4096

    def _round(self, h, rt, rows):
        t0 = time.perf_counter()
        for _ in range(self.ROUNDS):
            h.send_batch(rows)
            rt.flush()
        return time.perf_counter() - t0

    def test_overhead_under_five_percent(self):
        # paired A/B on ONE runtime: every recording site checks `tele.on`
        # (the SIDDHI_TELEMETRY=0 switch), so toggling it compares the
        # identical engine — same jit cache, same allocator state — with
        # zero cross-runtime variance. Rounds interleave with alternating
        # order so both arms sample the same scheduler/GC environment, and
        # timing on shared CI hardware is noisy enough that the whole A/B
        # retries: the claim is "within 5%", not "wins every race".
        rows = [(f"S{i % 31}", i, float(i)) for i in range(self.N)]
        rt = build(self.APP, batch_size=self.N)
        tele = rt.ctx.telemetry
        h = rt.get_input_handler("S")
        try:
            for _ in range(3):  # compile + allocator warm-in, untimed
                h.send_batch(rows)
                rt.flush()
            last = None
            for attempt in range(4):
                t_on = t_off = 0.0
                for rep in range(6):
                    if rep % 2 == 0:
                        tele.on = True
                        t_on += self._round(h, rt, rows)
                        tele.on = False
                        t_off += self._round(h, rt, rows)
                    else:
                        tele.on = False
                        t_off += self._round(h, rt, rows)
                        tele.on = True
                        t_on += self._round(h, rt, rows)
                last = t_off / t_on  # throughput_on / throughput_off
                if attempt > 0 and last >= 0.95:  # attempt 0 = warm-in
                    return
        finally:
            tele.on = True
            rt.shutdown()
        pytest.fail(f"telemetry overhead ratio {last:.3f} < 0.95")


# ------------------------------------------------------------------ profiling

class TestProfiling:
    def test_profile_reports_host_device_split(self):
        rt = build("@app:name('pf')\n" + S
                   + "@info(name='q') from S select symbol insert into Out;",
                   batch_size=8)
        sess = rt.profile(n_batches=3)
        assert sess.active
        h = rt.get_input_handler("S")
        for i in range(32):
            h.send(("A", float(i)))
        rt.flush()
        assert sess.wait(5.0)            # auto-disarmed after 3 batches
        assert rt.ctx.telemetry.profile is None
        rep = sess.report()
        assert rep["q"]["batches"] == 3
        assert rep["q"]["host_ms"] > 0
        assert 0.0 <= rep["q"]["device_fraction"] <= 1.0
        rt.shutdown()

    def test_profile_stop_is_idempotent(self):
        rt = build(S + "from S select symbol insert into Out;")
        sess = rt.profile(n_batches=100)
        sess.stop()
        sess.stop()
        assert not sess.active
        assert rt.ctx.telemetry.profile is None
        assert sess.report() == {}
        rt.shutdown()

    def test_maybe_start_without_env_is_noop(self, monkeypatch):
        from siddhi_tpu.telemetry.profiling import maybe_start_jax_profiler
        monkeypatch.delenv("SIDDHI_PROFILE", raising=False)
        assert maybe_start_jax_profiler() is False


# ---------------------------------------------------------- structured logs

class TestJsonLogs:
    def test_formatter_emits_parseable_context(self):
        fmt = JsonLogFormatter()
        rec = logging.LogRecord("siddhi_tpu.test", logging.WARNING,
                                __file__, 1, "sink retry %d", (3,), None)
        rec.app = "x"
        rec.stream = "S"
        rec.batch_id = 17
        out = json.loads(fmt.format(rec))
        assert out["level"] == "WARNING"
        assert out["logger"] == "siddhi_tpu.test"
        assert out["event"] == "sink retry 3"
        assert (out["app"], out["stream"], out["batch_id"]) == ("x", "S", 17)
        assert "ts" in out

    def test_formatter_includes_exceptions(self):
        fmt = JsonLogFormatter()
        try:
            raise ValueError("boom")
        except ValueError:
            import sys
            rec = logging.LogRecord("t", logging.ERROR, __file__, 1,
                                    "failed", (), sys.exc_info())
        out = json.loads(fmt.format(rec))
        assert "boom" in out["exc"]

    def test_configure_is_noop_without_env(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_LOG_FORMAT", raising=False)
        root = logging.getLogger()
        before = [(h, h.formatter) for h in root.handlers]
        configure_logging()
        assert [(h, h.formatter) for h in root.handlers] == before

    def test_configure_installs_json_formatter(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_LOG_FORMAT", "json")
        root = logging.getLogger()
        saved = [(h, h.formatter) for h in root.handlers]
        try:
            configure_logging()
            assert root.handlers, "expected at least one root handler"
            assert all(isinstance(h.formatter, JsonLogFormatter)
                       for h in root.handlers)
            configure_logging()  # idempotent
        finally:
            for h, f in saved:
                h.setFormatter(f)
