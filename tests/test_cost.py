"""Static cost model tests (analysis/cost.py + the SL501 admission gate).

The model's contract is byte-EXACT prediction on closed-schema apps: it
constructs the same operator objects the runtime would and sizes their
init_state under jax.eval_shape, so every test here asserts predicted ==
live to the byte (the 2x band in tools/cost_calibrate.py is headroom for
future inexact operators, not for these). The admission tests prove the
ISSUE acceptance criterion: an over-budget app is refused (error mode) or
deferred (queue mode) BEFORE any device state is allocated.
"""

import pytest

from siddhi_tpu.analysis.cost import (
    app_budget,
    compute_cost,
    format_size,
    measure_runtime_state_bytes,
    parse_size,
)
from siddhi_tpu.core import manager as manager_mod
from siddhi_tpu.core.manager import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError

pytestmark = pytest.mark.smoke


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.setenv("SIDDHI_LINT", "off")
    for var in ("SIDDHI_STATE_BUDGET", "SIDDHI_COMPILE_BUDGET",
                "SIDDHI_BUDGET_MODE"):
        monkeypatch.delenv(var, raising=False)


def _predict_vs_live(app: str, **kw):
    rep = compute_cost(app, **kw)
    rt = SiddhiManager().create_siddhi_app_runtime(app, **kw)
    live = sum(measure_runtime_state_bytes(rt).values())
    rt.shutdown()
    return rep, live


# ------------------------------------------------------------------ sizing

class TestSizeParsing:
    @pytest.mark.parametrize("text,expected", [
        ("0", 0), ("123", 123), ("1kb", 1024), ("1KiB", 1024),
        ("2MB", 2 << 20), ("1.5MiB", int(1.5 * (1 << 20))),
        ("1GB", 1 << 30), ("1gib", 1 << 30), (" 64 MB ", 64 << 20),
    ])
    def test_parse_size(self, text, expected):
        assert parse_size(text) == expected

    def test_parse_size_rejects_garbage(self):
        for bad in ("", "MB", "1xb", "-1kb"):
            with pytest.raises(ValueError):
                parse_size(bad)

    def test_format_size_round_trips_units(self):
        assert format_size(1024) == "1.0KiB"
        assert format_size(96_000_000).endswith("MiB")


class TestExactPrediction:
    """predicted == live to the byte, per operator family."""

    @pytest.mark.parametrize("window", [
        "#window.length(1000)",
        "#window.lengthBatch(512)",
        "#window.time(1 sec)",
        "#window.externalTime(ts, 2 sec)",
    ])
    def test_window_state_bytes_exact(self, window):
        app = f"""
        define stream S (ts long, v double);
        @info(name='q') from S{window} select ts, v insert into Out;
        """
        rep, live = _predict_vs_live(app)
        assert rep.exact
        assert rep.state_bytes == live

    def test_join_store_bytes_exact(self):
        app = """
        define stream L (k int, v double);
        define stream R (k int, w double);
        @info(name='q')
        from L#window.length(1000) as a join R#window.length(2000) as b
        on a.k == b.k
        select a.k as k, a.v as v, b.w as w
        insert into Out;
        """
        rep, live = _predict_vs_live(app)
        assert rep.exact
        assert rep.state_bytes == live

    def test_pattern_pending_bytes_exact(self):
        app = """
        define stream A (val int);
        define stream B (val int);
        @info(name='q')
        from every a=A -> b=B[b.val == a.val] within 5 sec
        select a.val as av, b.val as bv
        insert into Out;
        """
        rep, live = _predict_vs_live(app)
        assert rep.exact
        assert rep.state_bytes == live

    def test_group_by_table_bytes_exact(self):
        app = """
        define stream S (sym string, price double);
        @info(name='q')
        from S#window.lengthBatch(100)
        select sym, sum(price) as total
        group by sym
        insert into Out;
        """
        rep, live = _predict_vs_live(app, group_capacity=1 << 14)
        assert rep.exact
        assert rep.state_bytes == live

    def test_named_window_and_table_bytes_exact(self):
        app = """
        define stream S (k int, v long);
        define window W (k int, v long) length(500);
        define table T (k int, v long);
        @info(name='in') from S insert into W;
        @info(name='q') from W select k, v insert into Out;
        """
        rep, live = _predict_vs_live(app)
        assert rep.state_bytes == live

    def test_compile_ladder_matches_warmup(self):
        app = """
        define stream S (ts long, v double);
        @info(name='q') from S#window.time(1 sec)
        select ts, v insert into Out;
        """
        rep = compute_cost(app)
        rt = SiddhiManager().create_siddhi_app_runtime(app)
        rt.warmup()
        live = sum(rt.ctx.statistics.compiles.values())
        rt.shutdown()
        assert rep.compile_ladder == live

    def test_dominant_element_named(self):
        app = """
        define stream S (a long);
        define stream T (a long);
        @info(name='big') from S#window.length(100000)
        select a insert into Out1;
        @info(name='small') from T#window.length(10)
        select a insert into Out2;
        """
        rep = compute_cost(app)
        assert rep.dominant is not None
        assert rep.dominant.element == "big"
        assert rep.dominant_share > 0.5


# --------------------------------------------------------------- budgeting

BIG_APP = """
@app:name('Big')
define stream S (a long);
@info(name='q') from S#window.length(100000) select a insert into Out;
"""


class TestBudget:
    def test_annotation_budget_parsed(self):
        from siddhi_tpu import compiler
        app = compiler.parse(
            "@app:name('B') @app:budget(state='2MB', compiles='8')\n"
            "define stream S (a int);\n"
            "from S select a insert into Out;")
        b = app_budget(app)
        assert b.state_bytes == 2 << 20
        assert b.compiles == 8
        assert b.source == "annotation"

    def test_env_budget(self, monkeypatch):
        from siddhi_tpu import compiler
        monkeypatch.setenv("SIDDHI_STATE_BUDGET", "1GiB")
        app = compiler.parse("define stream S (a int);\n"
                             "from S select a insert into Out;")
        b = app_budget(app)
        assert b.state_bytes == 1 << 30
        assert b.source == "env"

    def test_no_budget_is_none(self):
        from siddhi_tpu import compiler
        app = compiler.parse("define stream S (a int);\n"
                             "from S select a insert into Out;")
        assert app_budget(app) is None

    def test_over_budget_refused_before_any_state_allocation(
            self, monkeypatch):
        """Error mode must raise BEFORE SiddhiAppRuntime is even
        constructed — patched constructor proves zero device state."""
        def _boom(*a, **kw):
            raise AssertionError("runtime constructed for a refused app")
        monkeypatch.setattr(manager_mod, "SiddhiAppRuntime", _boom)
        monkeypatch.setenv("SIDDHI_STATE_BUDGET", "1MB")
        with pytest.raises(SiddhiAppCreationError, match="SL501"):
            SiddhiManager().create_siddhi_app_runtime(BIG_APP)

    def test_queue_mode_defers_then_admits(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_STATE_BUDGET", "1MB")
        monkeypatch.setenv("SIDDHI_BUDGET_MODE", "queue")
        m = SiddhiManager()
        assert m.create_siddhi_app_runtime(BIG_APP) is None
        assert len(m.pending_apps) == 1
        assert not m.admit_pending()  # still over budget: stays queued
        assert len(m.pending_apps) == 1
        monkeypatch.setenv("SIDDHI_STATE_BUDGET", "1GB")  # headroom freed
        (rt,) = m.admit_pending()
        assert rt is not None and not m.pending_apps
        rt.shutdown()

    def test_compile_budget_refuses(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_COMPILE_BUDGET", "1")
        with pytest.raises(SiddhiAppCreationError, match="compile"):
            SiddhiManager().create_siddhi_app_runtime(BIG_APP)

    def test_env_budget_is_manager_wide(self, monkeypatch):
        """Two apps that fit individually must not both be admitted when
        their sum exceeds the env (fleet) budget."""
        one = compute_cost(BIG_APP).state_bytes
        monkeypatch.setenv("SIDDHI_STATE_BUDGET", str(int(one * 1.5)))
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(BIG_APP)
        assert rt is not None
        second = BIG_APP.replace("'Big'", "'Big2'")
        with pytest.raises(SiddhiAppCreationError, match="already held"):
            m.create_siddhi_app_runtime(second)
        rt.shutdown()

    def test_within_budget_admits_and_reports(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_STATE_BUDGET", "1GB")
        rt = SiddhiManager().create_siddhi_app_runtime(BIG_APP)
        rt.start()
        cost = rt.statistics_report()["cost"]
        assert cost["predicted_state_bytes"] == cost["live_state_bytes"]
        assert cost["state_ratio"] == 1.0
        assert cost["budget"]["state_bytes"] == 1 << 30
        rt.shutdown()


class TestSurfaces:
    def test_lint_report_carries_cost_section(self):
        from siddhi_tpu.analysis import analyze
        rep = analyze(BIG_APP)
        assert rep.cost is not None
        d = rep.to_dict()
        assert d["cost"]["predicted_state_bytes"] > 0
        assert d["cost"]["predicted_compiles"] > 0

    def test_prometheus_families_exported(self):
        from siddhi_tpu.telemetry.prometheus import render_manager
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(BIG_APP)
        body = render_manager(m)
        assert "siddhi_cost_predicted_state_bytes{app=\"Big\"}" in body
        assert "siddhi_cost_compile_ladder{app=\"Big\"}" in body
        rt.shutdown()

    def test_lint_cli_cost_flag(self, tmp_path, capsys):
        from siddhi_tpu.lint import main as lint_main
        p = tmp_path / "app.siddhi"
        p.write_text(BIG_APP)
        rc = lint_main(["--cost", str(p)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "cost:" in out and "device state" in out
