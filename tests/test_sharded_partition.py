"""Mesh-sharded partition execution (parallel/sharded.PartitionedQueryStep).

Runs `partition with (key of Stream)` apps on the virtual 8-device CPU mesh
(conftest forces it) and asserts output parity with the host-loop path, which
itself mirrors the reference's per-key runtime clones
(core/partition/PartitionStreamReceiver.java:82-141).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from siddhi_tpu import SiddhiManager


def _mesh(n=8):
    devs = jax.devices()[:n]
    assert len(devs) == n
    return Mesh(np.asarray(devs), ("part",))


def _run(app, sends, *, mesh=None, out_stream="Out", **kw):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app, batch_size=32, group_capacity=64,
                                     mesh=mesh, partition_capacity=16, **kw)
    got = []
    rt.add_callback(out_stream, lambda evs: got.extend(
        tuple(e) for e in evs))
    rt.start()
    for stream, rows in sends:
        h = rt.get_input_handler(stream)
        for row in rows:
            h.send(row)
        rt.flush()
    rt.shutdown()
    return got


PARTITIONED_LENGTH_BATCH = """
define stream S (sym string, price double, vol long);
partition with (sym of S)
begin
  @info(name='q')
  from S#window.lengthBatch(3)
  select sym, sum(price) as total, count() as n
  group by sym
  insert into Out;
end;
"""


def _trades(n, n_keys, seed=0):
    rng = np.random.default_rng(seed)
    return [(f"K{int(k)}", float(round(p, 2)), int(v)) for k, p, v in zip(
        rng.integers(0, n_keys, n), rng.uniform(1, 100, n),
        rng.integers(1, 50, n))]


def test_partitioned_lengthbatch_parity():
    rows = _trades(60, 5)
    sends = [("S", rows[:20]), ("S", rows[20:40]), ("S", rows[40:])]
    host = _run(PARTITIONED_LENGTH_BATCH, sends)
    sharded = _run(PARTITIONED_LENGTH_BATCH, sends, mesh=_mesh())
    assert len(host) == len(sharded) > 0
    # emission order differs (host: sorted key value; mesh: slot id) — compare
    # as multisets of rounded rows
    canon = lambda rs: sorted((s, round(t, 4), n) for s, t, n in rs)
    assert canon(host) == canon(sharded)


def test_partitioned_sliding_window_parity():
    app = """
    define stream S (sym string, price double, vol long);
    partition with (sym of S)
    begin
      @info(name='q')
      from S#window.length(4)
      select sym, sum(price) as total
      group by sym
      insert into Out;
    end;
    """
    rows = _trades(50, 4, seed=1)
    sends = [("S", rows[:25]), ("S", rows[25:])]
    host = _run(app, sends)
    sharded = _run(app, sends, mesh=_mesh())
    canon = lambda rs: sorted((s, round(t, 4)) for s, t in rs)
    assert len(host) == len(sharded) > 0
    assert canon(host) == canon(sharded)


def test_partitioned_per_key_isolation():
    # every key's lengthBatch window is isolated: with batches of 3, a key
    # flushes only after ITS OWN 3rd event, never because of other keys
    rows = [("A", 1.0, 1), ("B", 10.0, 1), ("A", 2.0, 1),
            ("B", 20.0, 1), ("A", 3.0, 1)]
    got = _run(PARTITIONED_LENGTH_BATCH, [("S", rows)], mesh=_mesh())
    # only A reached 3 events; the flush emits per-event running aggregates
    # (QuerySelector.processGroupBy semantics); B's window holds 2, no output
    assert [(s, t, n) for s, t, n in got] == [
        ("A", 1.0, 1), ("A", 3.0, 2), ("A", 6.0, 3)]


def test_partitioned_filter_inside_partition():
    app = """
    define stream S (sym string, price double, vol long);
    partition with (sym of S)
    begin
      @info(name='q')
      from S[vol > 5]#window.lengthBatch(2)
      select sym, sum(price) as total
      group by sym
      insert into Out;
    end;
    """
    rows = [("A", 1.0, 10), ("A", 2.0, 1), ("A", 3.0, 10),
            ("B", 5.0, 7), ("B", 6.0, 9)]
    host = _run(app, [("S", rows)])
    sharded = _run(app, [("S", rows)], mesh=_mesh())
    canon = lambda rs: sorted((s, round(t, 4)) for s, t in rs)
    # per-event running aggregates; the vol<=5 event never enters A's window
    assert canon(host) == canon(sharded) == [
        ("A", 1.0), ("A", 4.0), ("B", 5.0), ("B", 11.0)]


def test_partitioned_time_window_heartbeat_parity():
    app = """
    define stream S (sym string, price double, vol long);
    partition with (sym of S)
    begin
      @info(name='q')
      from S#window.timeBatch(1 sec)
      select sym, sum(price) as total, count() as n
      group by sym
      insert into Out;
    end;
    """

    def run(mesh):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(
            app, batch_size=16, group_capacity=64,
            mesh=mesh, partition_capacity=16)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(tuple(e) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for i, row in enumerate(_trades(12, 3, seed=2)):
            h.send(row, timestamp=10 + i * 50)
        rt.flush()
        rt.heartbeat(2000)  # expire the 1s bucket
        rt.shutdown()
        return got

    host, sharded = run(None), run(_mesh())
    canon = lambda rs: sorted((s, round(t, 4), n) for s, t, n in rs)
    assert len(host) == len(sharded) > 0
    assert canon(host) == canon(sharded)


def test_partitioned_int_key_and_many_batches():
    app = """
    define stream S (k long, v double);
    partition with (k of S)
    begin
      @info(name='q')
      from S#window.lengthBatch(5)
      select k, sum(v) as total, count() as n
      group by k
      insert into Out;
    end;
    """
    rng = np.random.default_rng(3)
    rows = [(int(k), float(v)) for k, v in zip(
        rng.integers(0, 10, 200), rng.uniform(0, 10, 200))]
    sends = [("S", rows[i:i + 40]) for i in range(0, 200, 40)]
    host = _run(app, sends)
    sharded = _run(app, sends, mesh=_mesh())
    canon = lambda rs: sorted((k, round(t, 3), n) for k, t, n in rs)
    assert len(host) == len(sharded) > 0
    assert canon(host) == canon(sharded)


def test_mesh_falls_back_for_range_partitions():
    app = """
    define stream S (sym string, price double);
    partition with (price < 50 as 'low' or price >= 50 as 'high' of S)
    begin
      @info(name='q')
      from S#window.lengthBatch(2)
      select sym, sum(price) as total
      group by sym
      insert into Out;
    end;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app, batch_size=8, group_capacity=16,
                                     mesh=_mesh(), partition_capacity=16)
    pr = next(iter(rt.partitions.values()))
    assert pr._mesh_step is None  # host loop retained
    got = []
    rt.add_callback("Out", lambda evs: got.extend(tuple(e) for e in evs))
    rt.start()
    h = rt.get_input_handler("S")
    for row in [("A", 10.0), ("A", 20.0), ("B", 60.0), ("B", 70.0)]:
        h.send(row)
    rt.flush()
    assert sorted(got) == [("A", 10.0), ("A", 30.0),
                           ("B", 60.0), ("B", 130.0)]
    rt.shutdown()


def test_mesh_partition_uses_sharded_step():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        PARTITIONED_LENGTH_BATCH, batch_size=8, group_capacity=16,
        mesh=_mesh(), partition_capacity=16)
    pr = next(iter(rt.partitions.values()))
    assert pr._mesh_step is not None
    assert pr._mesh_step.n_shards == 8


def test_mesh_partition_key_overflow_warns():
    # keys past partition_capacity are DROPPED (slot id >= n_slots matches no
    # device slot); the runtime must warn the first time the table fills
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        PARTITIONED_LENGTH_BATCH, batch_size=16, group_capacity=16,
        mesh=_mesh(), partition_capacity=8)
    rt.start()
    h = rt.get_input_handler("S")
    with pytest.warns(UserWarning, match="key slots"):
        for i in range(12):  # 12 distinct keys > 8 slots
            h.send((f"K{i}", 1.0, 1))
        rt.flush()
    rt.shutdown()


def test_mesh_partition_persist_restore():
    m = SiddhiManager()
    from siddhi_tpu.state.persistence import InMemoryPersistenceStore
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime(
        PARTITIONED_LENGTH_BATCH, batch_size=8, group_capacity=16,
        mesh=_mesh(), partition_capacity=16)
    got = []
    rt.add_callback("Out", lambda evs: got.extend(tuple(e) for e in evs))
    rt.start()
    h = rt.get_input_handler("S")
    h.send(("A", 1.0, 1)); h.send(("A", 2.0, 1))
    rt.flush()
    assert got == []  # window of 3 holds 2, no flush yet
    rt.persist()
    h.send(("A", 100.0, 1))  # post-snapshot event, lost on restore
    rt.flush()
    assert [(s, round(t, 4), n) for s, t, n in got] == [
        ("A", 1.0, 1), ("A", 3.0, 2), ("A", 103.0, 3)]
    got.clear()
    rt.restore_last_revision()
    h.send(("A", 3.0, 1))  # completes the pre-snapshot window of 2
    rt.flush()
    assert [(s, round(t, 4), n) for s, t, n in got] == [
        ("A", 1.0, 1), ("A", 3.0, 2), ("A", 6.0, 3)]
    rt.shutdown()
