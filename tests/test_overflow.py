"""Capacity-overflow observability (VERDICT r02 item 6; SURVEY §7
"overflow-to-host escape hatches").

Every fixed-capacity device structure must COUNT what it drops/overwrites
and surface it through Statistics.report()["overflow"] with a one-shot
warning — silent capacity loss is quietly-wrong results. The reference has
no analogue (JVM heaps grow); this is a TPU-design obligation.
"""

import warnings

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core import dtypes


def _mk(app, **kw):
    rt = SiddhiManager().create_siddhi_app_runtime(app, **kw)
    rt.add_callback(rt_out_stream(app), lambda evs: None)
    rt.start()
    return rt


def rt_out_stream(app):
    import re
    m = re.search(r"insert into (\w+)", app)
    return m.group(1)


class TestWindowRingOverflow:
    def test_time_window_overflow_counts_live_overwrites(self):
        # capacity 16 ring, 1-hour window, far more than 16 live rows
        app = """
        define stream S (k int);
        @info(name='q')
        from S#window.time(1 hour)
        select count() as n
        insert into Out;
        """
        prev = dtypes.config.default_window_capacity
        dtypes.config.default_window_capacity = 16  # floors at E = 1024
        try:
            rt = _mk(app, batch_size=256)
        finally:
            dtypes.config.default_window_capacity = prev
        h = rt.get_input_handler("S")
        n = 2048  # all live within the 1-hour window; ring holds 1024
        import time
        base = int(time.time() * 1000)  # live vs the wall-clock watermark
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for i in range(n):
                h.send((i,), timestamp=base + i)
            rt.flush()
            rep = rt.statistics_report()
        rt.shutdown()
        key = "query:q.window_ring_overflow"
        assert rep["overflow"].get(key, 0) >= n - 1024
        assert any("exceeded a fixed device capacity" in str(x.message)
                   for x in w)

    def test_length_window_does_not_overflow(self):
        app = """
        define stream S (k int);
        @info(name='q')
        from S#window.length(8)
        select count() as n
        insert into Out;
        """
        rt = _mk(app, batch_size=8)
        h = rt.get_input_handler("S")
        for i in range(64):
            h.send((i,), timestamp=1000 + i)
        rt.flush()
        rep = rt.statistics_report()
        rt.shutdown()
        assert rep["overflow"] == {}


class TestPatternPendingOverflow:
    def test_pending_table_drops_are_counted(self):
        app = """
        define stream A (v int);
        define stream B (v int);
        @info(name='p')
        from every a=A -> b=B[b.v == a.v]
        select a.v as av insert into Out;
        """
        prev = dtypes.config.pattern_pending_capacity
        dtypes.config.pattern_pending_capacity = 8
        try:
            rt = _mk(app, batch_size=64)
        finally:
            dtypes.config.pattern_pending_capacity = prev
        ha = rt.get_input_handler("A")
        for i in range(64):  # 64 partials into an 8-slot pending table
            ha.send((i,))
        rt.flush()
        rep = rt.statistics_report()
        rt.shutdown()
        key = "query:p.pattern_pending_dropped"
        assert rep["overflow"].get(key, 0) >= 64 - 8


class TestGroupKeyOverflow:
    def test_group_table_unresolved_lanes_are_counted(self):
        # 8-slot group table, 64 distinct keys: claims must fail
        app = """
        define stream S (k int, v double);
        @info(name='g')
        from S select k, sum(v) as total group by k insert into Out;
        """
        rt = _mk(app, batch_size=64, group_capacity=8)
        h = rt.get_input_handler("S")
        for i in range(64):
            h.send((i, 1.0))
        rt.flush()
        rep = rt.statistics_report()
        rt.shutdown()
        key = "query:g.key_table_unresolved"
        assert rep["overflow"].get(key, 0) > 0


class TestSessionKeyOverflow:
    def test_keyed_session_drops_are_counted(self):
        app = """
        define stream S (k int, v double);
        @info(name='s')
        from S#window.session(1 sec, k)
        select k, sum(v) as total
        insert into Out;
        """
        prev = dtypes.config.session_key_capacity
        dtypes.config.session_key_capacity = 4
        try:
            rt = _mk(app, batch_size=16)
        finally:
            dtypes.config.session_key_capacity = prev
        h = rt.get_input_handler("S")
        for i in range(16):  # keys 0..15 into a 4-key session table
            h.send((i, 1.0), timestamp=1000 + i)
        rt.flush()
        rep = rt.statistics_report()
        rt.shutdown()
        key = "query:s.session_key_dropped"
        assert rep["overflow"].get(key, 0) >= 12


class TestJoinDropSurfacing:
    def test_join_pair_drops_reach_statistics(self):
        # every probe matches every build row: fan-out far beyond k_max
        app = """
        define stream L (k int);
        define stream R (k int);
        @info(name='j')
        from L#window.length(1000) as a
        join R#window.length(1000) as b
        on a.k == b.k
        select a.k as k
        insert into Out;
        """
        rt = _mk(app, batch_size=64)
        hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
        for _ in range(8):
            for _i in range(64):
                hr.send((7,))
            rt.flush()
        for _i in range(64):
            hl.send((7,))
        rt.flush()
        rep = rt.statistics_report()
        rt.shutdown()
        key = "query:j.join_pairs_dropped"
        assert rep["overflow"].get(key, 0) > 0
