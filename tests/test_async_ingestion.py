"""Multithreaded async ingestion (reference: stream/JunctionTestCase —
multi-producer Disruptor publication; StreamJunction.java:279-316)."""

import threading
import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu import native as native_mod

pytestmark = pytest.mark.skipif(
    native_mod.native is None, reason="native ring unavailable")


def build(app, **kw):
    rt = SiddhiManager().create_siddhi_app_runtime(app, **kw)
    rt.start()
    return rt


class TestAsyncIngestion:
    def test_multithreaded_producers_all_delivered(self):
        rt = build(
            "@Async(buffer.size='64')\n"
            "define stream S (producer long, seq long);\n"
            "@info(name='q') from S select producer, seq insert into Out;")
        got = []
        lock = threading.Lock()

        def cb(ts, i, r):
            with lock:
                got.extend(tuple(e.data) for e in i or [])

        rt.add_query_callback("q", cb)
        h = rt.get_input_handler("S")
        N, P = 500, 4

        def produce(pid):
            for s in range(N):
                h.send((pid, s))

        threads = [threading.Thread(target=produce, args=(p,))
                   for p in range(P)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rt.flush()  # barrier: drain the ring
        rt.shutdown()
        assert len(got) == N * P
        # per-producer FIFO order survives the multi-producer ring
        for p in range(P):
            seqs = [s for pid, s in got if pid == p]
            assert seqs == list(range(N))

    def test_feeder_delivers_without_explicit_flush(self):
        rt = build(
            "@Async(buffer.size='8')\n"
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        for i in range(32):
            h.send((i,))
        deadline = time.time() + 5.0
        while len(got) < 32 and time.time() < deadline:
            time.sleep(0.01)
        rt.shutdown()
        assert [e.data[0] for e in got] == list(range(32))

    def test_backpressure_blocks_then_recovers(self):
        rt = build(
            "@Async(buffer.size='4')\n"
            "define stream S (v long);\n"
            "@info(name='q') from S select count() as n insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        h = rt.get_input_handler("S")
        # far more than the ring capacity; producers must block, not drop
        for i in range(5000):
            h.send((i,))
        rt.flush()
        rt.shutdown()
        assert got[-1].data[0] == 5000

    def test_sync_streams_unaffected(self):
        rt = build(
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;")
        assert not rt.junctions["S"].is_async
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.extend(i or []))
        rt.get_input_handler("S").send((1,))
        rt.flush()
        assert [e.data[0] for e in got] == [1]


class TestAutoFlush:
    """Wall-clock auto-flush (the Disruptor's immediate-consumption role,
    reference StreamJunction.java:68 + Scheduler.java:48): staged rows
    deliver within ~auto_flush_ms with no flush() from the caller."""

    def test_staged_rows_flush_without_caller(self):
        import time

        from siddhi_tpu import SiddhiManager
        app = ("define stream S (v double);\n"
               "from S[v > 0.0] select v insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(
            app, batch_size=256, auto_flush_ms=10)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        rt.get_input_handler("S").send((1.0,))
        t0 = time.perf_counter()
        while not got and time.perf_counter() - t0 < 10:
            time.sleep(0.005)
        rt.shutdown()
        assert got == [(1.0,)]

    def test_annotation_enables_flusher(self):
        from siddhi_tpu import SiddhiManager
        app = ("@app:autoFlush(interval='25 ms')\n"
               "define stream S (v double);\n"
               "from S select v insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(app)
        assert rt.auto_flush_ms == 25
        rt.start()
        assert rt._flusher_thread is not None
        rt.shutdown()
        assert rt._flusher_stop is None
