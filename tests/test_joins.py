"""Stream-stream and stream-table join tests.

Mirrors the reference's join suite
(modules/siddhi-core/src/test/java/io/siddhi/core/query/join/JoinTestCase.java):
black-box through the public API.
"""

import pytest

from siddhi_tpu import SiddhiManager


def make(app, batch_size=8):
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(app, batch_size=batch_size)
    got = []
    rt.add_callback("OutStream", lambda evs: got.extend(e.data for e in evs))
    rt.start()
    return rt, got


class TestStreamStreamJoin:
    APP = ("define stream TickStream (symbol string, price float);\n"
           "define stream NewsStream (symbol string, headline string);\n"
           "from TickStream#window.length(10) join NewsStream#window.length(10) "
           "on TickStream.symbol == NewsStream.symbol "
           "select TickStream.symbol as symbol, price, headline "
           "insert into OutStream;")

    def test_inner_join_basic(self):
        rt, got = make(self.APP)
        rt.get_input_handler("TickStream").send(("IBM", 75.0))
        rt.flush()
        rt.get_input_handler("NewsStream").send(("IBM", "up"))
        rt.flush()
        assert got == [("IBM", 75.0, "up")]

    def test_inner_join_no_match(self):
        rt, got = make(self.APP)
        rt.get_input_handler("TickStream").send(("IBM", 75.0))
        rt.flush()
        rt.get_input_handler("NewsStream").send(("WSO2", "down"))
        rt.flush()
        assert got == []

    def test_join_both_directions(self):
        rt, got = make(self.APP)
        rt.get_input_handler("NewsStream").send(("IBM", "up"))
        rt.flush()
        rt.get_input_handler("TickStream").send(("IBM", 10.0))
        rt.flush()
        # tick arrival probes news window
        assert got == [("IBM", 10.0, "up")]

    def test_multiple_matches(self):
        rt, got = make(self.APP)
        n = rt.get_input_handler("NewsStream")
        n.send(("IBM", "a"))
        n.send(("IBM", "b"))
        rt.flush()
        rt.get_input_handler("TickStream").send(("IBM", 5.0))
        rt.flush()
        assert sorted(got) == [("IBM", 5.0, "a"), ("IBM", 5.0, "b")]

    def test_window_expiry_limits_matches(self):
        app = ("define stream A (symbol string, x int);\n"
               "define stream B (symbol string, y int);\n"
               "from A#window.length(1) join B#window.length(10) "
               "on A.symbol == B.symbol "
               "select A.symbol as symbol, x, y insert into OutStream;")
        rt, got = make(app)
        a = rt.get_input_handler("A")
        a.send(("IBM", 1))
        rt.flush()
        a.send(("IBM", 2))  # evicts x=1 from A's window
        rt.flush()
        rt.get_input_handler("B").send(("IBM", 9))
        rt.flush()
        assert got == [("IBM", 2, 9)]

    def test_left_outer_join(self):
        app = ("define stream A (symbol string, x int);\n"
               "define stream B (symbol string, y int);\n"
               "from A#window.length(5) left outer join B#window.length(5) "
               "on A.symbol == B.symbol "
               "select A.symbol as symbol, x, y insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("A").send(("IBM", 1))
        rt.flush()
        # no B match: left outer emits with null右 (numeric null -> 0)
        assert got == [("IBM", 1, 0)]

    def test_unidirectional(self):
        app = ("define stream A (symbol string, x int);\n"
               "define stream B (symbol string, y int);\n"
               "from A#window.length(5) unidirectional join B#window.length(5) "
               "on A.symbol == B.symbol "
               "select A.symbol as symbol, x, y insert into OutStream;")
        rt, got = make(app)
        rt.get_input_handler("B").send(("IBM", 7))
        rt.flush()
        assert got == []  # B arrivals don't trigger
        rt.get_input_handler("A").send(("IBM", 1))
        rt.flush()
        assert got == [("IBM", 1, 7)]

    def test_non_equi_cross_join(self):
        app = ("define stream A (x int);\n"
               "define stream B (y int);\n"
               "from A#window.length(5) join B#window.length(5) on A.x < B.y "
               "select x, y insert into OutStream;")
        rt, got = make(app)
        b = rt.get_input_handler("B")
        b.send((5,))
        b.send((1,))
        rt.flush()
        rt.get_input_handler("A").send((3,))
        rt.flush()
        assert got == [(3, 5)]

    def test_join_with_aggregation(self):
        app = ("define stream A (symbol string, x int);\n"
               "define stream B (symbol string, y int);\n"
               "from A#window.length(10) join B#window.length(10) "
               "on A.symbol == B.symbol "
               "select A.symbol as symbol, sum(y) as total group by symbol "
               "insert into OutStream;")
        rt, got = make(app)
        b = rt.get_input_handler("B")
        b.send(("IBM", 10))
        b.send(("IBM", 20))
        rt.flush()
        rt.get_input_handler("A").send(("IBM", 1))
        rt.flush()
        # one arrival matching two B rows -> running sum emits per pair
        assert got[-1] == ("IBM", 30)


class TestStreamTableJoin:
    APP = ("define stream S (symbol string, qty int);\n"
           "define table Prices (symbol string, price float);\n"
           "from S join Prices on S.symbol == Prices.symbol "
           "select S.symbol as symbol, qty, price insert into OutStream;")

    def test_table_join(self):
        rt, got = make(self.APP)
        rt.tables["Prices"].insert_rows([("IBM", 75.0), ("WSO2", 57.0)])
        s = rt.get_input_handler("S")
        s.send(("IBM", 5))
        s.send(("ORCL", 3))
        rt.flush()
        assert got == [("IBM", 5, 75.0)]

    def test_table_join_updated_contents(self):
        rt, got = make(self.APP)
        rt.tables["Prices"].insert_rows([("IBM", 75.0)])
        rt.get_input_handler("S").send(("IBM", 1))
        rt.flush()
        rt.tables["Prices"].insert_rows([("ORCL", 10.0)])
        rt.get_input_handler("S").send(("ORCL", 2))
        rt.flush()
        assert got == [("IBM", 1, 75.0), ("ORCL", 2, 10.0)]


class TestHighFanoutPairs:
    """Regression: pair-block compaction must not truncate below the old
    k_max-per-probe bound at small batch sizes (review finding: a 4*B cap
    with B=4 dropped 24 of 40 matched pairs)."""

    def test_all_pairs_survive_small_batches(self):
        app = ("define stream L (k int, v int);\n"
               "define stream R (k int, v int);\n"
               "from L#window.length(16) join R#window.length(16) "
               "on L.k == R.k "
               "select L.v as lv, R.v as rv insert into OutStream;")
        rt, got = make(app, batch_size=4)
        lh = rt.get_input_handler("L")
        rh = rt.get_input_handler("R")
        # 10 build rows with the same key
        for i in range(10):
            rh.send((7, i))
        rt.flush()
        # 4 probe events, each matches all 10 build rows -> 40 pairs
        for j in range(4):
            lh.send((7, 100 + j))
        rt.flush()
        assert len(got) == 40
        assert sorted({p[0] for p in got}) == [100, 101, 102, 103]
        assert sorted({p[1] for p in got}) == list(range(10))
