"""Incremental aggregation tests (reference suites:
modules/siddhi-core/src/test/java/io/siddhi/core/aggregation/ —
Aggregation1TestCase, Aggregation2TestCase: define aggregation, send events
with explicit timestamps, pull-query `within ... per ...`).

Uses `aggregate by <ts attr>` with explicit epoch-ms timestamps so bucket
boundaries are deterministic.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.aggregation import bucket_start, parse_time_constant
from siddhi_tpu.query_api.definition import Duration

APP = """
define stream TradeStream (symbol string, price double, volume long, ts long);
define aggregation TradeAgg
from TradeStream
select symbol, avg(price) as avgPrice, sum(price) as total, count() as n
group by symbol
aggregate by ts every sec, min, hours, days;
"""

HOUR = 3_600_000
DAY = 86_400_000


def build(app=APP):
    rt = SiddhiManager().create_siddhi_app_runtime(app)
    rt.start()
    return rt


def send_trades(rt, rows):
    h = rt.get_input_handler("TradeStream")
    for row in rows:
        h.send(row)
    rt.flush()


class TestBucketStart:
    def test_fixed_widths(self):
        import jax.numpy as jnp
        ts = jnp.array([1_234_567, 59_999, 60_000], dtype=jnp.int64)
        assert bucket_start(Duration.SECONDS, ts).tolist() == [1_234_000, 59_000, 60_000]
        assert bucket_start(Duration.MINUTES, ts).tolist() == [1_200_000, 0, 60_000]

    def test_month_year_civil(self):
        import datetime
        import jax.numpy as jnp
        # 2026-07-15 12:30:00 UTC → month bucket 2026-07-01, year 2026-01-01
        t = int(datetime.datetime(2026, 7, 15, 12, 30,
                                  tzinfo=datetime.timezone.utc).timestamp() * 1000)
        ts = jnp.array([t], dtype=jnp.int64)
        m = bucket_start(Duration.MONTHS, ts).tolist()[0]
        y = bucket_start(Duration.YEARS, ts).tolist()[0]
        assert m == int(datetime.datetime(2026, 7, 1,
                                          tzinfo=datetime.timezone.utc).timestamp() * 1000)
        assert y == int(datetime.datetime(2026, 1, 1,
                                          tzinfo=datetime.timezone.utc).timestamp() * 1000)

    def test_parse_time_constant(self):
        assert parse_time_constant(1000) == 1000
        assert parse_time_constant("1970-01-01 00:00:10") == 10_000
        assert parse_time_constant("1970-01-01 01:00:00 +01:00") == 0


class TestAggregationFind:
    def test_per_sec_group_by(self):
        rt = build()
        send_trades(rt, [
            ("IBM", 10.0, 1, 1_000), ("IBM", 20.0, 2, 1_500),  # same second
            ("IBM", 40.0, 3, 2_200),                            # next second
            ("WSO2", 5.0, 1, 1_100),
        ])
        events = rt.query(
            "from TradeAgg within 0, 10000 per 'sec' "
            "select symbol, avgPrice, total, n")
        rows = sorted(tuple(e.data) for e in events)
        assert rows == [
            ("IBM", pytest.approx(15.0), pytest.approx(30.0), 2),
            ("IBM", pytest.approx(40.0), pytest.approx(40.0), 1),
            ("WSO2", pytest.approx(5.0), pytest.approx(5.0), 1),
        ]

    def test_per_hour_rollup(self):
        rt = build()
        send_trades(rt, [
            ("IBM", 10.0, 1, 10 * HOUR + 5),
            ("IBM", 30.0, 1, 10 * HOUR + 70_000),   # same hour, later minute
            ("IBM", 100.0, 1, 11 * HOUR + 1),       # next hour
        ])
        events = rt.query(
            "from TradeAgg within 0, 86400000 per 'hours' "
            "select symbol, total, n")
        rows = sorted(tuple(e.data) for e in events)
        assert rows == [("IBM", pytest.approx(40.0), 2),
                        ("IBM", pytest.approx(100.0), 1)]

    def test_within_filters_buckets(self):
        rt = build()
        send_trades(rt, [
            ("A", 1.0, 1, 1 * DAY + 10),
            ("A", 2.0, 1, 2 * DAY + 10),
            ("A", 4.0, 1, 3 * DAY + 10),
        ])
        events = rt.query(
            f"from TradeAgg within {2 * DAY}, {3 * DAY} per 'days' "
            "select symbol, total")
        assert [tuple(e.data) for e in events] == [("A", pytest.approx(2.0))]

    def test_out_of_order_events_merge(self):
        rt = build()
        send_trades(rt, [("A", 10.0, 1, 5_000)])
        send_trades(rt, [("A", 30.0, 1, 1_000)])   # late event, older bucket
        send_trades(rt, [("A", 2.0, 1, 5_500)])    # back to the newer second
        events = rt.query(
            "from TradeAgg within 0, 10000 per 'sec' select symbol, total, n")
        rows = sorted((e.data[1], e.data[2]) for e in events)
        assert rows == [(pytest.approx(12.0), 2), (pytest.approx(30.0), 1)]

    def test_further_aggregation_in_pull_query(self):
        rt = build()
        send_trades(rt, [
            ("A", 10.0, 1, 1_000), ("A", 20.0, 1, 2_000), ("B", 5.0, 1, 3_000)])
        events = rt.query(
            "from TradeAgg within 0, 100000 per 'sec' "
            "select symbol, sum(total) as grand group by symbol")
        rows = sorted(tuple(e.data) for e in events)
        assert rows == [("A", pytest.approx(30.0)), ("B", pytest.approx(5.0))]

    def test_agg_timestamp_exposed(self):
        rt = build()
        send_trades(rt, [("A", 10.0, 1, 61_000)])
        events = rt.query(
            "from TradeAgg within 0, 600000 per 'min' select AGG_TIMESTAMP, total")
        assert [tuple(e.data) for e in events] == [(60_000, pytest.approx(10.0))]

    def test_missing_per_rejected(self):
        rt = build()
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError):
            rt.query("from TradeAgg select symbol")

    def test_unknown_duration_rejected(self):
        rt = build()
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError):
            rt.query("from TradeAgg within 0, 10 per 'months' select symbol")


class TestAggregationUnsupportedAggregator:
    def test_distinct_count_rejected_clearly(self):
        from siddhi_tpu.errors import SiddhiAppCreationError
        with pytest.raises(SiddhiAppCreationError, match="not supported"):
            SiddhiManager().create_siddhi_app_runtime("""
            define stream S (k string, v double, ts long);
            define aggregation A from S
            select k, distinctCount(v) as n
            group by k aggregate by ts every sec;
            """)


class TestAggregationMinMax:
    def test_min_max_buckets(self):
        app = """
        define stream S (k string, v double, ts long);
        define aggregation MM
        from S select k, min(v) as lo, max(v) as hi
        group by k aggregate by ts every sec, min;
        """
        rt = build(app)
        h = rt.get_input_handler("S")
        for row in [("a", 5.0, 1_000), ("a", 2.0, 1_200), ("a", 9.0, 1_900),
                    ("a", 7.0, 2_500)]:
            h.send(row)
        rt.flush()
        events = rt.query("from MM within 0, 2000 per 'sec' select k, lo, hi")
        assert [tuple(e.data) for e in events] == [
            ("a", pytest.approx(2.0), pytest.approx(9.0))]


class TestAggregationEviction:
    def test_capacity_pressure_evicts_oldest_buckets(self):
        import warnings as _warnings
        app = """
        define stream S (k string, v double, ts long);
        define aggregation A
        from S select k, sum(v) as total
        group by k aggregate by ts every sec;
        """
        rt = SiddhiManager().create_siddhi_app_runtime(app, group_capacity=4096)
        rt.start()
        h = rt.get_input_handler("S")
        # > 0.85 * 4096 distinct (bucket, key) slots, then trigger the check
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            for i in range(3600):
                h.send(("x", 1.0, 1_000 * i))
            rt.flush()
            agg = rt.aggregations["A"]
            agg._batches_since_check = 32
            h.send(("x", 1.0, 1_000 * 3600))
            rt.flush()
        count = int(agg.state[0].key_table.count)
        assert count <= 4096 // 2 + 64  # compacted to ~newest half
        # newest buckets survive
        events = rt.query(
            f"from A within {3_599_000}, {3_601_000} per 'sec' select total")
        assert len(events) == 2

    def test_retention_purge(self):
        app = """
        define stream S (k string, v double, ts long);
        @purge(enable='true', @retentionPeriod(sec='10 sec'))
        define aggregation A
        from S select k, sum(v) as total
        group by k aggregate by ts every sec, min;
        """
        rt = SiddhiManager().create_siddhi_app_runtime(app)
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("x", 1.0, 1_000))
        h.send(("x", 2.0, 50_000))
        rt.flush()
        rt.heartbeat(60_000)  # retention: sec buckets older than 10s drop
        events = rt.query("from A within 0, 100000 per 'sec' select total")
        assert [e.data[0] for e in events] == [pytest.approx(2.0)]
        # the min duration has no retention configured → its (single) bucket
        # keeps both events' contribution
        events = rt.query("from A within 0, 100000 per 'min' select total")
        assert [e.data[0] for e in events] == [pytest.approx(3.0)]


class TestAggregationJoin:
    def test_stream_join_aggregation(self):
        app = APP + """
        define stream QueryStream (symbol string, qts long);
        @info(name='j')
        from QueryStream join TradeAgg
        on QueryStream.symbol == TradeAgg.symbol
        per 'sec'
        select QueryStream.symbol as symbol, TradeAgg.total as total
        insert into Out;
        """
        rt = build(app)
        send_trades(rt, [("IBM", 10.0, 1, 1_000), ("IBM", 20.0, 2, 1_500),
                         ("WSO2", 5.0, 1, 1_100)])
        got = []
        rt.add_query_callback("j", lambda ts, i, r: got.extend(i or []))
        rt.get_input_handler("QueryStream").send(("IBM", 0))
        rt.flush()
        assert [tuple(e.data) for e in got] == [("IBM", pytest.approx(30.0))]


class TestAggregationPersistence:
    def test_snapshot_restore(self):
        rt = build()
        send_trades(rt, [("A", 10.0, 1, 1_000)])
        blob = rt.snapshot()
        rt2 = build()
        rt2.restore(blob)
        events = rt2.query("from TradeAgg within 0, 10000 per 'sec' select total")
        assert [e.data[0] for e in events] == [pytest.approx(10.0)]
