"""Upgrade-under-chaos: SIGKILL the engine MID-hot-swap at three seeded
points (core/upgrade.py _crash_point, selected via SIDDHI_UPGRADE_CRASH),
recover with the v1 app, finish the stream — and the windowed output must
match a no-upgrade oracle exactly.

The three points cover the distinct durability shapes of the cutover:

  after-pause    sources quiesced, nothing persisted yet → recovery is the
                 pre-upgrade revision + the journaled suffix
  after-persist  the upgrade's own persist() committed (journal rotated)
                 but the swap didn't → recovery is that revision, empty tail
  after-cutover  the swap committed in-memory only; the process died before
                 acking → same durable state as after-persist, and the
                 operator's manifest still says v1

Recovery always uses the V1 app: a crashed upgrade never acked, and the
mid-upgrade revision carries v1's structural fingerprint (rt1.persist runs
before the swap), so the v1 restore passes the persistence gate.

Driven through the same acknowledged-stdin worker as test_crash_recovery, so
the accepted-event set at the kill is exact, not racy.
"""

import pytest

from tests.crash_worker import WINDOW
from tests.test_crash_recovery import _Worker, _value

# slow: each case SIGKILLs and re-boots engine subprocesses — excluded from
# the tier-1 sweep, run directly by the dedicated CI upgrade-chaos step
pytestmark = [pytest.mark.smoke, pytest.mark.slow]

EVENTS = 40


@pytest.fixture(scope="module")
def oracle(tmp_path_factory):
    """No-crash, no-upgrade run of the same stream."""
    w = _Worker(str(tmp_path_factory.mktemp("oracle")))
    w.send_range(0, EVENTS)
    res = w.cmd("result", "RESULT")
    w.close()
    vals = [_value(i) for i in range(EVENTS)]
    assert res == f"RESULT {WINDOW} {sum(vals[-WINDOW:])}"
    return res


@pytest.mark.parametrize("point,expect_replay", [
    ("after-pause", 5),     # manual persist rotated at 10; journal has 10..14
    ("after-persist", 0),   # the upgrade's persist rotated; empty tail
    ("after-cutover", 0),   # same durable state; swap was in-memory only
])
def test_sigkill_mid_upgrade_recovery_matches_oracle(
        tmp_path, oracle, point, expect_replay):
    base = str(tmp_path / point)
    w = _Worker(base, extra_env={"SIDDHI_UPGRADE_CRASH": point})
    w.send_range(0, 10)
    w.cmd("persist", "PERSISTED")
    w.send_range(10, 15)
    # the upgrade SIGKILLs itself at the seeded point: no reply ever comes
    w.proc.stdin.write("upgrade\n")
    w.proc.stdin.flush()
    w.proc.wait(timeout=180)
    w._watchdog.cancel()
    assert w.proc.returncode == -9  # died BY the seeded SIGKILL, not an error

    w = _Worker(base)
    rec = w.cmd("recover", "RECOVERED").split()
    assert rec[1] != "None", "a persisted revision must survive the crash"
    assert int(rec[2]) == expect_replay
    w.send_range(15, EVENTS)
    got = w.cmd("result", "RESULT")
    w.close()
    assert got == oracle


def test_committed_upgrade_is_exact_under_the_same_stream(tmp_path, oracle):
    """Control arm: the SAME worker protocol with a mid-stream hot-swap that
    is allowed to finish must also match the oracle — the chaos cases above
    then isolate the crash, not the upgrade, as the variable."""
    w = _Worker(str(tmp_path / "live"))
    w.send_range(0, 20)
    assert w.cmd("upgrade", "UPGRADED") == "UPGRADED compatible"
    w.send_range(20, EVENTS)
    got = w.cmd("result", "RESULT")
    w.close()
    assert got == oracle
