"""REST service + config manager + doc-gen tests (reference:
modules/siddhi-service/ deploy API, config/YAMLConfigManagerTestCase,
siddhi-doc-gen)."""

import json
import threading
import urllib.request

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.service import SiddhiService
from siddhi_tpu.util.config import InMemoryConfigManager, YAMLConfigManager
from siddhi_tpu.util.docgen import generate_markdown


pytestmark = pytest.mark.smoke

APP = """@app:name('svc')
define stream S (symbol string, price float);
define table T (symbol string, price float);
from S insert into T;
"""


@pytest.fixture()
def server():
    svc = SiddhiService()
    httpd = svc.make_server(port=0)  # ephemeral port
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", svc
    httpd.shutdown()


def _req(url, method="GET", body=None):
    data = body.encode() if isinstance(body, str) else body
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


class TestService:
    def test_deploy_send_query_undeploy(self, server):
        base, _svc = server
        code, out = _req(f"{base}/siddhi-apps", "POST", APP)
        assert code == 201 and out["app"] == "svc"

        code, out = _req(f"{base}/siddhi-apps")
        assert out["apps"] == ["svc"]

        code, out = _req(f"{base}/siddhi-apps/svc/streams/S", "POST",
                         json.dumps({"events": [["IBM", 75.0], ["WSO2", 57.0]]}))
        assert out["accepted"] == 2

        code, out = _req(f"{base}/siddhi-apps/svc/query", "POST",
                         json.dumps({"query": "from T select symbol, price"}))
        assert sorted(r[0] for r in out["records"]) == ["IBM", "WSO2"]

        code, out = _req(f"{base}/siddhi-apps/svc", "DELETE")
        assert out["undeployed"] is True
        code, out = _req(f"{base}/siddhi-apps")
        assert out["apps"] == []

    def test_duplicate_deploy_rejected(self, server):
        base, _svc = server
        _req(f"{base}/siddhi-apps", "POST", APP)
        try:
            _req(f"{base}/siddhi-apps", "POST", APP)
            raised = False
        except urllib.error.HTTPError as e:
            raised = True
            assert e.code == 400
        assert raised
        _req(f"{base}/siddhi-apps/svc", "DELETE")

    def test_bad_json_body_returns_400(self, server):
        base, _svc = server
        _req(f"{base}/siddhi-apps", "POST", APP)
        try:
            _req(f"{base}/siddhi-apps/svc/streams/S", "POST", "not json")
            raised = False
        except urllib.error.HTTPError as e:
            raised = True
            assert e.code == 400
        assert raised
        _req(f"{base}/siddhi-apps/svc", "DELETE")

    def test_bad_app_returns_400(self, server):
        base, _svc = server
        try:
            _req(f"{base}/siddhi-apps", "POST", "definitely not siddhiql ;;;")
            raised = False
        except urllib.error.HTTPError as e:
            raised = True
            assert e.code == 400
        assert raised

    def test_persist_and_recover_endpoints(self, server, tmp_path):
        from siddhi_tpu.state.persistence import FileSystemPersistenceStore
        base, svc = server
        svc.manager.set_persistence_store(
            FileSystemPersistenceStore(str(tmp_path)))
        _req(f"{base}/siddhi-apps", "POST", APP)
        _req(f"{base}/siddhi-apps/svc/streams/S", "POST",
             json.dumps({"events": [["IBM", 75.0]]}))
        status, out = _req(f"{base}/siddhi-apps/svc/persist", "POST", "")
        assert status == 200 and out["revision"].endswith("_svc")
        status, out = _req(f"{base}/siddhi-apps/svc/recover", "POST", "")
        assert status == 200
        assert out == {"revision": out["revision"], "wal_replayed": 0}
        assert out["revision"].endswith("_svc")

    def test_persist_without_store_returns_400(self, server):
        import urllib.error
        base, svc = server
        _req(f"{base}/siddhi-apps", "POST",
             "@app:name('nostore')\ndefine stream S (v long);\n"
             "from S select v insert into Out;")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(f"{base}/siddhi-apps/nostore/persist", "POST", "")
        assert ei.value.code == 400

    def test_script_functions_rejected_by_default(self, server):
        base, _svc = server
        app = ("@app:name('scripted')\n"
               "define function sq[python] return int { return x * x };\n"
               "define stream S (x int);\n"
               "from S select sq(x) as y insert into Out;\n")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(f"{base}/siddhi-apps", "POST", app)
        assert ei.value.code == 400
        assert "script" in json.loads(ei.value.read())["error"]

    def test_allow_scripts_opt_in(self):
        svc = SiddhiService(allow_scripts=True)
        app = ("@app:name('scripted2')\n"
               "define function sq[python] return int { return x * x };\n"
               "define stream S (x int);\n"
               "from S select sq(x) as y insert into Out;\n")
        assert svc.deploy(app) == "scripted2"
        svc.undeploy("scripted2")


class TestServiceAuth:
    @pytest.fixture()
    def auth_server(self):
        svc = SiddhiService(token="s3cret")
        httpd = svc.make_server(port=0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        yield f"http://127.0.0.1:{httpd.server_address[1]}"
        httpd.shutdown()

    def test_requests_without_token_rejected(self, auth_server):
        for method, path, body in [("GET", "/siddhi-apps", None),
                                   ("POST", "/siddhi-apps", APP),
                                   ("DELETE", "/siddhi-apps/svc", None)]:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(f"{auth_server}{path}", method, body)
            assert ei.value.code == 401

    def test_bearer_token_accepted(self, auth_server):
        req = urllib.request.Request(
            f"{auth_server}/siddhi-apps",
            headers={"Authorization": "Bearer s3cret"})
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
            assert json.loads(resp.read()) == {"apps": []}


class TestConfigManager:
    YAML = """
extensions:
  - extension:
      name: inMemory
      namespace: source
      properties:
        topic: configuredTopic
properties:
  some.flag: "42"
"""

    def test_yaml_config_reader(self):
        cm = YAMLConfigManager(yaml_text=self.YAML)
        reader = cm.generate_config_reader("source", "inMemory")
        assert reader.read_config("topic") == "configuredTopic"
        assert reader.read_config("missing", "dflt") == "dflt"
        assert cm.extract_property("some.flag") == "42"

    def test_source_topic_from_config(self):
        from siddhi_tpu.io import InMemoryBroker
        InMemoryBroker.clear()
        manager = SiddhiManager()
        manager.set_config_manager(YAMLConfigManager(yaml_text=self.YAML))
        rt = manager.create_siddhi_app_runtime(
            "@source(type='inMemory', @map(type='passThrough'))\n"
            "define stream S (v long);\n"
            "from S select v insert into Out;")
        rt.start()
        got = []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        # topic came from deployment config, not the annotation
        InMemoryBroker.publish("configuredTopic", (7,))
        rt.flush()
        assert [e.data[0] for e in got] == [7]
        InMemoryBroker.clear()

    def test_in_memory_config_manager(self):
        cm = InMemoryConfigManager({"sink.log.prefix": "XYZ"})
        assert cm.generate_config_reader("sink", "log").read_config("prefix") == "XYZ"


class TestDocGen:
    def test_markdown_covers_registered_extensions(self):
        md = generate_markdown()
        # registry keys are case-insensitive (stored lowercased)
        for needle in ("## Windows", "`lengthbatch`", "`cron`",
                       "## Aggregators", "`distinctcount`",
                       "## Sources", "`inmemory`", "## Sink distribution strategies"):
            assert needle in md
