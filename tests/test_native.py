"""Native C marshaller tests — parity with the pure-Python encoder and shared
string interning (native/columnar.c, loaded via siddhi_tpu/native.py)."""

import numpy as np
import pytest

from siddhi_tpu import native
from siddhi_tpu.core.event import StreamCodec, StringTable
from siddhi_tpu.query_api.definition import Attribute, AttributeType, StreamDefinition

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native extension not built")

DEF = StreamDefinition(id="S", attributes=(
    Attribute("sym", AttributeType.STRING),
    Attribute("price", AttributeType.DOUBLE),
    Attribute("vol", AttributeType.LONG),
    Attribute("n", AttributeType.INT),
    Attribute("f", AttributeType.FLOAT),
    Attribute("ok", AttributeType.BOOL),
))

ROWS = [
    ("IBM", 75.5, 100, 3, 1.5, True),
    ("WSO2", 57.25, 10, -2, -0.5, False),
    (None, None, None, None, None, None),
    ("IBM", 0.0, 2**40, 7, 9.0, True),
]


def _codec(force_python=False):
    shared = StringTable()
    codec = StreamCodec(DEF, shared)
    if force_python:
        codec._native_plan = None
    return codec, shared


class TestNativeEncoder:
    def test_parity_with_python_encoder(self):
        c_native, s1 = _codec()
        c_python, s2 = _codec(force_python=True)
        assert c_native._native_plan is not None
        a = c_native.rows_to_columns(ROWS, n_pad=8)
        b = c_python.rows_to_columns(ROWS, n_pad=8)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)
        assert s1.snapshot() == s2.snapshot()

    def test_interning_shared_with_python_table(self):
        codec, shared = _codec()
        pre = shared.encode("IBM")  # interned via the PYTHON path first
        cols = codec.rows_to_columns(ROWS, n_pad=4)
        assert cols["sym"][0] == pre  # native reused the same code
        assert cols["sym"][2] == 0  # null
        assert shared.decode(int(cols["sym"][1])) == "WSO2"

    def test_restore_keeps_native_plan_wired(self):
        codec, shared = _codec()
        codec.rows_to_columns(ROWS, n_pad=4)
        snap = shared.snapshot()
        shared.restore(snap)
        cols = codec.rows_to_columns([("IBM", 1.0, 1, 1, 1.0, True)], n_pad=2)
        assert shared.decode(int(cols["sym"][0])) == "IBM"

    def test_fill_ts_monotone_pad(self):
        out = np.zeros(6, dtype=np.int64)
        native.native.fill_ts([5, 7, 9], out, 6)
        assert out.tolist() == [5, 7, 9, 9, 9, 9]

    def test_throughput_improvement(self):
        # not a strict benchmark — just assert the native path isn't slower
        import time
        rows = [(f"S{i % 100}", float(i), i, i, float(i), True)
                for i in range(20_000)]
        c_native, _ = _codec()
        c_python, _ = _codec(force_python=True)
        t0 = time.perf_counter()
        c_native.rows_to_columns(rows)
        t_native = time.perf_counter() - t0
        t0 = time.perf_counter()
        c_python.rows_to_columns(rows)
        t_python = time.perf_counter() - t0
        assert t_native < t_python
