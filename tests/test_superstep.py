"""Device-resident supersteps (core/superstep.py + the ingress feeder's
K-staging).

The correctness contract under test: with `@app:superstep(k=K)` (or
SIDDHI_SUPERSTEP_K), the feeder stages K ring chunks into one device
chunk and runs the whole eligible sub-plan as a single `lax.scan` with
on-device output compaction — and every observable surface (sink blocks,
timestamps, dtypes, expired flags, telemetry traces, statistics) is
BIT-IDENTICAL to the same app run per-batch at K=1. Equality below is
`np.testing.assert_array_equal`, not approx: the scan replays the exact
K=1 step function over the same padded lanes, so even float accumulator
order is unchanged.

Plus the operational surface: the decline taxonomy (ineligible plans fall
back loudly to per-batch, once, with the reason in stats_snapshot), the
device-native packed-key argsort vs the retired host radix callback
(SIDDHI_RADIX_CALLBACK=1 A/B), telemetry batch attribution under K>1
(one trace per inner batch, stages additive, `superstep_k` stamped), and
the pure-Python-ring subprocess parity run (SIDDHI_NATIVE=0)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BS = 64
K = 4
N_ROWS = 2048  # 32 full chunks at BS=64 -> 8 supersteps at K=4

ASYNC_HDR = "@Async(buffer.size='64', workers='2')\n" \
            "define stream TradeStream (symbol string, price double, " \
            "volume long);\n"

APP_FILTER = (
    "@app:name('SSF{tag}')\n{ann}" + ASYNC_HDR +
    "@info(name='filt') from TradeStream[price < 700.0] "
    "select symbol, price, volume insert into OutStream;")

APP_CHAIN = (
    "@app:name('SSC{tag}')\n{ann}" + ASYNC_HDR +
    "@info(name='filt') from TradeStream[price < 700.0] "
    "select symbol, price, volume insert into MidStream;\n"
    "@info(name='agg') from MidStream#window.lengthBatch(50) "
    "select symbol, sum(price) as total, avg(price) as avgPrice "
    "group by symbol insert into OutStream;")

APP_SLIDING = (
    "@app:name('SSW{tag}')\n{ann}" + ASYNC_HDR +
    "@info(name='slide') from TradeStream#window.length(40) "
    "select symbol, sum(price) as s, count() as n "
    "insert into OutStream;")

APP_DISTINCT = (
    "@app:name('SSD{tag}')\n{ann}" + ASYNC_HDR +
    "@info(name='dq') from TradeStream#window.length(64) "
    "select distinctCount(symbol) as d insert into OutStream;")

APP_JOIN = (
    "@app:name('SSJ{tag}')\n{ann}" + ASYNC_HDR +
    "define stream QuoteStream (symbol string, bid double);\n"
    "@info(name='jq') from TradeStream#window.length(32) join "
    "QuoteStream#window.length(16) "
    "on TradeStream.symbol == QuoteStream.symbol "
    "select TradeStream.symbol as symbol, TradeStream.price as price, "
    "QuoteStream.bid as bid insert into OutStream;")


def _rows(n, seed=11):
    rng = np.random.default_rng(seed)
    ks = rng.integers(1, 12, n)
    ps = rng.uniform(1.0, 1000.0, n)
    vs = rng.integers(1, 1000, n)
    return [(f"S{int(k)}", float(p), int(v)) for k, p, v in zip(ks, ps, vs)]


def _with_k(app_tmpl, k):
    if k <= 1:
        return app_tmpl.format(tag="K1", ann="")
    return app_tmpl.format(tag=f"K{k}",
                           ann=f"@app:superstep(k='{k}')\n")


def _capture(app, feed):
    """Run `app`, collect OutStream blocks columnar, return
    (blocks, pipeline stats_snapshot)."""
    rt = SiddhiManager().create_siddhi_app_runtime(app)
    blocks = []
    rt.add_callback("OutStream", lambda b: blocks.append(
        (b.timestamps.copy(),
         {k: v.copy() for k, v in b.columns.items()},
         b.is_expired.copy())), columnar=True)
    rt.start()
    try:
        feed(rt)
        rt.drain()
        snap = rt.junctions["TradeStream"]._pipeline.stats_snapshot()
    finally:
        rt.shutdown()
    return blocks, snap


def _feed_trades(rt):
    h = rt.get_input_handler("TradeStream")
    h.send_batch(_rows(N_ROWS),
                 timestamps=np.arange(1, N_ROWS + 1, dtype=np.int64))


def _assert_blocks_identical(got, want):
    assert len(got) == len(want), (len(got), len(want))
    for (gt, gc, ge), (wt, wc, we) in zip(got, want):
        np.testing.assert_array_equal(gt, wt)
        np.testing.assert_array_equal(ge, we)
        assert gc.keys() == wc.keys()
        for k in wc:
            assert gc[k].dtype == wc[k].dtype, k
            np.testing.assert_array_equal(gc[k], wc[k], err_msg=k)


def _parity(app_tmpl, feed=_feed_trades):
    want, s1 = _capture(_with_k(app_tmpl, 1), feed)
    got, sk = _capture(_with_k(app_tmpl, K), feed)
    # the superstep actually engaged — we are not comparing K=1 to K=1
    assert sk["supersteps_dispatched"] > 0, sk
    assert sk["superstep_decline"] is None, sk
    assert sk["superstep_k"] == K
    assert s1["supersteps_dispatched"] == 0
    _assert_blocks_identical(got, want)
    return got


class TestSuperstepParity:
    """Bit-identical output, K=4 vs K=1, across the plan shapes the scan
    supports: plain filter, chained group-by, sliding window, a custom
    aggregate (distinctCount maintenance replays per inner batch), and a
    stream-stream join side."""

    def test_filter(self):
        blocks = _parity(APP_FILTER)
        assert sum(len(b[0]) for b in blocks) > 0

    def test_chained_groupby(self):
        _parity(APP_CHAIN)

    def test_sliding_window(self):
        _parity(APP_SLIDING)

    def test_distinct_count(self):
        _parity(APP_DISTINCT)

    def test_join_side(self):
        def feed(rt):
            q = rt.get_input_handler("QuoteStream")
            for i in range(12):
                q.send((f"S{i % 12 + 1}", 10.0 + i))
            rt.flush()
            _feed_trades(rt)

        _parity(APP_JOIN, feed)

    def test_env_knob_overrides_annotation(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_SUPERSTEP_K", str(K))
        want, _ = _capture(_with_k(APP_FILTER, 1).replace("SSFK1",
                                                          "SSFenvW"),
                           _feed_trades)
        monkeypatch.undo()
        monkeypatch.setenv("SIDDHI_SUPERSTEP_K", "1")
        got, snap = _capture(_with_k(APP_FILTER, K).replace("SSFK4",
                                                            "SSFenvG"),
                             _feed_trades)
        # env K=1 overrides the annotation's k=4: no supersteps ran
        assert snap["supersteps_dispatched"] == 0
        _assert_blocks_identical(got, want)

    def test_python_ring_subprocess_parity(self, tmp_path):
        """SIDDHI_NATIVE=0 forces the pure-Python ingress ring (decided
        at import time, hence the subprocess): same superstep parity
        oracle on the chained group-by app."""
        script = tmp_path / "ss_parity_py.py"
        script.write_text(
            "import sys; sys.path.insert(0, %r)\n" % REPO
            + "from siddhi_tpu.util.platform import force_cpu_platform\n"
            "force_cpu_platform(1)\n"
            "from tests.test_superstep import APP_CHAIN, _parity\n"
            "from siddhi_tpu.core.ingress import _PyColRing\n"
            "import siddhi_tpu.core.ingress as ing\n"
            "blocks = _parity(APP_CHAIN)\n"
            "print('SS-PARITY-PY OK', len(blocks))\n")
        env = {**os.environ, "SIDDHI_NATIVE": "0", "JAX_PLATFORMS": "cpu"}
        env.pop("SIDDHI_SUPERSTEP_K", None)
        p = subprocess.run([sys.executable, str(script)], env=env,
                           capture_output=True, text=True, timeout=420)
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        assert "SS-PARITY-PY OK" in p.stdout


class TestSuperstepDecline:
    """Ineligible plans fall back to per-batch dispatch — loudly, once,
    with the reason surfaced in stats_snapshot — and still produce
    correct output."""

    def test_non_query_ingress_receiver_declines(self):
        app = _with_k(APP_FILTER, K).replace("SSFK4", "SSFdecl")
        rt = SiddhiManager().create_siddhi_app_runtime(app)
        out, taps = [], []
        rt.add_callback("OutStream",
                        lambda b: out.append(b.count), columnar=True)
        # a callback on the INGRESS stream itself is a non-step receiver:
        # the scan cannot absorb it, so the whole plan declines
        rt.add_callback("TradeStream",
                        lambda b: taps.append(b.count), columnar=True)
        rt.start()
        try:
            _feed_trades(rt)
            rt.drain()
            snap = rt.junctions["TradeStream"]._pipeline.stats_snapshot()
        finally:
            rt.shutdown()
        assert snap["supersteps_dispatched"] == 0
        assert snap["superstep_decline"] is not None
        assert sum(taps) == N_ROWS  # fallback delivered everything
        assert sum(out) > 0

    def test_k1_never_builds_a_runner(self):
        _, snap = _capture(_with_k(APP_FILTER, 1), _feed_trades)
        assert snap["superstep_k"] == 1
        assert snap["supersteps_dispatched"] == 0
        assert snap["superstep_decline"] is None


class TestDeviceSortParity:
    """The packed-key `lax.sort` argsort that replaced the host radix
    callback: stable, and bit-identical to the legacy CPU callback on
    seeded heavy-tie keys (SIDDHI_RADIX_CALLBACK=1 A/B)."""

    LANES = 16384  # above _RADIX_SORT_MIN_LANES -> wide path

    def _keys(self, seed):
        rng = np.random.default_rng(seed)
        # heavy ties: 50 distinct values over 16384 lanes
        return rng.integers(0, 50, self.LANES).astype(np.int32)

    def test_packed_sort_is_stable(self):
        from siddhi_tpu.ops.search import stable_argsort_bounded
        for seed in (1, 2, 3):
            x = self._keys(seed)
            got = np.asarray(stable_argsort_bounded(x))
            want = np.argsort(x, kind="stable").astype(np.int32)
            np.testing.assert_array_equal(got, want)

    def test_packed_sort_matches_legacy_callback(self, monkeypatch):
        from siddhi_tpu.ops.search import (stable_argsort_bounded,
                                           _legacy_callback_enabled)
        x = self._keys(7)
        assert not _legacy_callback_enabled()
        dev = np.asarray(stable_argsort_bounded(x))
        monkeypatch.setenv("SIDDHI_RADIX_CALLBACK", "1")
        assert _legacy_callback_enabled()
        legacy = np.asarray(stable_argsort_bounded(x))
        np.testing.assert_array_equal(dev, legacy)

    def test_batched_rows_stable(self):
        from siddhi_tpu.ops.search import stable_argsort_bounded
        rng = np.random.default_rng(9)
        x = rng.integers(0, 8, (4, self.LANES)).astype(np.int32)
        got = np.asarray(stable_argsort_bounded(x))
        want = np.argsort(x, axis=-1, kind="stable").astype(np.int32)
        np.testing.assert_array_equal(got, want)


class TestSuperstepTelemetry:
    """Under K>1, batch attribution keeps per-batch semantics: one trace
    per inner batch (same count, sizes, and monotone IDs as K=1), each
    stamped with `superstep_k`, and the scan's device time split across
    them so stage totals stay additive."""

    # small feed: 8 chunks -> 2 supersteps, so every trace (ingress +
    # chained streams) fits in the RECENT_RING=64 deque without eviction
    N_TELE = 8 * BS

    def _traces(self, app):
        rt = SiddhiManager().create_siddhi_app_runtime(app)
        rt.add_callback("OutStream", lambda b: None, columnar=True)
        rt.start()
        try:
            h = rt.get_input_handler("TradeStream")
            h.send_batch(_rows(self.N_TELE),
                         timestamps=np.arange(1, self.N_TELE + 1,
                                              dtype=np.int64))
            rt.drain()
            tele = rt.ctx.telemetry
            traces = [t for t in tele.recent_summaries()
                      if t["stream"] == "TradeStream"]
            snap = rt.junctions["TradeStream"]._pipeline.stats_snapshot()
        finally:
            rt.shutdown()
        return traces, snap

    def test_one_trace_per_inner_batch(self):
        traces, snap = self._traces(_with_k(APP_CHAIN, K))
        assert snap["supersteps_dispatched"] > 0
        ss = [t for t in traces if t.get("superstep_k") == K]
        assert ss, "no superstep-stamped traces retired"
        # each superstep retires exactly K inner-batch traces
        assert len(ss) == snap["supersteps_dispatched"] * K
        assert all(t["batch_size"] == BS for t in ss)
        ids = [t["batch_id"] for t in traces]
        assert ids == sorted(ids) and len(set(ids)) == len(ids)
        # conservation: every row is attributed to exactly one trace
        assert sum(t["batch_size"] for t in traces) == self.N_TELE

    def test_stages_additive_and_queries_attributed(self):
        traces, _ = self._traces(_with_k(APP_CHAIN, K))
        ss = [t for t in traces if t.get("superstep_k") == K]
        assert ss
        # the scan's device span was split across inner batches
        assert sum(t["stages_ms"]["device"] for t in ss) > 0
        assert any("filt" in t["queries"] for t in ss)

    def test_k1_traces_carry_no_superstep_key(self):
        traces, _ = self._traces(_with_k(APP_CHAIN, 1))
        assert traces
        assert all("superstep_k" not in t for t in traces)


class TestSuperstepStatistics:
    """@app:statistics stays supported under supersteps: throughput and
    latency accounting match K=1 (in-scan chain counts feed track_in)."""

    def test_statistics_parity(self):
        tmpl = APP_CHAIN.replace("@app:name('SSC{tag}')",
                                 "@app:name('SSS{tag}')\n"
                                 "@app:statistics('true')")
        reps = {}
        for k in (1, K):
            rt = SiddhiManager().create_siddhi_app_runtime(_with_k(tmpl, k))
            rt.add_callback("OutStream", lambda b: None, columnar=True)
            rt.start()
            try:
                _feed_trades(rt)
                rt.drain()
                snap = rt.junctions["TradeStream"]._pipeline \
                    .stats_snapshot()
                if k > 1:
                    assert snap["supersteps_dispatched"] > 0, snap
                reps[k] = rt.statistics_report()["events_in"]
            finally:
                rt.shutdown()
        assert reps[1] == reps[K]
