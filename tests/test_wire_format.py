"""SXF1 wire-format tests (io/wire.py): framing roundtrip, malformed-input
rejection, the service's binary streams endpoint, and the @map(type='frame')
source mapper. The format is the zero-copy contract between producers and
the ingress pipeline, so the decode side must both reproduce the encoder's
columns exactly and refuse anything that does not match the stream schema.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, compiler
from siddhi_tpu.io import wire

pytestmark = pytest.mark.smoke

DEF_TEXT = ("define stream T (symbol string, price double, "
            "volume long, flag bool);")


def _definition():
    return compiler.parse(DEF_TEXT + "\nfrom T select symbol insert into O;"
                          ).stream_definitions["T"]


def _cols(n, seed=3):
    rng = np.random.default_rng(seed)
    syms = np.array([None if i % 9 == 0 else f"S{int(k)}"
                     for i, k in enumerate(rng.integers(1, 20, n))],
                    dtype=object)
    return {
        "symbol": syms,
        "price": rng.uniform(0.5, 900.0, n),
        "volume": rng.integers(1, 1000, n).astype(np.int64),
        "flag": rng.integers(0, 2, n).astype(bool),
    }


class TestRoundtrip:
    def test_plan_shape(self):
        plan = wire.schema_plan(_definition())
        # price is DOUBLE in SiddhiQL but the engine's device dtype is
        # float32 (x64 off) — the wire carries what the device will hold
        assert [(name, code) for name, _dt, code in plan] == [
            ("symbol", "s"), ("price", "f"), ("volume", "l"), ("flag", "b")]

    def test_encode_decode_roundtrip(self):
        plan = wire.schema_plan(_definition())
        cols = _cols(257)
        ts = np.arange(100, 357, dtype=np.int64)
        body = wire.encode_frames(plan, cols, 257, ts=ts)
        frames = list(wire.iter_frames(body))
        assert len(frames) == 1
        got_ts, got, n = wire.decode_frame(frames[0], plan)
        assert n == 257
        np.testing.assert_array_equal(got_ts, ts)
        np.testing.assert_array_equal(
            wire.materialize_strings(got["symbol"]), cols["symbol"])
        np.testing.assert_allclose(got["price"], cols["price"])
        np.testing.assert_array_equal(got["volume"], cols["volume"])
        np.testing.assert_array_equal(got["flag"].astype(bool), cols["flag"])

    def test_chunked_bodies_cover_all_rows(self):
        plan = wire.schema_plan(_definition())
        cols = _cols(500)
        body = wire.encode_frames(plan, cols, 500, chunk=128)
        sizes = []
        seen_syms = []
        for frame in wire.iter_frames(body):
            _ts, got, n = wire.decode_frame(frame, plan)
            sizes.append(n)
            seen_syms.append(wire.materialize_strings(got["symbol"]))
        assert sizes == [128, 128, 128, 116]
        np.testing.assert_array_equal(np.concatenate(seen_syms),
                                      cols["symbol"])

    def test_encoding_is_deterministic(self):
        plan = wire.schema_plan(_definition())
        cols = _cols(100)
        assert wire.encode_frames(plan, cols, 100) == \
            wire.encode_frames(plan, cols, 100)

    def test_numeric_views_are_zero_copy(self):
        plan = wire.schema_plan(_definition())
        cols = _cols(64)
        body = wire.encode_frames(plan, cols, 64)
        frame = next(wire.iter_frames(body))
        _ts, got, _n = wire.decode_frame(frame, plan)
        assert not got["price"].flags.owndata  # a view over the payload

    def test_object_attrs_rejected(self):
        definition = compiler.parse(
            "define stream T (payload object);\n"
            "from T select payload insert into O;"
        ).stream_definitions["T"]
        with pytest.raises(wire.WireFormatError):
            wire.schema_plan(definition)


class TestMalformedInput:
    def _one_frame(self, n=16):
        plan = wire.schema_plan(_definition())
        return plan, wire.encode_frames(plan, _cols(n), n)

    def test_bad_magic(self):
        plan, body = self._one_frame()
        corrupt = bytearray(body)
        corrupt[4:8] = b"NOPE"
        with pytest.raises(wire.WireFormatError, match="magic"):
            for f in wire.iter_frames(bytes(corrupt)):
                wire.decode_frame(f, plan)

    def test_truncated_body(self):
        plan, body = self._one_frame()
        with pytest.raises(wire.WireFormatError, match="truncated"):
            list(wire.iter_frames(body[:-3]))

    def test_truncated_length_prefix(self):
        with pytest.raises(wire.WireFormatError, match="length prefix"):
            list(wire.iter_frames(b"\x01\x02"))

    def test_column_count_mismatch(self):
        plan, body = self._one_frame()
        with pytest.raises(wire.WireFormatError, match="columns"):
            wire.decode_frame(next(wire.iter_frames(body)), plan[:-1])

    def test_typecode_mismatch(self):
        plan, body = self._one_frame()
        swapped = [plan[1], plan[0]] + list(plan[2:])  # symbol <-> price
        with pytest.raises(wire.WireFormatError, match="typecode"):
            wire.decode_frame(next(wire.iter_frames(body)), swapped)


APP = """
@app:name('WireApp')
define stream TradeStream (symbol string, price double, volume long);
@info(name='q')
from TradeStream[price < 700.0]
select symbol, price, volume
insert into OutStream;
"""


class TestServiceIngestion:
    def _deploy(self):
        from siddhi_tpu.service import SiddhiService
        svc = SiddhiService()
        svc.deploy(APP)
        rt = svc.manager.runtimes["WireApp"]
        got = [0]
        rt.add_callback("OutStream", lambda b: got.__setitem__(
            0, got[0] + b.count), columnar=True)
        return svc, rt, got

    def _body(self, n=200):
        rng = np.random.default_rng(5)
        cols = {
            "symbol": np.array([f"S{int(k)}"
                                for k in rng.integers(1, 10, n)],
                               dtype=object),
            "price": rng.uniform(1.0, 1000.0, n),
            "volume": rng.integers(1, 100, n).astype(np.int64),
        }
        plan = wire.schema_plan(
            compiler.parse(APP).stream_definitions["TradeStream"])
        expected = int((cols["price"] < 700.0).sum())
        return wire.encode_frames(plan, cols, n, chunk=64), expected

    def test_send_frames_delivers(self):
        svc, rt, got = self._deploy()
        try:
            body, expected = self._body()
            assert svc.send_frames("WireApp", "TradeStream", body) == 200
            rt.flush()
            rt.drain()
            assert got[0] == expected
        finally:
            svc.undeploy("WireApp")

    def test_http_frames_endpoint(self):
        svc, rt, got = self._deploy()
        server = svc.make_server(port=0)  # ephemeral port
        port = server.server_address[1]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            body, expected = self._body()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/siddhi-apps/WireApp/streams/"
                "TradeStream", data=body,
                headers={"Content-Type": "application/x-siddhi-frames"})
            with urllib.request.urlopen(req) as resp:
                assert json.loads(resp.read())["accepted"] == 200
            rt.flush()
            rt.drain()
            assert got[0] == expected

            # malformed body → 400, not a 500 traceback
            bad = urllib.request.Request(
                f"http://127.0.0.1:{port}/siddhi-apps/WireApp/streams/"
                "TradeStream", data=b"\x10\x00\x00\x00garbagegarbagegar",
                headers={"Content-Type": "application/x-siddhi-frames"})
            try:
                urllib.request.urlopen(bad)
                raise AssertionError("expected HTTP 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            server.shutdown()
            svc.undeploy("WireApp")

    def test_json_path_unaffected(self):
        svc, rt, got = self._deploy()
        try:
            n = svc.send("WireApp", "TradeStream",
                         [["S1", 10.0, 5], ["S2", 900.0, 6]])
            assert n == 2
            rt.drain()
            assert got[0] == 1  # 900.0 filtered out
        finally:
            svc.undeploy("WireApp")


class TestFrameSourceMapper:
    def test_mapper_roundtrip(self):
        from siddhi_tpu.io.broker import InMemoryBroker

        app = """
        @app:name('FrameSrc')
        @source(type='inMemory', topic='frames', @map(type='frame'))
        define stream TradeStream (symbol string, price double, volume long);
        @info(name='q')
        from TradeStream select symbol, price, volume insert into OutStream;
        """
        rt = SiddhiManager().create_siddhi_app_runtime(app)
        rows: list = []
        rt.add_callback("OutStream",
                        lambda evs: rows.extend(tuple(e.data) for e in evs))
        rt.start()
        try:
            plan = wire.schema_plan(
                rt.junctions["TradeStream"].definition)
            cols = {
                "symbol": np.array(["A", None, "B"], dtype=object),
                "price": np.array([1.5, 2.5, 3.5]),
                "volume": np.array([10, 20, 30], dtype=np.int64),
            }
            InMemoryBroker.publish("frames",
                                   wire.encode_frames(plan, cols, 3))
            rt.flush()
            rt.drain()
        finally:
            rt.shutdown()
        assert [r[0] for r in rows] == ["A", None, "B"]
        assert [r[2] for r in rows] == [10, 20, 30]
