"""Optimizer mechanics: group formation, the decline taxonomy (loud, with
SL114 anchoring), statistics/Prometheus surfaces, compile-count sublinearity,
and the dark-sink re-light path. Output CORRECTNESS under fusion lives in
tests/test_optimizer_parity.py — this file tests the machinery around it."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.analysis import analyze_sharing
from siddhi_tpu.analysis.optimizer import (
    DECLINE_BREAKER,
    DECLINE_OBJECT,
    DECLINE_PARTITION,
)

pytestmark = pytest.mark.smoke

STREAM = "define stream S (symbol string, price double, volume long);\n"

FUSABLE = (STREAM +
           "@info(name='a') from S[price > 10.0] select symbol, price "
           "insert into OutA;\n"
           "@info(name='b') from S[price > 20.0] select symbol, volume "
           "insert into OutB;\n"
           "@info(name='c') from S select symbol insert into OutC;\n")


def _runtime(app, **kw):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app, batch_size=8, **kw)
    return m, rt


def _feed(rt, n=12):
    h = rt.get_input_handler("S")
    for i in range(n):
        h.send(("IBM", 5.0 * i, i), timestamp=1000 + i)
    rt.flush()


# ----------------------------------------------------------------- opt-in


class TestOptIn:
    def test_off_by_default(self):
        m, rt = _runtime(FUSABLE)
        assert rt.optimizer_report is None or \
            not rt.optimizer_report.get("enabled")
        assert not getattr(rt, "shared_groups", ())
        m.shutdown()

    def test_app_annotation_opts_in(self):
        m, rt = _runtime("@app:optimize\n" + FUSABLE)
        assert rt.optimizer_report["queries_fused"] == 3
        m.shutdown()

    def test_env_var_opts_in(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_OPTIMIZE", "1")
        m, rt = _runtime(FUSABLE)
        assert rt.optimizer_report["queries_fused"] == 3
        m.shutdown()

    def test_kwarg_wins_over_annotation(self):
        m, rt = _runtime("@app:optimize\n" + FUSABLE, optimize=False)
        assert not getattr(rt, "shared_groups", ())
        m.shutdown()


# ------------------------------------------------------------- formation


class TestFormation:
    def test_groups_are_contiguous_runs(self):
        m, rt = _runtime(FUSABLE, optimize=True)
        groups = rt.shared_groups
        assert len(groups) == 1 and len(groups[0].members) == 3
        # delivery order preserved: members in source order
        assert [q.name for q in groups[0].members] == ["a", "b", "c"]
        m.shutdown()

    def test_group_cap_chunks_long_runs(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_OPTIMIZE_GROUP_CAP", "4")
        app = STREAM + "".join(
            f"@info(name='q{i}') from S[price > {i}.0] select symbol "
            f"insert into Out{i};\n" for i in range(10))
        m, rt = _runtime(app, optimize=True)
        sizes = sorted(len(g.members) for g in rt.shared_groups)
        assert sizes == [2, 4, 4]   # 10 split at cap=4, remainder kept
        assert sum(sizes) == 10
        m.shutdown()

    def test_single_query_never_grouped(self):
        app = STREAM + ("@info(name='only') from S select symbol "
                        "insert into Out;\n")
        m, rt = _runtime(app, optimize=True)
        assert not rt.shared_groups
        assert rt.optimizer_report["groups"] == 0
        m.shutdown()


# ---------------------------------------------------------------- declines


class TestDeclines:
    """The small-fix satellite: the optimizer declines LOUDLY — report +
    SL114 note — and never silently fuses different isolation semantics."""

    def _declined(self, app, qname):
        m, rt = _runtime(app, optimize=True)
        rep = rt.optimizer_report
        fused = {name for g in rt.shared_groups for name in
                 (q.name for q in g.members)}
        assert qname not in fused
        m.shutdown()
        return rep["declined"]

    def test_breaker_declines(self):
        app = (STREAM +
               "@info(name='plain') from S select symbol insert into O1;\n"
               "@info(name='frag') from S[price > 0.0] select symbol "
               "insert into O2;\n")
        app = app.replace("@info(name='frag')",
                          "@breaker(threshold='2')\n@info(name='frag')")
        declined = self._declined(app, "frag")
        assert declined.get("frag") == DECLINE_BREAKER

    def test_partition_declines(self):
        app = (STREAM +
               "@info(name='top') from S select symbol insert into O1;\n"
               "@info(name='top2') from S select volume insert into O2;\n"
               "partition with (symbol of S) begin "
               "@info(name='inner') from S select symbol, price "
               "insert into POut; end;\n")
        m, rt = _runtime(app, optimize=True)
        fused = {name for g in rt.shared_groups for name in
                 (q.name for q in g.members)}
        assert "inner" not in fused
        assert rt.optimizer_report["declined"].get(
            "inner") == DECLINE_PARTITION
        m.shutdown()

    def test_object_attribute_declines(self):
        app = ("define stream S (symbol string, payload object);\n"
               "@info(name='x') from S select symbol insert into O1;\n"
               "@info(name='y') from S[symbol == 'IBM'] select symbol "
               "insert into O2;\n")
        m, rt = _runtime(app, optimize=True)
        assert not rt.shared_groups
        reasons = set(rt.optimizer_report["declined"].values())
        assert reasons == {DECLINE_OBJECT}
        m.shutdown()

    def test_lone_query_declines_nothing(self):
        # a decline is only reported when sharing was actually forgone
        app = STREAM + ("@breaker(threshold='2')\n@info(name='solo') "
                        "from S select symbol insert into O;\n")
        rep = analyze_sharing(__import__(
            "siddhi_tpu").compiler.parse(app), enabled=True)
        assert rep.declined == {}


# ------------------------------------------------------------------ SL114


class TestSL114:
    def test_validate_reports_shareable_work(self):
        report = SiddhiManager().validate(FUSABLE)
        notes = [d for d in report.diagnostics if d.rule_id == "SL114"]
        assert notes, [d.format() for d in report.diagnostics]
        assert "3 queries" in notes[0].message

    def test_validate_reports_decline(self):
        app = (STREAM +
               "@info(name='plain') from S select symbol insert into O1;\n"
               "@breaker(threshold='2')\n"
               "@info(name='frag') from S select symbol insert into O2;\n")
        report = SiddhiManager().validate(app)
        msgs = [d.message for d in report.diagnostics if d.rule_id == "SL114"]
        assert any("declines" in m and "@breaker" in m for m in msgs), msgs

    def test_no_note_without_sharing(self):
        app = STREAM + "from S select symbol insert into Out;\n"
        report = SiddhiManager().validate(app)
        assert not [d for d in report.diagnostics if d.rule_id == "SL114"]


# ------------------------------------------------------- stats & prometheus


class TestReporting:
    def test_statistics_report_section(self):
        m, rt = _runtime(FUSABLE, optimize=True)
        rt.start()
        _feed(rt)
        sec = rt.statistics_report()["optimizer"]
        assert sec["enabled"] is True
        assert sec["groups"] == 1
        assert sec["queries_fused"] == 3
        assert sec["compiles_avoided"] >= 2   # one shape compiled so far
        assert list(sec["group_members"].values()) == [["a", "b", "c"]]
        m.shutdown()

    def test_statistics_report_when_off(self):
        m, rt = _runtime(FUSABLE)
        rt.start()
        assert rt.statistics_report()["optimizer"] == {"enabled": False}
        m.shutdown()

    def test_per_query_attribution_survives_fusion(self):
        m, rt = _runtime(FUSABLE, optimize=True)
        rt.statistics.set_level("detail")
        rt.start()
        _feed(rt)
        lat = rt.statistics_report()["query_latency_ms"]
        for q in ("a", "b", "c"):
            assert q in lat, lat
        m.shutdown()

    def test_prometheus_families(self):
        from siddhi_tpu.telemetry.prometheus import render_manager
        m, rt = _runtime(FUSABLE, optimize=True)
        rt.start()
        _feed(rt)
        body = render_manager(m)
        for fam in ("siddhi_optimizer_enabled", "siddhi_optimizer_groups",
                    "siddhi_optimizer_queries_fused",
                    "siddhi_optimizer_compiles_avoided_total"):
            assert fam in body, fam
        m.shutdown()


# --------------------------------------------------------- compile counts


class TestCompileCounts:
    def test_fused_compiles_once_per_group(self):
        app = STREAM + "".join(
            f"@info(name='q{i}') from S[price > {i}.0] select symbol "
            f"insert into Out{i};\n" for i in range(8))
        m, rt = _runtime(app, optimize=True)
        rt.start()
        _feed(rt, n=8)   # one full batch, one shape
        comp = rt.statistics_report()["compiles"]
        group_compiles = sum(v for k, v in comp.items()
                             if k.startswith("shared:"))
        member_compiles = sum(v for k, v in comp.items()
                              if k.startswith("q"))
        assert group_compiles == 1
        assert member_compiles == 0
        m.shutdown()


# ------------------------------------------------------------- dark sinks


class TestDarkSinkRelight:
    def test_late_callback_relights_member(self):
        """Dark members' outputs are dead-code-eliminated from the fused
        graph; attaching a callback mid-run must rebuild the jit (one
        retrace) and deliver from the next batch on."""
        m, rt = _runtime(FUSABLE, optimize=True)
        got_a, got_b = [], []
        rt.add_callback("OutA", lambda evs: got_a.extend(
            tuple(e.data) for e in evs))
        rt.start()
        _feed(rt, n=8)
        assert got_a and not got_b
        # OutB was dark through that batch; light it up now
        rt.add_callback("OutB", lambda evs: got_b.extend(
            tuple(e.data) for e in evs))
        h = rt.get_input_handler("S")
        for i in range(8):
            h.send(("IBM", 100.0 + i, i), timestamp=2000 + i)
        rt.flush()
        assert got_b, "re-lit member delivered nothing"
        assert len(got_b) == 8
        m.shutdown()
