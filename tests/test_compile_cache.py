"""Shape-bucketed compile cache, AOT warmup, and the watchdogged bench.

Covers the round-6 perf tentpole:
- junctions pad partial micro-batches to power-of-two lane buckets, so a
  shape-polymorphic query step compiles at most log2(max_batch)+1 variants
  (visible through the new per-query compile counter in Statistics);
- padded (bucketed) execution is bit-identical to full-capacity execution;
- AOT warmup precompiles the whole ladder at start();
- bench.py can never go dark again: a deliberately-hung config is bounded
  by the parent-side watchdog and still yields a JSON line from partials.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core import dtypes
from siddhi_tpu.errors import SiddhiAppCreationError

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")

FILTER_APP = """
define stream S (symbol string, price double, volume long);
@info(name = 'q')
from S[700.0 > price]
select symbol, price
insert into Out;
"""


@pytest.fixture
def buckets_on():
    prev = dtypes.config.shape_buckets
    dtypes.config.shape_buckets = True
    yield
    dtypes.config.shape_buckets = prev


def _feed_and_collect(app, sizes, *, batch_size=8192, **kw):
    rt = SiddhiManager().create_siddhi_app_runtime(
        app, batch_size=batch_size, **kw)
    got = []
    out_id = next(ln.split("insert into ")[1].split(";")[0].strip()
                  for ln in app.splitlines() if "insert into" in ln)
    rt.add_callback(out_id, lambda evs: got.extend(
        (e.data, e.is_expired) for e in evs))
    rt.start()
    h = rt.get_input_handler("S")
    ts = 1
    for n in sizes:
        rows = [(f"S{i % 50}", float(i % 900), i) for i in range(n)]
        h.send_batch(rows, timestamps=list(range(ts, ts + n)))
        ts += n
        rt.flush()
    compiles = dict(rt.statistics.compiles)
    widths = {q: list(w) for q, w in rt.statistics.compile_widths.items()}
    rt.shutdown()
    return got, compiles, widths


class TestBucketLadder:
    def test_bucket_capacity_math(self):
        assert dtypes.bucket_capacity(0, 8192) == dtypes.config.min_bucket
        assert dtypes.bucket_capacity(1, 8192) == 16
        assert dtypes.bucket_capacity(16, 8192) == 16
        assert dtypes.bucket_capacity(17, 8192) == 32
        assert dtypes.bucket_capacity(8191, 8192) == 8192
        assert dtypes.bucket_capacity(9000, 8192) == 8192
        # non-power-of-two capacity stays the top rung
        assert dtypes.bucket_capacity(200, 200) == 200
        assert dtypes.bucket_ladder(200)[-1] == 200

    def test_ladder_is_log2_bounded(self):
        for cap in (16, 100, 256, 8192, 131072):
            ladder = dtypes.bucket_ladder(cap)
            assert ladder[-1] == cap
            assert len(ladder) <= int(math.log2(max(cap, 2))) + 1
            assert list(ladder) == sorted(set(ladder))


class TestCompileCountStability:
    """Acceptance: one query fed batches of sizes {1, 7, 100, 8192}
    compiles <= log2(max_batch)+1 variants, bit-identical to unpadded."""

    SIZES = (1, 7, 100, 8192, 7, 1, 8192, 100)

    def test_filter_query_log2_bound_and_bit_identity(self, buckets_on):
        got_b, compiles_b, widths_b = _feed_and_collect(
            FILTER_APP, self.SIZES)
        bound = int(math.log2(8192)) + 1
        assert 0 < compiles_b["q"] <= bound
        # repeats of a seen size never retrace: distinct widths == compiles
        assert len(set(widths_b["q"])) == compiles_b["q"]

        dtypes.config.shape_buckets = False
        got_u, compiles_u, _ = _feed_and_collect(FILTER_APP, self.SIZES)
        assert compiles_u["q"] == 1  # always padded to full capacity
        assert got_b == got_u  # bit-identical decode (values + order)

    def test_sliding_window_query_bit_identity(self, buckets_on):
        app = """
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.time(60 sec)
        select symbol, distinctCount(symbol) as d
        insert into Out;
        """
        sizes = (1, 7, 100, 256, 3)
        got_b, compiles_b, _ = _feed_and_collect(app, sizes, batch_size=256)
        assert 0 < compiles_b["q"] <= int(math.log2(256)) + 1
        dtypes.config.shape_buckets = False
        got_u, _, _ = _feed_and_collect(app, sizes, batch_size=256)
        assert got_b == got_u

    def test_shape_baked_window_pads_to_one_compile(self, buckets_on):
        # lengthBatch is NOT shape-polymorphic: the runtime pads bucketed
        # deliveries back to full capacity — exactly one compile, same
        # results as with bucketing disabled
        app = """
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.lengthBatch(5)
        select symbol, sum(volume) as total
        insert into Out;
        """
        sizes = (1, 7, 100, 3, 13)
        got_b, compiles_b, _ = _feed_and_collect(app, sizes, batch_size=128)
        assert compiles_b["q"] == 1
        dtypes.config.shape_buckets = False
        got_u, _, _ = _feed_and_collect(app, sizes, batch_size=128)
        assert got_b == got_u


class TestAotWarmup:
    def test_start_precompiles_ladder_then_traffic_adds_none(
            self, buckets_on):
        rt = SiddhiManager().create_siddhi_app_runtime(
            FILTER_APP, batch_size=1024, aot_warmup=True)
        rt.start()
        ladder = dtypes.bucket_ladder(1024)
        assert rt.statistics.compiles["q"] == len(ladder)
        assert sorted(rt.statistics.compile_widths["q"]) == sorted(ladder)
        h = rt.get_input_handler("S")
        for n in (1, 5, 1000, 1024):
            h.send_batch([(f"S{i}", 1.0, i) for i in range(n)])
            rt.flush()
        assert rt.statistics.compiles["q"] == len(ladder)  # zero retraces
        rt.shutdown()

    def test_warmup_method_returns_compile_counts(self, buckets_on):
        rt = SiddhiManager().create_siddhi_app_runtime(
            FILTER_APP, batch_size=256)
        fresh = rt.warmup()
        assert fresh["q"] == len(dtypes.bucket_ladder(256))
        assert rt.warmup()["q"] == 0  # second warmup: all cached

    def test_warmup_does_not_disturb_live_state(self, buckets_on):
        app = """
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.lengthBatch(3)
        select symbol, sum(volume) as total
        insert into Out;
        """
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=64)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(
            e.data for e in evs if not e.is_expired))
        rt.start()
        h = rt.get_input_handler("S")
        h.send_batch([("a", 1.0, 1), ("a", 1.0, 2)])
        rt.flush()
        rt.warmup()  # state copies only: the partial window must survive
        h.send_batch([("a", 1.0, 3)])
        rt.flush()
        assert [d[1] for d in got][-3:] == [1, 3, 6]
        rt.shutdown()


class TestStatisticsSurface:
    def test_report_exposes_compiles_and_step_hist(self, buckets_on):
        rt = SiddhiManager().create_siddhi_app_runtime(
            FILTER_APP, batch_size=64)
        rt.set_statistics_level("DETAIL")
        rt.start()
        h = rt.get_input_handler("S")
        h.send_batch([("a", 1.0, 1)])
        rt.flush()
        rep = rt.statistics_report()
        assert rep["compiles"]["q"] >= 1
        assert rep["compile_widths"]["q"]
        hist = rep["step_time_hist_us"]["q"]
        assert sum(hist.values()) >= 1
        assert all(b > 0 and (b & (b - 1)) == 0 for b in hist)  # pow2 buckets
        rt.shutdown()


class TestSetProjectionProvenance:
    """ADVICE r5: sizeOfSet over an ORDINARY long column must raise instead
    of silently forwarding the value; provenance-marked forwarded unionSet
    columns keep working (chained stream + insert-into table)."""

    def test_plain_long_rejected(self):
        app = ("define stream S (sym string, n long);\n"
               "@info(name='fw') from S select sym, n insert into Mid;\n"
               "@info(name='rd') from Mid select sizeOfSet(n) as c "
               "insert into Out;")
        with pytest.raises(SiddhiAppCreationError, match="sizeOfSet"):
            SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)

    def test_forwarded_union_set_still_readable(self):
        app = ("define stream S (sym string);\n"
               "@info(name='fw') from S select unionSet(sym) as s "
               "insert into Mid;\n"
               "@info(name='rd') from Mid select sizeOfSet(s) as c "
               "insert into Out;")
        rt = SiddhiManager().create_siddhi_app_runtime(app, batch_size=8)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(
            e.data[0] for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for x in ("a", "b", "a", "c"):
            h.send((x,))
            rt.flush()
        assert got == [1, 2, 2, 3]
        rt.shutdown()


class TestBenchWatchdog:
    """Acceptance: per-config watchdogs provably bound a deliberately-hung
    config — the `_hang` hidden config swallows the in-process alarm, so
    only the parent-side deadline can stop it, and the emitted JSON line
    must still carry the partial numbers."""

    def test_hung_config_is_bounded_and_yields_partial_json(self):
        budget = 6
        t0 = time.monotonic()
        r = subprocess.run(
            [sys.executable, BENCH, "_hang",
             f"--config-seconds={budget}", "--max-seconds=30"],
            capture_output=True, text=True, timeout=90,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        elapsed = time.monotonic() - t0
        assert elapsed < 60, f"watchdog failed to bound the hang: {elapsed}s"
        lines = [ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")]
        assert lines, r.stdout + r.stderr
        res = json.loads(lines[-1])
        assert res["partial"] is True
        assert "timeout" in res["error"]
        assert res["stage_one"] == 1.0  # checkpointed number survived


@pytest.mark.smoke
@pytest.mark.slow
def test_bench_filter_bounded_smoke():
    """Smoke tier: a bounded `bench.py filter --max-seconds=60` run emits a
    JSON line with the device-path number within the budget (possibly
    tagged partial if the e2e leg did not fit — the device measure itself
    compiles and runs in seconds on CPU)."""
    r = subprocess.run(
        [sys.executable, BENCH, "filter",
         "--config-seconds=55", "--max-seconds=60"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 SIDDHI_E2E_BATCH="16384"))
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, r.stdout + r.stderr
    res = json.loads(lines[-1])
    assert res.get("metric", "").startswith("filter")
    assert isinstance(res.get("value"), (int, float)), res
