"""Sanitizer gate for the lock-free columnar ring (native/colring_core.h).

Builds native/colring_stress.c with -fsanitize=thread, then with
-fsanitize=address,undefined, and runs the multi-producer stress under
each. A data race, UB, leak, or oracle failure (conservation / integrity /
checksum / quiescence) fails the test. Skipped when no gcc is available —
CI always has one, so the protocol stays machine-checked there.
"""

import shutil
import subprocess
from pathlib import Path

import pytest

SANITIZE = Path(__file__).parent.parent / "native" / "sanitize.sh"

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None, reason="gcc not available")


def test_colring_stress_sanitizer_clean(tmp_path):
    proc = subprocess.run(
        ["sh", str(SANITIZE), "4", "100000", "512", "17"],
        capture_output=True, text=True, timeout=300,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "TMPDIR": str(tmp_path)},
    )
    assert proc.returncode == 0, (
        f"sanitize.sh failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "OK" in proc.stdout
    assert "clean under tsan and asan+ubsan" in proc.stdout
