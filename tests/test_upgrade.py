"""Blue-green hot-swap + deterministic WAL replay (core/upgrade.py).

Covers the full contract: the SL3xx plan-diff classification and its force
gating, the conservation invariant (every accepted event processed by
exactly one version, zero loss / zero dupes under live traffic), window
state carrying across the swap byte-for-byte, rollback leaving v1 exactly
as it was, the fingerprint gate refusing cross-structure restores outside
the upgrade path, bit-identical accelerated-clock replay, and the REST
surface (upgrade / replay / errors endpoints).
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from siddhi_tpu import SiddhiManager, compiler
from siddhi_tpu.analysis.upgrade import diff_apps
from siddhi_tpu.errors import CannotRestoreStateError, SiddhiAppCreationError
from siddhi_tpu.service import SiddhiService
from siddhi_tpu.state.persistence import InMemoryPersistenceStore

pytestmark = pytest.mark.smoke

V1 = """@app:name('Up')
define stream S (k string, v long);
@info(name='q') from S#window.length(4)
select count() as c, sum(v) as s insert into Out;
"""

# adds a query: SL305 (INFO) only -> compatible, q's state carries over
V2_ADD = """@app:name('Up')
define stream S (k string, v long);
@info(name='q') from S#window.length(4)
select count() as c, sum(v) as s insert into Out;
@info(name='mirror') from S select k, v insert into Mirror;
"""

# changes q's window: SL303 (WARN) -> state-migratable, needs force=True
V2_CHANGED = """@app:name('Up')
define stream S (k string, v long);
@info(name='q') from S#window.length(6)
select count() as c, sum(v) as s insert into Out;
"""

# renames the app: SL301 (ERROR) -> incompatible
V2_RENAMED = V1.replace("name('Up')", "name('Up2')")

# changes the consumed stream's column layout: SL302 (ERROR) -> incompatible
V2_SCHEMA = V1.replace("(k string, v long)", "(k string, v long, w long)")


def _value(i: int) -> int:
    return (i * 7 + 3) % 101


# --------------------------------------------------------------------------- #
# plan-graph diff (analysis/upgrade.py)
# --------------------------------------------------------------------------- #


class TestDiff:
    def test_added_query_is_compatible(self):
        d = diff_apps(compiler.parse(V1), compiler.parse(V2_ADD))
        assert d.classification == "compatible"
        assert "query:q" in d.migratable
        assert "query:mirror" in d.added
        assert {x.rule_id for x in d.report.diagnostics} == {"SL305"}

    def test_identical_apps_are_compatible(self):
        d = diff_apps(compiler.parse(V1), compiler.parse(V1))
        assert d.classification == "compatible"
        assert d.old_fingerprint == d.new_fingerprint
        assert not d.report.diagnostics

    def test_changed_query_is_state_migratable(self):
        d = diff_apps(compiler.parse(V1), compiler.parse(V2_CHANGED))
        assert d.classification == "state-migratable"
        assert "query:q" in d.changed
        assert "SL303" in {x.rule_id for x in d.report.diagnostics}
        # the changed query must NOT land in the restore filter
        assert "q" not in d.restore_elements().get("queries", set())

    def test_rename_is_incompatible(self):
        d = diff_apps(compiler.parse(V1), compiler.parse(V2_RENAMED))
        assert d.is_incompatible
        assert "SL301" in {x.rule_id for x in d.report.diagnostics}

    def test_schema_change_is_incompatible(self):
        d = diff_apps(compiler.parse(V1), compiler.parse(V2_SCHEMA))
        assert d.is_incompatible
        assert "SL302" in {x.rule_id for x in d.report.diagnostics}


# --------------------------------------------------------------------------- #
# hot swap (core/upgrade.py upgrade_app)
# --------------------------------------------------------------------------- #


def _boot(app=V1, store=True, **kw):
    mgr = SiddhiManager()
    if store:
        mgr.set_persistence_store(InMemoryPersistenceStore())
    rt = mgr.create_siddhi_app_runtime(app, batch_size=4, **kw)
    out = []
    rt.add_callback("Out", lambda evs: out.extend(tuple(e.data) for e in evs))
    rt.start()
    return mgr, rt, out


class TestHotSwap:
    @pytest.mark.parametrize("store", [True, False],
                             ids=["persist-store", "snapshot-only"])
    def test_window_state_carries_across_swap(self, store):
        mgr, rt1, out = _boot(store=store)
        h = rt1.get_input_handler("S")
        for i, v in enumerate((1, 2, 3)):
            h.send(("k", v), timestamp=1_000 + i)
        rt1.flush()
        summary = mgr.upgrade(V2_ADD)
        assert summary["status"] == "swapped"
        assert summary["classification"] == "compatible"
        assert "query:q" in summary["migrated"]
        assert summary["cutover_pause_ms"] > 0
        rt2 = mgr.runtimes["Up"]
        assert rt2 is not rt1
        # the migrated callback keeps firing; the pre-swap window rows are
        # inside v2's state, so the 4-slot window now holds 1+2+3+10
        rt2.get_input_handler("S").send(("k", 10), timestamp=1_010)
        rt2.flush()
        assert out[-1] == (4, 16)
        rep = rt2.statistics_report()["upgrade"]
        assert rep["upgrades"] == 1 and rep["rollbacks"] == 0
        rt2.shutdown()

    def test_old_input_handler_forwards_through_redirect(self):
        mgr, rt1, out = _boot()
        h1 = rt1.get_input_handler("S")  # captured BEFORE the swap
        h1.send(("k", 5), timestamp=1_000)
        rt1.flush()
        mgr.upgrade(V2_ADD)
        rt2 = mgr.runtimes["Up"]
        h1.send(("k", 7), timestamp=1_001)  # stale handle: v1 junction
        rt2.flush()
        assert out[-1] == (2, 12)
        rt2.shutdown()

    def test_state_migratable_requires_force(self):
        mgr, rt1, out = _boot()
        rt1.get_input_handler("S").send(("k", 9), timestamp=1_000)
        rt1.flush()
        with pytest.raises(SiddhiAppCreationError, match="force=True"):
            mgr.upgrade(V2_CHANGED)
        # the refusal happened before any quiescing: v1 untouched & live
        assert mgr.runtimes["Up"] is rt1
        rt1.get_input_handler("S").send(("k", 1), timestamp=1_001)
        rt1.flush()
        assert out[-1] == (2, 10)
        # force accepts the state loss: q restarts empty
        summary = mgr.upgrade(V2_CHANGED, force=True)
        assert summary["classification"] == "state-migratable"
        rt2 = mgr.runtimes["Up"]
        rt2.get_input_handler("S").send(("k", 3), timestamp=1_002)
        rt2.flush()
        assert out[-1] == (1, 3)
        rt2.shutdown()

    def test_incompatible_upgrade_is_refused(self):
        mgr, rt1, out = _boot()
        with pytest.raises(SiddhiAppCreationError, match="SL302"):
            mgr.upgrade(V2_SCHEMA)
        assert mgr.runtimes["Up"] is rt1
        rt1.shutdown()

    def test_failed_swap_rolls_back_to_working_v1(self, monkeypatch):
        from siddhi_tpu.core.app_runtime import SiddhiAppRuntime
        mgr, rt1, out = _boot()
        h = rt1.get_input_handler("S")
        h.send(("k", 4), timestamp=1_000)
        rt1.flush()

        def boom(self, blob, *, elements=None):
            raise RuntimeError("injected restore failure")

        monkeypatch.setattr(SiddhiAppRuntime, "restore", boom)
        with pytest.raises(RuntimeError, match="injected restore failure"):
            mgr.upgrade(V2_ADD)
        monkeypatch.undo()
        # v1 is still the registered runtime and still fully functional:
        # WAL back, callbacks back, sources resumed, async pipelines up
        assert mgr.runtimes["Up"] is rt1
        h.send(("k", 6), timestamp=1_001)
        rt1.flush()
        assert out[-1] == (2, 10)
        rep = rt1.statistics_report()["upgrade"]
        assert rep["rollbacks"] == 1 and rep["upgrades"] == 0
        rt1.shutdown()

    def test_conservation_under_live_traffic(self):
        """Zero-downtime invariant: a producer hammering the v1 input
        handler straight through the swap loses nothing and duplicates
        nothing — every event is processed by exactly one version."""
        app_v1 = ("@app:name('Cons')\n"
                  "define stream S (k string, v long);\n"
                  "@info(name='q') from S select k, v insert into Out;")
        app_v2 = app_v1 + ("\n@info(name='extra') from S "
                           "select v insert into Copy;")
        mgr = SiddhiManager()
        mgr.set_persistence_store(InMemoryPersistenceStore())
        rt1 = mgr.create_siddhi_app_runtime(app_v1, batch_size=8)
        seen = []
        rt1.add_callback("Out",
                         lambda evs: seen.extend(e.data[1] for e in evs))
        rt1.start()
        from siddhi_tpu.util.faults import apply_fault_spec
        apply_fault_spec(rt1)  # no-op unless SIDDHI_FAULT_SPEC seeds chaos
        h = rt1.get_input_handler("S")
        n = 2_000
        started = threading.Event()

        def produce():
            for i in range(n):
                h.send((f"k{i % 7}", i), timestamp=1_000 + i)
                if i == n // 8:
                    started.set()
                if i % 64 == 0:
                    mgr.runtimes["Cons"].flush()

        t = threading.Thread(target=produce)
        t.start()
        started.wait(timeout=30)
        summary = mgr.upgrade(app_v2)
        assert summary["status"] == "swapped"
        t.join(timeout=60)
        assert not t.is_alive()
        rt2 = mgr.runtimes["Cons"]
        rt2.drain()
        assert sorted(seen) == list(range(n))  # no loss, no dupes
        rt2.shutdown()

    def test_inmemory_source_transport_carries_over(self):
        """A live @source transport survives the swap: payloads published
        before, during (buffered while paused), and after all land in
        exactly one version's pipeline."""
        from siddhi_tpu.io import InMemoryBroker
        src_v1 = ("@app:name('Src')\n"
                  "@source(type='inMemory', topic='upg')\n"
                  "define stream S (k string, v long);\n"
                  "@info(name='q') from S select k, v insert into Out;")
        src_v2 = src_v1 + ("\n@info(name='extra') from S "
                           "select v insert into Copy;")
        mgr = SiddhiManager()
        mgr.set_persistence_store(InMemoryPersistenceStore())
        rt1 = mgr.create_siddhi_app_runtime(src_v1, batch_size=4)
        seen = []
        rt1.add_callback("Out",
                         lambda evs: seen.extend(e.data[1] for e in evs))
        rt1.start()
        try:
            InMemoryBroker.publish("upg", ("a", 1))
            mgr.upgrade(src_v2)
            rt2 = mgr.runtimes["Src"]
            # the transport moved over: v2 owns it for backpressure and
            # teardown, and a fresh publish flows into the v2 pipeline
            assert len(rt2.sources) >= 1
            InMemoryBroker.publish("upg", ("b", 2))
            rt2.drain()
            assert sorted(seen) == [1, 2]
            rt2.shutdown()
        finally:
            InMemoryBroker.clear()


# --------------------------------------------------------------------------- #
# fingerprint gate (state/persistence.py) — upgrade is the only sanctioned
# cross-structure restore path
# --------------------------------------------------------------------------- #


class TestFingerprintGate:
    def test_full_restore_refuses_cross_structure_snapshot(self):
        store = InMemoryPersistenceStore()
        mgr = SiddhiManager()
        mgr.set_persistence_store(store)
        rt = mgr.create_siddhi_app_runtime(V1, batch_size=4)
        rt.start()
        rt.get_input_handler("S").send(("k", 1), timestamp=1_000)
        rt.flush()
        rev = rt.persist()
        rt.shutdown()
        # same app NAME, different structure: a full restore must refuse
        mgr2 = SiddhiManager()
        mgr2.set_persistence_store(store)
        rt2 = mgr2.create_siddhi_app_runtime(V2_CHANGED, batch_size=4)
        rt2.start()
        blob = store.load("Up", rev)
        with pytest.raises(CannotRestoreStateError, match="fingerprint"):
            rt2.restore(blob)
        # the element-mapped form (what the upgrade path feeds) is allowed
        rt2.restore(blob, elements={"queries": set()})
        rt2.shutdown()

    def test_same_structure_restore_passes_the_gate(self):
        store = InMemoryPersistenceStore()
        mgr = SiddhiManager()
        mgr.set_persistence_store(store)
        rt = mgr.create_siddhi_app_runtime(V1, batch_size=4)
        rt.start()
        rt.get_input_handler("S").send(("k", 5), timestamp=1_000)
        rt.flush()
        rev = rt.persist()
        rt.shutdown()
        mgr2 = SiddhiManager()
        mgr2.set_persistence_store(store)
        rt2 = mgr2.create_siddhi_app_runtime(V1, batch_size=4)
        out = []
        rt2.add_callback("Out",
                         lambda evs: out.extend(tuple(e.data) for e in evs))
        rt2.start()
        rt2.restore(store.load("Up", rev))
        rt2.get_input_handler("S").send(("k", 7), timestamp=1_001)
        rt2.flush()
        assert out[-1] == (2, 12)
        rt2.shutdown()


# --------------------------------------------------------------------------- #
# deterministic accelerated-clock replay (core/upgrade.py replay_wal)
# --------------------------------------------------------------------------- #

RAPP = """@app:name('Rp')
define stream S (k string, v long);
@info(name='q') from S#window.length(4)
select k, sum(v) as s insert into Out;
"""


def _record_journal(tmp_path, n=25):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(RAPP, batch_size=4,
                                       wal_dir=str(tmp_path))
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(n):
        h.send((f"k{i % 3}", _value(i)), timestamp=1_000 + i * 10)
        rt.flush()
    rt.shutdown()


class TestReplay:
    def test_replay_is_bit_identical_across_runs(self, tmp_path):
        _record_journal(tmp_path)
        mgr = SiddhiManager()
        r1 = mgr.replay(RAPP, str(tmp_path))
        r2 = mgr.replay(RAPP, str(tmp_path))
        assert r1["events"] == r2["events"] == 25
        assert r1["records"] == 25 and r1["skipped"] == 0
        assert r1["digest"] == r2["digest"]
        assert r1["outputs"] == r2["outputs"]
        assert r1["outputs"]["S"] == 25
        assert r1["virtual_ms"] == 240  # journal time, not wall time

    def test_replay_against_candidate_app(self, tmp_path):
        """What-if: the same journal driven through a CHANGED candidate is
        still deterministic, and its output differs from the original's."""
        _record_journal(tmp_path)
        candidate = RAPP.replace("window.length(4)", "window.length(8)")
        mgr = SiddhiManager()
        base = mgr.replay(RAPP, str(tmp_path))
        c1 = mgr.replay(candidate, str(tmp_path))
        c2 = mgr.replay(candidate, str(tmp_path))
        assert c1["digest"] == c2["digest"]
        assert c1["digest"] != base["digest"]

    def test_replay_skips_streams_unknown_to_candidate(self, tmp_path):
        _record_journal(tmp_path)
        narrow = """@app:name('Rp')
define stream T (x long);
@info(name='q') from T select x insert into Out;
"""
        mgr = SiddhiManager()
        r = mgr.replay(narrow, str(tmp_path), app_name="Rp")
        assert r["events"] == 0 and r["skipped"] == 25

    def test_replay_speed_paces_the_virtual_clock(self, tmp_path):
        """speed=N scales journal-time gaps into wall-time sleeps through
        the injectable sleep — no real time passes in the test."""
        from siddhi_tpu.core.upgrade import replay_wal
        _record_journal(tmp_path, n=5)  # gaps: 4 x 10ms of journal time
        sleeps = []
        mgr = SiddhiManager()
        r = replay_wal(mgr, compiler.parse(RAPP), str(tmp_path),
                       speed=2.0, sleep=sleeps.append)
        assert r["events"] == 5
        assert len(sleeps) == 4
        assert sleeps == pytest.approx([0.005] * 4)  # 10ms / speed 2.0

    def test_replay_counts_on_live_runtime_statistics(self, tmp_path):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(RAPP, batch_size=4,
                                           wal_dir=str(tmp_path))
        rt.start()
        rt.get_input_handler("S").send(("k", 1), timestamp=1_000)
        rt.flush()
        mgr.replay(RAPP, str(tmp_path))
        rep = rt.statistics_report()["replay"]
        assert rep["runs"] == 1 and rep["events"] == 1
        rt.shutdown()


# --------------------------------------------------------------------------- #
# REST surface
# --------------------------------------------------------------------------- #


@pytest.fixture()
def server():
    svc = SiddhiService(token="secret-token")
    svc.manager.set_persistence_store(InMemoryPersistenceStore())
    httpd = svc.make_server(port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", svc
    httpd.shutdown()


def _req(url, method="GET", body=None):
    req = urllib.request.Request(
        url, data=body.encode() if body is not None else None, method=method)
    req.add_header("Authorization", "Bearer secret-token")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestRest:
    def _deploy(self, base, tmp_path):
        app = ("@app:name('R')\n"
               f"@app:persist(interval='1 hour', wal.dir='{tmp_path}')\n"
               "define stream S (k string, v long);\n"
               "@info(name='q') from S#window.length(4) "
               "select count() as c, sum(v) as s insert into Out;")
        code, _ = _req(f"{base}/siddhi-apps", "POST", app)
        assert code == 201
        return app

    def test_upgrade_replay_errors_endpoints(self, server, tmp_path):
        base, svc = server
        app = self._deploy(base, tmp_path)
        _req(f"{base}/siddhi-apps/R/streams/S", "POST",
             json.dumps({"events": [["a", 1], ["b", 2]]}))

        v2 = app + ("\n@info(name='mirror') from S "
                    "select k, v insert into Mirror;")
        code, body = _req(f"{base}/siddhi-apps/R/upgrade", "POST", v2)
        assert code == 200
        assert body["status"] == "swapped"
        assert body["classification"] == "compatible"
        assert body["revision"] is not None  # store present -> rotated

        # post-swap traffic flows into v2 and is journaled there
        code, _ = _req(f"{base}/siddhi-apps/R/streams/S", "POST",
                       json.dumps({"events": [["c", 3]]}))
        assert code == 200

        # the upgrade's persist() rotated the journal inside the cutover:
        # a replay now covers exactly the post-swap suffix — and twice over
        # it is bit-identical
        code, r1 = _req(f"{base}/siddhi-apps/R/replay", "POST", "{}")
        assert code == 200 and r1["events"] == 1
        code, r2 = _req(f"{base}/siddhi-apps/R/replay", "POST", "{}")
        assert code == 200 and r2["digest"] == r1["digest"]

        # error-store surface (default InMemoryErrorStore): empty list,
        # no-op replay
        code, body = _req(f"{base}/siddhi-apps/R/errors")
        assert code == 200 and body["errors"] == []
        code, body = _req(f"{base}/siddhi-apps/R/errors/replay", "POST",
                          "{}")
        assert code == 200 and body["replayed_entries"] == 0

        code, stats = _req(f"{base}/siddhi-apps/R/statistics")
        assert stats["upgrade"]["upgrades"] == 1
        assert stats["replay"]["runs"] == 2

    def test_upgrade_rejects_name_mismatch(self, server, tmp_path):
        base, _svc = server
        self._deploy(base, tmp_path)
        code, body = _req(f"{base}/siddhi-apps/R/upgrade", "POST",
                          V1)  # body deploys 'Up', URL names 'R'
        assert code == 400
        assert "must keep the app name" in body["error"]

    def test_incompatible_upgrade_returns_400(self, server, tmp_path):
        base, _svc = server
        app = self._deploy(base, tmp_path)
        bad = app.replace("(k string, v long)", "(k string)")
        code, body = _req(f"{base}/siddhi-apps/R/upgrade", "POST", bad)
        assert code == 400
        assert "SL302" in body["error"]

    def test_force_param_gates_state_migratable(self, server, tmp_path):
        base, _svc = server
        app = self._deploy(base, tmp_path)
        changed = app.replace("window.length(4)", "window.length(6)")
        code, body = _req(f"{base}/siddhi-apps/R/upgrade", "POST", changed)
        assert code == 400 and "force=True" in body["error"]
        code, body = _req(f"{base}/siddhi-apps/R/upgrade?force=true",
                          "POST", changed)
        assert code == 200
        assert body["classification"] == "state-migratable"

    def test_stored_error_listing_and_replay(self, server, tmp_path):
        base, svc = server
        self._deploy(base, tmp_path)
        rt = svc.manager.runtimes["R"]
        es = rt.ctx.error_store
        es.save("R", "S", [(1_000, ("x", 9))], cause="boom", kind="error")
        code, body = _req(f"{base}/siddhi-apps/R/errors")
        assert code == 200 and len(body["errors"]) == 1
        e = body["errors"][0]
        assert e["stream"] == "S" and e["kind"] == "error" \
            and e["events"] == 1
        code, body = _req(f"{base}/siddhi-apps/R/errors?kind=sink")
        assert code == 200 and body["errors"] == []
        code, body = _req(f"{base}/siddhi-apps/R/errors/replay", "POST",
                          json.dumps({"stream": "S"}))
        assert code == 200
        assert body == {"replayed_entries": 1, "replayed_events": 1}
        assert es.load("R") == []  # discarded only after acceptance
