"""Output rate-limiter behavioral tests (reference:
modules/siddhi-core/src/test/java/io/siddhi/core/query/ratelimit/ —
EventOutputRateLimitTestCase, TimeOutputRateLimitTestCase)."""

import pytest

from siddhi_tpu import SiddhiManager


pytestmark = pytest.mark.smoke

S = "define stream S (symbol string, price float);\n"


def build(app, batch_size=4, playback=True):
    text = ("@app:playback\n" if playback else "") + app
    rt = SiddhiManager().create_siddhi_app_runtime(text, batch_size=batch_size)
    rt.start()
    return rt


def q_callback(rt, name="q"):
    got = []
    rt.add_query_callback(name, lambda ts, i, r: got.extend(i or []))
    return got


class TestEventRateLimits:
    def test_output_last_every_3_events(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output last every 3 events insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, sym in enumerate("abcdef"):
            h.send((sym, float(i)))
        rt.flush()
        # every 3rd event emits, carrying the LAST of its group
        assert [e.data[0] for e in got] == ["c", "f"]

    def test_output_first_every_3_events(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output first every 3 events insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, sym in enumerate("abcdef"):
            h.send((sym, float(i)))
        rt.flush()
        assert [e.data[0] for e in got] == ["a", "d"]

    def test_output_all_every_2_events(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output all every 2 events insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, sym in enumerate("abcde"):
            h.send((sym, float(i)))
        rt.flush()
        # batches of 2 release buffered events; 'e' stays buffered
        assert [e.data[0] for e in got] == ["a", "b", "c", "d"]

    def test_carry_across_batches(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output last every 3 events insert into Out;", batch_size=2)
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, sym in enumerate("abcd"):
            h.send((sym, float(i)))
            rt.flush()
        assert [e.data[0] for e in got] == ["c"]


class TestSnapshotRateLimit:
    def test_snapshot_reemits_latest(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=200)
        rt.flush()
        rt.heartbeat(1_500)  # period boundary: snapshot of the latest row
        assert [e.data[0] for e in got] == ["b"]
        rt.heartbeat(2_500)  # no new events: the same snapshot re-emits
        assert [e.data[0] for e in got] == ["b", "b"]

    def test_snapshot_boundary_uses_pre_batch_row(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=900)
        rt.flush()
        # batch crossing the 1000ms boundary: snapshot shows 'a' (as of the
        # boundary), not the newly arrived 'b'
        h.send(("b", 2.0), timestamp=1_100)
        rt.flush()
        assert [e.data[0] for e in got] == ["a"]

    def test_grouped_snapshot_retains_last_row_per_group(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "group by symbol output snapshot every 1 sec "
                   "insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.append(
            sorted((e.data[0], e.data[1]) for e in i or [])))
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=200)
        h.send(("a", 3.0), timestamp=300)
        rt.flush()
        assert got == []  # bucket still open
        rt.heartbeat(1_500)
        # every group's LAST row re-emits at the boundary
        assert got == [[("a", 3.0), ("b", 2.0)]]
        # next period with no arrivals: snapshot repeats
        rt.heartbeat(2_500)
        assert got[-1] == [("a", 3.0), ("b", 2.0)]
        # update one group; others retained
        h.send(("b", 9.0), timestamp=2_600)
        rt.flush()
        rt.heartbeat(3_500)
        assert got[-1] == [("a", 3.0), ("b", 9.0)]

    def test_grouped_snapshot_with_aggregate(self):
        rt = build(S + "@info(name='q') from S select symbol, "
                   "sum(price) as total group by symbol "
                   "output snapshot every 1 sec insert into Out;")
        got = []
        rt.add_query_callback("q", lambda ts, i, r: got.append(
            sorted((e.data[0], e.data[1]) for e in i or [])))
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("a", 2.0), timestamp=200)
        h.send(("b", 5.0), timestamp=300)
        rt.flush()
        rt.heartbeat(1_500)
        assert got == [[("a", 3.0), ("b", 5.0)]]

class TestTimeRateLimits:
    def test_output_first_every_second(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output first every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=200)   # same window: suppressed
        rt.flush()
        h.send(("c", 3.0), timestamp=1_300)  # new window
        rt.flush()
        assert [e.data[0] for e in got] == ["a", "c"]

    def test_output_all_every_second_buffers(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output all every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=200)
        rt.flush()
        assert got == []  # buffered until the period elapses
        rt.heartbeat(1_500)
        assert [e.data[0] for e in got] == ["a", "b"]

    def test_output_last_every_second(self):
        rt = build(S + "@info(name='q') from S select symbol, price "
                   "output last every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=200)
        rt.flush()
        rt.heartbeat(1_500)
        assert [e.data[0] for e in got] == ["b"]


class TestBufferedLimiterOverflow:
    """The buffer ring only retains the newest C lanes; a bucket that
    accumulates more must truncate (oldest dropped) rather than replay newer
    lanes under stale ordinals (advisor finding, round 1)."""

    def test_time_bucket_overflow_truncates_to_newest(self):
        import jax.numpy as jnp

        from siddhi_tpu.core.event import EventBatch
        from siddhi_tpu.ops.ratelimit import BufferedLimiter

        layout = {"x": jnp.int32}
        lim = BufferedLimiter(layout, out_width=4, time_ms=1000, which="all")
        lim.C = 8  # shrink the ring to force overflow
        state = lim.init_state()

        def batch(vals, ts):
            b = len(vals)
            return EventBatch(
                ts=jnp.full((b,), ts, jnp.int64),
                cols={"x": jnp.asarray(vals, jnp.int32)},
                valid=jnp.ones((b,), bool),
                types=jnp.zeros((b,), jnp.int8))

        emitted = []
        # 12 lanes in bucket 0 overflow the C=8 ring
        for start in (0, 4, 8):
            state, out = lim.step(state, batch(range(start, start + 4), 100),
                                  jnp.int64(100))
            emitted.extend(out.cols["x"][out.valid].tolist())
        assert emitted == []  # bucket still open
        # bucket closes: only the newest 8 lanes survive, in order, no dupes
        state, out = lim.step(state, batch([], 1500), jnp.int64(1500))
        assert out.cols["x"][out.valid].tolist() == list(range(4, 12))


class TestWindowedSnapshot:
    """Non-aggregated window query + `output snapshot`: each tick re-emits
    the FULL window contents (reference:
    snapshot/WindowedPerSnapshotOutputRateLimiter.java eventList)."""

    def test_snapshot_emits_all_window_rows(self):
        rt = build(S + "@info(name='q') from S#window.length(3) "
                   "select symbol, price "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, (s, p) in enumerate([("a", 1.0), ("b", 2.0), ("c", 3.0),
                                    ("d", 4.0)]):
            h.send((s, p), timestamp=100 + i)
        rt.flush()
        rt.heartbeat(1_500)
        # window.length(3) holds the last 3 rows: b, c, d
        assert [tuple(e.data) for e in got] == [
            ("b", 2.0), ("c", 3.0), ("d", 4.0)]

    def test_snapshot_tracks_time_window_expiry(self):
        rt = build(S + "@info(name='q') from S#window.time(2 sec) "
                   "select symbol, price "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=1_900)
        rt.flush()
        # boundary 2000: 'a' (expires 2100) is STILL in the window
        rt.heartbeat(2_500)
        assert [tuple(e.data) for e in got] == [("a", 1.0), ("b", 2.0)]
        del got[:]
        rt.heartbeat(3_500)   # boundary 3000: only 'b' (expires 3900) left
        assert [tuple(e.data) for e in got] == [("b", 2.0)]
        del got[:]
        rt.heartbeat(4_500)   # 'b' expired too: empty snapshot emits nothing
        assert got == []

    def test_aggregated_window_snapshot_keeps_value_semantics(self):
        rt = build(S + "@info(name='q') from S#window.length(3) "
                   "select sum(price) as total "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, p in enumerate([1.0, 2.0, 3.0]):
            h.send(("a", p), timestamp=100 + i)
        rt.flush()
        rt.heartbeat(1_500)
        assert [tuple(e.data) for e in got] == [(6.0,)]

    def test_snapshot_over_batch_window(self):
        # regression: batch windows default to CURRENT-only emission; the
        # full-window limiter must still see EXPIRED lanes to pop its ring
        rt = build(S + "@info(name='q') from S#window.lengthBatch(2) "
                   "select symbol, price "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, (s, p) in enumerate([("a", 1.0), ("b", 2.0), ("c", 3.0),
                                    ("d", 4.0)]):
            h.send((s, p), timestamp=100 + i)
        rt.flush()
        rt.heartbeat(1_500)
        # the second batch [c, d] replaced [a, b] at its flush
        assert [tuple(e.data) for e in got] == [("c", 3.0), ("d", 4.0)]

    def test_const_insert_rejects_schema_mismatch(self):
        import pytest as _pytest

        from siddhi_tpu.errors import SiddhiAppCreationError
        rt = build("define table T (sym string, price double);")
        with _pytest.raises(SiddhiAppCreationError, match="missing"):
            rt.query("select 5.0 as wrongname insert into T")

    def test_const_insert_maps_by_name(self):
        rt = build("define table T (sym string, price double);")
        rt.query("select 5.0 as price, 'NEW' as sym insert into T")
        assert rt.tables["T"].all_rows() == [("NEW", 5.0)]


class TestNonFifoAndGroupedSnapshots:
    """VERDICT r3 item 5: full-window snapshots for grouped queries and for
    non-FIFO windows (reference: snapshot/GroupByPerSnapshotOutputRateLimiter
    and WindowedPerSnapshotOutputRateLimiter over any findable window)."""

    def test_grouped_non_aggregated_snapshot_emits_window_contents(self):
        rt = build(S + "@info(name='q') from S#window.length(3) "
                   "select symbol, price group by symbol "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, (s, p) in enumerate([("a", 1.0), ("b", 2.0), ("a", 3.0),
                                    ("b", 4.0)]):
            h.send((s, p), timestamp=100 + i)
        rt.flush()
        rt.heartbeat(1_500)
        # full window contents (last 3 rows), not one retained row per group
        assert sorted(tuple(e.data) for e in got) == [
            ("a", 3.0), ("b", 2.0), ("b", 4.0)]

    def test_sort_window_snapshot_shows_live_set(self):
        rt = build(S + "@info(name='q') from S#window.sort(2, price) "
                   "select symbol, price "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("hi", 5.0), timestamp=100)
        h.send(("lo", 1.0), timestamp=101)
        h.send(("mid", 3.0), timestamp=102)
        rt.flush()
        rt.heartbeat(1_500)
        # sort(2, price) keeps the 2 smallest; 5.0 was evicted — a FIFO
        # tracker would have evicted the OLDEST instead
        assert sorted(tuple(e.data) for e in got) == [
            ("lo", 1.0), ("mid", 3.0)]
        del got[:]
        rt.heartbeat(2_500)  # repeats while contents unchanged
        assert sorted(tuple(e.data) for e in got) == [
            ("lo", 1.0), ("mid", 3.0)]

    def test_frequent_window_snapshot_shows_live_set(self):
        rt = build(S + "@info(name='q') from S#window.frequent(1, symbol) "
                   "select symbol, price "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, (s, p) in enumerate([("a", 1.0), ("a", 2.0), ("b", 3.0)]):
            h.send((s, p), timestamp=100 + i)
            rt.flush()
        rt.heartbeat(1_500)
        # frequent(1): only the dominant symbol's events remain
        assert all(e.data[0] == "a" for e in got) and got

    def test_session_window_snapshot_tracks_session_expiry(self):
        rt = build(S + "@info(name='q') from S#window.session(1 sec) "
                   "select symbol, price "
                   "output snapshot every 2 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        rt.flush()
        rt.heartbeat(2_500)  # session closed at ~1100: window empty
        assert got == []

    def test_grouped_aggregated_snapshot_unchanged(self):
        # aggregated grouped queries keep per-group retained rows (the
        # running aggregate IS the reference's per-group snapshot value)
        rt = build(S + "@info(name='q') from S#window.length(3) "
                   "select symbol, sum(price) as total group by symbol "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=101)
        h.send(("a", 3.0), timestamp=102)
        rt.flush()
        rt.heartbeat(1_500)
        assert sorted(tuple(e.data) for e in got) == [("a", 4.0), ("b", 2.0)]

    def test_nonfifo_snapshot_is_pre_batch_at_boundary(self):
        """The batch that reveals a boundary crossing must not leak its own
        rows into that boundary's snapshot (SnapshotLimiter semantics)."""
        rt = build(S + "@info(name='q') from S#window.sort(5, price) "
                   "select symbol, price "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("early", 1.0), timestamp=500)
        rt.flush()
        h.send(("late", 2.0), timestamp=1_500)  # crosses the 1000 boundary
        rt.flush()
        assert [tuple(e.data) for e in got] == [("early", 1.0)]
        del got[:]
        rt.heartbeat(2_500)  # next tick includes both
        assert sorted(tuple(e.data) for e in got) == [
            ("early", 1.0), ("late", 2.0)]

    def test_nonfifo_snapshot_honors_having(self):
        rt = build(S + "@info(name='q') from S#window.sort(5, price) "
                   "select symbol, price having price > 2.0 "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("lo", 1.0), timestamp=100)
        h.send(("hi", 5.0), timestamp=101)
        rt.flush()
        rt.heartbeat(1_500)
        assert [tuple(e.data) for e in got] == [("hi", 5.0)]

    def test_nonfifo_snapshot_rejects_limit(self):
        import pytest as _pytest
        from siddhi_tpu.errors import SiddhiAppCreationError
        with _pytest.raises(SiddhiAppCreationError, match="limit"):
            build(S + "@info(name='q') from S#window.sort(5, price) "
                  "select symbol, price limit 1 "
                  "output snapshot every 1 sec insert into Out;")


class TestRateLimitGroupByCross:
    """Rate-limit x GROUP BY cross products (reference: the ratelimit suite
    runs each limiter over grouped queries too — the limiter applies to the
    query OUTPUT after grouped aggregation)."""

    GAPP = (S + "@info(name='q') from S select symbol, sum(price) as total "
            "group by symbol output {rate} insert into Out;")

    def test_last_every_3_events_grouped(self):
        rt = build(self.GAPP.format(rate="last every 3 events"))
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, sym in enumerate("ababab"):
            h.send((sym, float(i)))
        rt.flush()
        # output lanes are per-event post-update rows; every 3rd emits the
        # LAST of its window: events 0..2 -> (a,0+2? no: a=0, b=1, a then
        # row3 is 'a' running sum 0+2=2) ... assert positions + groups
        assert [e.data[0] for e in got] == ["a", "b"]
        assert [e.data[1] for e in got] == [
            pytest.approx(2.0), pytest.approx(9.0)]

    def test_first_every_2_events_grouped(self):
        rt = build(self.GAPP.format(rate="first every 2 events"))
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        for i, sym in enumerate("abab"):
            h.send((sym, float(i)))
        rt.flush()
        assert [e.data[0] for e in got] == ["a", "a"]
        assert [e.data[1] for e in got] == [
            pytest.approx(0.0), pytest.approx(2.0)]

    def test_snapshot_time_grouped(self):
        rt = build(S + "@info(name='q') from S select symbol, "
                   "sum(price) as total group by symbol "
                   "output snapshot every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=200)
        h.send(("a", 3.0), timestamp=300)
        rt.flush()
        rt.heartbeat(now=1500)
        # snapshot re-emits the latest row PER GROUP
        assert sorted((e.data[0], e.data[1]) for e in got) == [
            ("a", pytest.approx(4.0)), ("b", pytest.approx(2.0))]

    def test_all_every_second_grouped(self):
        rt = build(S + "@info(name='q') from S select symbol, "
                   "count() as n group by symbol "
                   "output all every 1 sec insert into Out;")
        got = q_callback(rt)
        h = rt.get_input_handler("S")
        h.send(("a", 1.0), timestamp=100)
        h.send(("b", 2.0), timestamp=200)
        rt.flush()
        assert got == []  # buffered until the time boundary
        rt.heartbeat(now=1500)
        assert sorted((e.data[0], e.data[1]) for e in got) == [
            ("a", 1), ("b", 1)]
