"""Multi-host shard serving tier: kill-one-host failover drill.

The tentpole proof for parallel/front_tier.py — a router process forwards
SXF1 frames to two REAL worker processes (`python -m siddhi_tpu.service`),
one worker is SIGKILLed mid-traffic, and the drill must show:

  * exact conservation — sent == delivered + spool_replayed + diverted,
    zero silent loss, checked after drain();
  * per-key-ordered multiset parity vs a no-kill oracle (bit-identical:
    values are multiples of 0.25 with small sums, and per-event running
    aggregates are batch-boundary invariant);
  * the failover surfaces: Prometheus families, a shard_failover flight-
    recorder bundle, a doctor critical finding, /ready degradation;
  * zombie fencing — the killed host resurrected after takeover is
    refused at its stale epoch, with frames rejected-and-recounted, never
    double-applied.

The in-process tests below it cover the satellite seams deterministically
(stale-router 409 reroute, lost-ack dedupe, unowned-slot divert, spool
restart adoption) using threaded services instead of subprocesses.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from siddhi_tpu import doctor
from siddhi_tpu.core.manager import SiddhiManager
from siddhi_tpu.parallel.front_tier import FrontTier, _http
from siddhi_tpu.service import SiddhiService
from siddhi_tpu.state.error_store import InMemoryErrorStore
from siddhi_tpu.telemetry.prometheus import (FRONT_TIER_ALWAYS_ON,
                                             validate_exposition)
from siddhi_tpu.util import faults

APP = """
@app:name('FailApp')
@app:shards(n='4', key='k')
define stream S (k string, v double);
@info(name='q1')
from S select k, sum(v) as total, count() as n group by k insert into Out;
"""

#: same computation, no shards annotation: ONE plain runtime is the oracle
ORACLE_APP = """
@app:name('FailOracle')
define stream S (k string, v double);
@info(name='q1')
from S select k, sum(v) as total, count() as n group by k insert into Out;
"""

N_KEYS = 17
ROWS_PER_FRAME = 32


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _frames(n_frames: int):
    """Deterministic traffic: keys K0..K16 cycling, v a multiple of 0.25
    (sums stay exactly representable in float32 AND float64 — parity can
    demand bit equality), timestamps strictly increasing."""
    out = []
    t = 0
    for f in range(n_frames):
        rows, tss = [], []
        for r in range(ROWS_PER_FRAME):
            i = f * ROWS_PER_FRAME + r
            rows.append((f"K{i % N_KEYS}", ((i % 7) + 1) * 0.25))
            t += 1
            tss.append(t)
        out.append((rows, tss))
    return out


def _oracle(frames):
    """{key: [(total, n), ...] in emission order} from one plain runtime
    fed the SAME frames (same batching, same timestamps)."""
    rt = SiddhiManager().create_siddhi_app_runtime(ORACLE_APP)
    got = []
    rt.add_callback("Out", lambda evs: got.extend(
        [list(e.data) for e in evs]))
    rt.start()
    h = rt.get_input_handler("S")
    for rows, tss in frames:
        h.send_batch(rows, timestamps=tss)
    rt.flush()
    rt.drain()
    rt.shutdown()
    by_key: dict = {}
    for k, total, n in got:
        by_key.setdefault(str(k), []).append((float(total), int(n)))
    return by_key


def _worker_outputs(front) -> dict:
    """{key: [(total, n), ...]} fetched per shard from its CURRENT owner
    (an adopted shard's full history was re-emitted during WAL replay)."""
    by_key: dict = {}
    for shard in range(front.n_shards):
        owner = front.shard_owner[shard]
        assert owner is not None, f"shard {shard} has no owner"
        url = front.hosts[owner].url
        status, body = _http(
            "GET", f"{url}/shard-host/outputs?app={front.name}"
            f"&shard={shard}", timeout=30.0)
        assert status == 200, (status, body)
        for _stream, _ts, data in body["outputs"].get(str(shard), []):
            k, total, n = data
            by_key.setdefault(str(k), []).append((float(total), int(n)))
    return by_key


# ========================================================================= #
# the chaos drill: real subprocess workers, SIGKILL one mid-traffic
# ========================================================================= #


def test_kill_one_host_shard_failover(worker_fleet, tmp_path):
    ports = [_free_port(), _free_port()]
    for p in ports:
        worker_fleet.spawn_service(p)
    for p in ports:
        worker_fleet.wait_http_ready(p)

    wal_dir = str(tmp_path / "wal")
    bundles = str(tmp_path / "bundles")
    front = FrontTier(
        APP, [f"http://127.0.0.1:{p}" for p in ports], wal_dir=wal_dir,
        heartbeat_interval_s=0.3, miss_threshold=3,
        max_retries=1, retry_initial_s=0.02, retry_max_s=0.05,
        capture=["Out"], bundle_dir=bundles,
        recorder_cooldown_s=0.0, recorder_min_interval_s=0.0)
    front.start()
    try:
        frames = _frames(30)
        h = front.get_input_handler("S")

        # phase 1: healthy traffic across both hosts
        for rows, tss in frames[:12]:
            h.send_batch(rows, timestamps=tss)
        assert front.ready()[0] == 200

        # host-kill fault: SIGKILL worker 1 BETWEEN frames (deterministic:
        # no request is in flight, so the ack-window race stays closed and
        # parity can demand bit equality)
        worker_fleet.kill(worker_fleet.procs[1])

        # phase 2: the FIRST post-kill frame spools (the dead owner's
        # sub-frames can't be delivered) and /ready must degrade — checked
        # immediately, well inside the >=0.9s detection window, so the
        # assertion stays deterministic even when chaos slows the senders
        rows, tss = frames[12]
        h.send_batch(rows, timestamps=tss)
        code, body = front.ready()
        assert code == 503 and not body["ready"], body
        assert front.spooled_frames_total > 0
        for rows, tss in frames[13:24]:
            h.send_batch(rows, timestamps=tss)

        # the detector + takeover run on the heartbeat thread
        deadline = time.monotonic() + 60
        while front.failovers_total < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert front.failovers_total == 1, "takeover never completed"
        assert all(o is not None for o in front.shard_owner)

        # phase 3: post-takeover traffic to the adopted shards
        for rows, tss in frames[24:]:
            h.send_batch(rows, timestamps=tss)
        front.drain(timeout_s=60)

        # exact conservation: zero silent loss
        cons = front.conservation_report()
        total_rows = 30 * ROWS_PER_FRAME
        assert cons["sent"] == total_rows, cons
        assert cons["spooled_pending"] == 0, cons
        assert cons["diverted"] == 0, cons
        assert cons["conserved"], cons
        assert cons["delivered"] + cons["spool_replayed"] == total_rows

        # per-key-ordered multiset parity vs the no-kill oracle,
        # bit-identical (running aggregates over 0.25-multiples)
        want = _oracle(frames)
        got = _worker_outputs(front)
        assert set(got) == set(want)
        for k in sorted(want):
            assert got[k] == want[k], (
                f"key {k}: got {got[k][:5]}... want {want[k][:5]}...")

        # --- failover surfaces ---------------------------------------- #
        stats = front.statistics_report()
        ft = stats["front_tier"]
        assert ft["failovers_total"] == 1
        assert ft["spooled_frames_total"] > 0
        dead_url = f"http://127.0.0.1:{ports[1]}"
        assert not ft["hosts"][dead_url]["up"]

        text = front.metrics_text()
        assert validate_exposition(text) == []
        for fam in FRONT_TIER_ALWAYS_ON:
            assert f"# TYPE {fam} " in text, fam
        assert 'siddhi_shard_failovers_total{app="FailApp"} 1' in text
        assert f'siddhi_router_host_up{{app="FailApp",host="{dead_url}"}}' \
            ' 0' in text

        rec = front.recorder.report()
        assert rec["triggers"].get("shard_failover", 0) >= 1
        assert rec["bundles_written"] >= 1

        # doctor: the detection bundle (frozen pre-takeover) must carry a
        # critical dead-owner finding naming slots and spool depth
        bdirs = sorted(os.path.join(bundles, d) for d in os.listdir(bundles)
                       if "shard_failover" in d)
        assert bdirs, os.listdir(bundles)
        findings = doctor.analyze(doctor.load_bundle(bdirs[0]))
        dead_findings = [f for f in findings
                        if f["severity"] == "critical"
                        and "dead shard owner" in f["title"]]
        assert dead_findings, findings
        assert "slots" in dead_findings[0]["evidence"]

        # recovered: the tier serves every shard again
        assert front.ready()[0] == 200

        # --- zombie fencing ------------------------------------------- #
        # resurrect the killed worker on the SAME port; its self-deploy at
        # the pre-takeover epoch must be refused against the durable meta
        worker_fleet.spawn_service(ports[1])
        worker_fleet.wait_http_ready(ports[1])
        moved = [i for i in range(front.n_shards)
                 if front.shard_epochs[i] > 0]
        assert moved
        status, body = _http(
            "POST", f"{dead_url}/shard-host/apps",
            body=json.dumps({"app": APP, "shards": moved,
                             "wal_dir": wal_dir, "epoch": 0}).encode())
        assert status == 200
        assert [f["shard"] for f in body["fenced"]] == moved, body
        assert body["deployed"] == [], body

        # a stale-epoch frame at the CURRENT owner: rejected and counted,
        # never applied
        sh = moved[0]
        owner_url = front.hosts[front.shard_owner[sh]].url
        rows, tss = frames[0]
        from siddhi_tpu.io import wire
        plan = front._plan("S")
        cols = {"k": np.array([r[0] for r in rows], dtype=object),
                "v": np.array([r[1] for r in rows])}
        frame = wire.encode_frame(plan, cols, len(rows),
                                  np.asarray(tss, dtype=np.int64))
        status, body = _http(
            "POST", f"{owner_url}/shard-host/frames/FailApp/S"
            f"?shard={sh}&epoch=0&seq=999999999999", body=frame,
            ctype="application/x-siddhi-frames")
        assert status == 409 and body["error"] == "stale-epoch", body
        status, body = _http(
            "GET", f"{owner_url}/shard-host/state?app=FailApp")
        assert body["stale_rejected"] >= 1

        # nothing double-applied: parity still holds bit-for-bit
        assert _worker_outputs(front) == want
        assert front.conservation_report()["conserved"]
    finally:
        front.shutdown()


# ========================================================================= #
# in-process seams (threaded services — deterministic, no subprocesses)
# ========================================================================= #


class _TierHarness:
    """N SiddhiService worker hosts on daemon threads + helpers."""

    def __init__(self, n_hosts: int) -> None:
        self.services = [SiddhiService() for _ in range(n_hosts)]
        self.ports = [_free_port() for _ in range(n_hosts)]
        self.servers = [svc.make_server(port)
                        for svc, port in zip(self.services, self.ports)]
        self.threads = [threading.Thread(target=s.serve_forever,
                                         daemon=True)
                        for s in self.servers]
        for t in self.threads:
            t.start()
        self.urls = [f"http://127.0.0.1:{p}" for p in self.ports]

    def close(self) -> None:
        for s in self.servers:
            s.shutdown()
            s.server_close()


@pytest.fixture
def tier2(tmp_path):
    h = _TierHarness(2)
    try:
        yield h
    finally:
        h.close()


@pytest.fixture
def tier1(tmp_path):
    h = _TierHarness(1)
    try:
        yield h
    finally:
        h.close()


@pytest.mark.smoke
def test_stale_router_is_rerouted_after_409(tier2, tmp_path):
    """A second router instance left on a pre-takeover view forwards to the
    OLD owner at the OLD epoch; the worker's 409 makes it refresh from the
    durable meta and re-dispatch once — rows applied exactly once."""
    wal_dir = str(tmp_path / "wal")
    mk = dict(wal_dir=wal_dir, heartbeat_interval_s=60.0,
              capture=["Out"], max_retries=0)
    front1 = FrontTier(APP, tier2.urls, **mk)
    front1.start()
    front2 = FrontTier(APP, tier2.urls, **mk)  # stale view: never started
    try:
        # takeover with BOTH hosts alive (operator-driven drain shape):
        # host 1's shards move to host 0 at a new epoch; the fence
        # broadcast drops host 1's replicas
        moved = [i for i, o in enumerate(front1.shard_owner) if o == 1]
        res = front1.failover(1)
        assert sorted(res["adopted"]) == moved and not res["unowned"]

        # a key owned by a moved shard, per the SAME slot map front2 holds
        key = next(f"K{i}" for i in range(200)
                   if front2.router.shard_of(f"K{i}") in moved)
        h2 = front2.get_input_handler("S")
        h2.send_batch([(key, 0.25), (key, 0.5)], timestamps=[1, 2])

        assert front2.stale_epoch_rejections >= 1
        assert front2.reroutes >= 1
        assert front2.epoch == front1.epoch  # refreshed from the meta
        cons = front2.conservation_report()
        assert cons["conserved"] and cons["delivered"] == 2, cons

        # applied exactly once, at the NEW owner
        sh = front2.router.shard_of(key)
        assert front2.shard_owner[sh] == 0
        got = _worker_outputs(front2)
        assert got[key] == [(0.25, 1), (0.75, 2)]
    finally:
        front1.shutdown()
        front2.shutdown()


@pytest.mark.smoke
def test_lost_ack_is_retried_and_deduped(tier1, tmp_path):
    """A forward whose worker processed the frame but whose ack never
    arrived is retried with the SAME seq; the worker's journaled seq mark
    rejects the duplicate, so rows apply exactly once."""
    front = FrontTier(APP, tier1.urls, wal_dir=str(tmp_path / "wal"),
                      heartbeat_interval_s=60.0, capture=["Out"],
                      max_retries=2, retry_initial_s=0.01,
                      retry_max_s=0.02)
    front.start()
    try:
        plan = faults.inject_after(front, "_post",
                                   faults.FaultPlan(nth=(1,), exc=OSError))
        h = front.get_input_handler("S")
        h.send_batch([("K1", 0.25), ("K1", 0.25)], timestamps=[1, 2])
        faults.restore(front, "_post")
        assert plan.fired == 1

        cons = front.conservation_report()
        assert cons["conserved"] and cons["delivered"] == 2, cons
        assert cons["deduped_frames"] == 1, cons

        # worker side agrees: one duplicate rejected, rows applied once
        sh_state = tier1.services[0].shard_host.state("FailApp")
        assert sh_state["duplicate_frames"] == 1, sh_state
        got = _worker_outputs(front)
        assert got["K1"] == [(0.25, 1), (0.5, 2)]
    finally:
        front.shutdown()


@pytest.mark.smoke
def test_unowned_slots_divert_to_error_store(tmp_path):
    """With NO surviving owner, frames divert to the replayable ErrorStore
    (kind="unowned") instead of blocking or vanishing, /ready degrades,
    the doctor names the condition, and metrics expose the depth."""
    store = InMemoryErrorStore()
    front = FrontTier(APP, [f"http://127.0.0.1:{_free_port()}"],
                      wal_dir=str(tmp_path / "wal"),
                      heartbeat_interval_s=60.0, error_store=store,
                      recorder_cooldown_s=0.0, recorder_min_interval_s=0.0)
    try:
        res = front.failover(0)  # the only host is dead: no survivors
        assert res["unowned"] == [0, 1, 2, 3]

        h = front.get_input_handler("S")
        h.send_batch([("K0", 0.25), ("K1", 0.5), ("K2", 0.75)],
                     timestamps=[1, 2, 3])

        cons = front.conservation_report()
        assert cons["diverted"] == 3 and cons["conserved"], cons
        entries = store.load("FailApp", kind="unowned")
        parked = sorted(ev for e in entries for ev in e.events)
        # replayable shape: (original_ts, row) pairs, decoded values
        assert parked == [(1, ("K0", 0.25)), (2, ("K1", 0.5)),
                          (3, ("K2", 0.75))]

        code, body = front.ready()
        assert code == 503 and body["unowned_slots"], body

        findings = doctor.analyze({"stats": front.statistics_report()})
        crit = [f for f in findings if f["severity"] == "critical"
                and "unowned" in f["title"]]
        assert crit, findings
        assert "slots" in crit[0]["evidence"]

        text = front.metrics_text()
        assert validate_exposition(text) == []
        assert 'siddhi_router_unowned_slots{app="FailApp"} 64' in text
    finally:
        front.shutdown()


@pytest.mark.smoke
def test_router_restart_adopts_pending_spool(tmp_path):
    """Spooled frames survive a router restart: the new incarnation reads
    the durable spool back, keeps conservation balanced, and starts its
    seq counter above every spooled seq (worker dedupe stays monotone)."""
    wal_dir = str(tmp_path / "wal")
    url = f"http://127.0.0.1:{_free_port()}"
    front = FrontTier(APP, [url], wal_dir=wal_dir,
                      heartbeat_interval_s=60.0, max_retries=0,
                      retry_initial_s=0.01, retry_max_s=0.01)
    front.hosts[0].up = False  # owner unreachable, NOT confirmed dead:
    h = front.get_input_handler("S")  # frames must spool, not divert
    h.send_batch([("K0", 0.25), ("K1", 0.5)], timestamps=[1, 2])
    cons = front.conservation_report()
    assert cons["spooled_pending"] == 2 and cons["conserved"], cons
    max_seq = max(front._seq)
    front.shutdown()

    front2 = FrontTier(APP, [url], wal_dir=wal_dir,
                       heartbeat_interval_s=60.0)
    try:
        cons2 = front2.conservation_report()
        assert cons2["spooled_pending"] == 2, cons2
        assert cons2["sent"] == 2 and cons2["conserved"], cons2
        assert max(front2._seq) >= max_seq
        assert front2.ready()[0] == 503  # backlog = not ready
    finally:
        front2.shutdown()
