"""Sandbox + Validate manager surfaces (reference:
managment/SandboxTestCase, managment/ValidateTestCase)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.errors import SiddhiAppCreationError, SiddhiError



pytestmark = pytest.mark.smoke

class TestValidate:
    def test_valid_app_passes(self):
        SiddhiManager().validate_siddhi_app(
            "define stream S (k int);\n"
            "from S select k insert into Out;")

    def test_unknown_stream_raises(self):
        with pytest.raises(SiddhiError):
            SiddhiManager().validate_siddhi_app(
                "define stream S (k int);\n"
                "from Nope select k insert into Out;")

    def test_bad_expression_raises(self):
        with pytest.raises(SiddhiError):
            SiddhiManager().validate_siddhi_app(
                "define stream S (k int);\n"
                "from S select missingAttr insert into Out;")

    def test_validate_does_not_register_runtime(self):
        mgr = SiddhiManager()
        mgr.validate_siddhi_app(
            "define stream S (k int);\nfrom S select k insert into Out;")
        assert mgr.runtimes == {}


class TestSandbox:
    APP = """
    @source(type='inMemory', topic='t1', @map(type='passThrough'))
    define stream S (k string, v double);
    @store(type='inMemory')
    define table T (k string, v double);
    from S select k, v insert into T;
    @info(name='q')
    from S select k, sum(v) as total group by k insert into Out;
    """

    def test_sources_sinks_stores_stripped(self):
        mgr = SiddhiManager()
        rt = mgr.create_sandbox_siddhi_app_runtime(self.APP, batch_size=8)
        assert rt.sources == [] and rt.sinks == []
        from siddhi_tpu.core.table import InMemoryTable
        assert isinstance(rt.tables["T"], InMemoryTable)  # not a RecordTable

    def test_sandboxed_app_runs_via_input_handler(self):
        mgr = SiddhiManager()
        rt = mgr.create_sandbox_siddhi_app_runtime(self.APP, batch_size=8)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(tuple(e) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("a", 1.0))
        h.send(("a", 2.0))
        rt.flush()
        rt.shutdown()
        assert rows[-1] == ("a", 3.0)
        assert sorted(rt.tables["T"].all_rows()) == [("a", 1.0), ("a", 2.0)]
