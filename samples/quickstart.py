"""Quick-start sample (reference:
modules/siddhi-samples/quick-start-samples/ — SimpleFilterQuery etc.).

Run:  python samples/quickstart.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable from any cwd

from siddhi_tpu import SiddhiManager

APP = """
define stream StockStream (symbol string, price float, volume long);

@info(name = 'filterQuery')
from StockStream[price > 50.0]
select symbol, price
insert into HighPriceStream;
"""


def main() -> None:
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(APP)
    runtime.add_callback(
        "HighPriceStream",
        lambda events: [print(f"  -> {e.data}") for e in events])
    runtime.start()

    handler = runtime.get_input_handler("StockStream")
    print("sending events...")
    for row in [("IBM", 75.6, 100), ("WSO2", 45.6, 10), ("GOOG", 120.0, 50)]:
        handler.send(row)
    runtime.flush()
    runtime.shutdown()


if __name__ == "__main__":
    main()
