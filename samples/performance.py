"""Performance harnesses (reference:
modules/siddhi-samples/performance-samples/ —
SimpleFilterSingleQueryPerformance.java:40-52 prints throughput + avg latency
every 10M events; window/group-by/partition variants alongside).

Run:  python samples/performance.py [config] [n_events]
Configs: filter | window_groupby | distinct | partition | join
(the BASELINE.md harness shapes). Prints events/sec and per-batch latency.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable from any cwd

import time

import numpy as np

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import EventBatch

CONFIGS = {
    "filter": """
        define stream In (symbol string, price double, volume long);
        @info(name='q') from In[price > 50.0] select symbol, price
        insert into Out;""",
    "window_groupby": """
        define stream In (symbol string, price double, volume long);
        @info(name='q') from In#window.lengthBatch(10000)
        select symbol, sum(price) as total, avg(price) as avgPrice
        group by symbol insert into Out;""",
    "distinct": """
        define stream In (symbol string, price double, volume long);
        @info(name='q') from In#window.time(60 sec)
        select distinctCount(symbol) as uniques insert into Out;""",
    "join": """
        define stream In (symbol string, price double, volume long);
        define stream In2 (symbol string, qty long);
        @info(name='q') from In#window.length(1000) join In2#window.length(1000)
        on In.symbol == In2.symbol
        select In.symbol as symbol, In.price as price, In2.qty as qty
        insert into Out;""",
}


def run(config: str, n_events: int, batch: int = 8192,
        n_keys: int = 100_000) -> None:
    app = CONFIGS[config]
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(app, batch_size=batch,
                                           group_capacity=1 << 20)
    rt.start()
    qr = rt.query_runtimes["q"]

    rng = np.random.default_rng(0)
    rows = [(f"S{int(k)}", float(p), int(v))
            for k, p, v in zip(rng.integers(0, n_keys, batch),
                               rng.uniform(1.0, 100.0, batch),
                               rng.integers(1, 1000, batch))]
    cols = qr.codec.rows_to_columns(rows, n_pad=batch) \
        if hasattr(qr, "codec") else qr.left.codec.rows_to_columns(rows, n_pad=batch)

    import jax.numpy as jnp
    steps = max(n_events // batch, 1)
    t_total = 0.0
    sent = 0
    junction = rt.junctions["In"]
    for i in range(steps + 3):
        ts = np.full(batch, i * 1000, dtype=np.int64)
        eb = EventBatch.from_numpy(ts, cols, batch)
        t0 = time.perf_counter()
        junction.publish_batch(eb, i * 1000)
        if i >= 3:  # skip warmup/compile
            t_total += time.perf_counter() - t0
            sent += batch
    eps = sent / max(t_total, 1e-9)
    print(f"{config}: {eps:,.0f} events/sec "
          f"({t_total / max(steps, 1) * 1e3:.2f} ms/batch of {batch})")


def main() -> None:
    config = sys.argv[1] if len(sys.argv) > 1 else "filter"
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 2_000_000
    if config == "all":
        for c in CONFIGS:
            run(c, n)
    else:
        run(config, n)


if __name__ == "__main__":
    main()
