"""REST deployment microservice.

Reference: modules/siddhi-service/ — an MSF4J/Swagger service exposing deploy/
undeploy/list of SiddhiQL apps (SiddhiApiServiceImpl.java:24). Here: a
stdlib ThreadingHTTPServer over one SiddhiManager.

Endpoints (JSON):
  POST   /siddhi-apps                 body = SiddhiQL text  → deploy + start
  GET    /siddhi-apps                 → list of app names
  DELETE /siddhi-apps/<name>          → shutdown + undeploy
  POST   /siddhi-apps/<name>/streams/<stream>  body = {"events": [[...], ...]}
  POST   /siddhi-apps/<name>/query    body = {"query": "from T select ..."}
  POST   /siddhi-apps/<name>/persist  → {"revision": "..."}
  POST   /siddhi-apps/<name>/recover  → {"revision": ..., "wal_replayed": n}
  POST   /siddhi-apps/<name>/upgrade[?force=true]
                                      body = SiddhiQL text of the NEW app
                                      version (same @app:name) → blue-green
                                      hot-swap (core/upgrade.py): state
                                      migrates, WAL tail replays, sources/
                                      routing cut over atomically; any
                                      pre-commit failure rolls back to v1
  POST   /siddhi-apps/<name>/replay   body = {"app"?: SiddhiQL, "wal_dir"?:
                                      path, "speed"?: float} → deterministic
                                      replay of recorded WAL segments
                                      against a candidate app (defaults:
                                      the deployed app over its own journal)
  GET    /siddhi-apps/<name>/errors?kind=&stream=
                                      → stored error entries (metadata)
  POST   /siddhi-apps/<name>/errors/replay
                                      body = {"kind"?, "stream"?, "ids"?}
                                      → re-send matching entries into their
                                      original streams, original timestamps
  GET    /siddhi-apps/<name>/statistics
  POST   /siddhi-apps/<name>/diagnostics
                                      → force a flight-recorder diagnostic
                                        bundle now (telemetry/recorder.py;
                                        bypasses the trigger rate limits);
                                        {"bundle": path, "recorder": {...}}
  GET    /slo                         → 200 when no declared objective is
                                        breached; 503 with per-app burn
                                        detail otherwise (same lock-free
                                        contract as /ready)
  GET    /health                      → 200 always while the process serves
  GET    /ready                       → 200 when every app is "running";
                                        503 with per-app detail otherwise
                                        (degraded = breaker open, or
                                        recovering) — lock-free, so a
                                        wedged deploy can't flap probes
  GET    /metrics                     → Prometheus text exposition
                                        (docs/OBSERVABILITY.md)

Shard-host endpoints (parallel/front_tier.py — this service doubles as a
worker host of the multi-host shard serving tier; docs/SHARDING.md):
  GET    /shard-host/ping             → liveness for the front tier's
                                        failure detector (auth-exempt,
                                        like the other probes)
  GET    /shard-host/state?app=       → owned shards, epochs, last seqs
  GET    /shard-host/outputs?app=&shard=  → captured output rows (tests)
  POST   /shard-host/apps             body = {app, shards, wal_dir,
                                        shard_epochs, capture,
                                        runtime_kwargs} → build + start
                                        shard replicas (epoch fence-checked)
  POST   /shard-host/adopt            body = {app, shard, epoch, wal_dir,
                                        capture, runtime_kwargs} → take
                                        over a dead host's shard by WAL
                                        replay; returns last_seq
  POST   /shard-host/fence            body = {app, shard_epochs} → drop
                                        owned shards behind the committed
                                        epochs (zombie fencing)
  POST   /shard-host/drain            body = {app} → flush+drain replicas
  POST   /shard-host/frames/<app>/<stream>?shard=&epoch=&seq=
                                      body = raw SXF1 frames → deliver to
                                        the owned replica; 409 not-owner /
                                        stale-epoch (the sender re-routes)

Probe note: /health, /ready, and /metrics skip bearer-token auth by design —
orchestrator probes and scrapers carry no credentials; the bodies expose
only app names, health states, and metric aggregates, never data or query
text.

Usage:  python -m siddhi_tpu.service [port]

Concurrency note: requests serialize through one lock — the engine is a
single-controller runtime by design (SURVEY §7); the service is a deployment
surface, not a data-plane load balancer.

Security: **deploying an app is code execution** — SiddhiQL may contain
`define function f[python] { ... }` bodies that run in-process. The service
therefore (a) rejects script-function definitions unless constructed with
`allow_scripts=True`, and (b) requires a shared bearer token on every request
when constructed with `token=...`. Always set a token before binding to a
non-loopback host.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .core.manager import SiddhiManager
from .errors import SiddhiError
from .util.locks import named_lock, note_blocking


class SiddhiService:
    def __init__(self, manager: SiddhiManager | None = None, *,
                 token: str | None = None,
                 allow_scripts: bool = False) -> None:
        self.manager = manager or SiddhiManager()
        self.lock = named_lock("service.registry")
        self.token = token
        self.allow_scripts = allow_scripts
        if self.manager.error_store is None:
            # the /errors endpoints need a store to read; the bounded
            # in-memory default makes @OnError(action='STORE') / dead-letter
            # capture work out of the box on a fresh service
            from .state.error_store import InMemoryErrorStore
            self.manager.set_error_store(InMemoryErrorStore())
        self._shard_host = None

    @property
    def shard_host(self):
        """Worker-side shard adoption hooks, built on first /shard-host/*
        request — a service that never joins a front tier pays nothing."""
        if self._shard_host is None:
            with self.lock:
                if self._shard_host is None:
                    from .parallel.front_tier import ShardHost
                    self._shard_host = ShardHost(self.manager)
        return self._shard_host

    # ------------------------------------------------------------- operations

    def deploy(self, siddhi_ql: str) -> str:
        with self.lock:
            from . import compiler
            text = (compiler.update_variables(siddhi_ql)
                    if "${" in siddhi_ql else siddhi_ql)
            app = compiler.parse(text)
            if app.function_definitions and not self.allow_scripts:
                names = ", ".join(sorted(app.function_definitions))
                raise SiddhiError(
                    "app defines script functions (" + names + ") which "
                    "execute arbitrary code; start the service with "
                    "allow_scripts=True to permit them")
            if app.name in self.manager.runtimes:
                # reference service rejects duplicate deployment
                raise SiddhiError(f"app {app.name!r} is already deployed")
            rt = self.manager.create_siddhi_app_runtime(app)
            rt.start()
            return rt.app.name

    def undeploy(self, name: str) -> bool:
        with self.lock:
            rt = self.manager.runtimes.pop(name, None)
            if rt is None:
                return False
            rt.shutdown()
            return True

    def list_apps(self) -> list[str]:
        with self.lock:
            return sorted(self.manager.runtimes)

    def send(self, app: str, stream: str, events: list) -> int:
        with self.lock:
            rt = self.manager.runtimes[app]
            handler = rt.get_input_handler(stream)
            # one batched staging call for the whole payload (the REST body
            # is already a batch) — the engine's fast public path
            handler.send_batch([tuple(row) for row in events])
            rt.flush()
            return len(events)

    def send_frames(self, app: str, stream: str, body: bytes) -> int:
        """Binary columnar ingestion (Content-Type:
        application/x-siddhi-frames, io/wire.py SXF1 framing). The service
        lock covers only the runtime lookup: frame decode and staging run
        lock-free so N client connections feed the ingress pipeline
        concurrently — the engine's own junction/controller locks protect
        delivery. No flush: the pipeline (or the columnar path's immediate
        delivery) owns batching."""
        with self.lock:
            rt = self.manager.runtimes[app]
            handler = rt.get_input_handler(stream)
        from .io import wire
        return wire.deliver_frames(handler, body)

    def query(self, app: str, text: str) -> list:
        with self.lock:
            rt = self.manager.runtimes[app]
            return [list(e.data) for e in rt.query(text)]

    def attach_query(self, app: str, query_text: str,
                     name: str | None = None) -> dict:
        """Splice one query into a RUNNING app (manager.attach_query:
        per-splice SL501 admission + one-retrace splice, siblings
        undisturbed). Returns the deploy summary incl. deploy_ms."""
        with self.lock:
            return self.manager.attach_query(app, query_text, name=name)

    def detach_query(self, app: str, query_name: str) -> dict:
        """Splice one query out of a RUNNING app; frees its budget and
        retries the pending-app queue (manager.detach_query)."""
        with self.lock:
            return self.manager.detach_query(app, query_name)

    def statistics(self, app: str) -> dict:
        with self.lock:
            return self.manager.runtimes[app].statistics_report()

    def persist(self, app: str) -> str:
        with self.lock:
            return self.manager.runtimes[app].persist()

    def recover(self, app: str) -> dict:
        """Restore the last revision + replay the app's WAL (crash
        recovery over the deployment surface)."""
        with self.lock:
            return self.manager.runtimes[app].recover()

    def _parse_guarded(self, siddhi_ql: str):
        """Parse SiddhiQL with the same script-function gate as deploy():
        an upgrade/replay body is code-execution surface too."""
        from . import compiler
        text = (compiler.update_variables(siddhi_ql)
                if "${" in siddhi_ql else siddhi_ql)
        app = compiler.parse(text)
        if app.function_definitions and not self.allow_scripts:
            names = ", ".join(sorted(app.function_definitions))
            raise SiddhiError(
                "app defines script functions (" + names + ") which "
                "execute arbitrary code; start the service with "
                "allow_scripts=True to permit them")
        return app

    def upgrade(self, name: str, siddhi_ql: str, *,
                force: bool = False) -> dict:
        """Blue-green hot-swap of deployed app `name` to the new version in
        the body (core/upgrade.py). Held under the service lock: the swap
        replaces the manager routing entry every other endpoint resolves."""
        with self.lock:
            app = self._parse_guarded(siddhi_ql)
            if app.name != name:
                raise SiddhiError(
                    f"body deploys {app.name!r} but the URL names {name!r}; "
                    "an upgrade must keep the app name")
            return self.manager.upgrade(app, force=force)

    def replay(self, name: str, *, siddhi_ql: str | None = None,
               wal_dir: str | None = None,
               speed: float | None = None) -> dict:
        """Deterministic WAL replay against a candidate app (defaults to the
        deployed app replaying its own journal)."""
        import os
        with self.lock:
            rt = self.manager.runtimes[name]
            app = (self._parse_guarded(siddhi_ql) if siddhi_ql
                   else rt.app)
            if wal_dir is None:
                if rt.wal is None:
                    raise SiddhiError(
                        f"app {name!r} has no WAL; pass wal_dir explicitly")
                wal_dir = os.path.dirname(rt.wal.dir)
            return self.manager.replay(app, wal_dir, app_name=name,
                                       speed=speed)

    def errors(self, name: str, *, stream: str | None = None,
               kind: str | None = None) -> list[dict]:
        """Stored error entries for one app (metadata only: row payloads may
        not be JSON-safe and can be large — replay acts on the stored
        originals server-side)."""
        with self.lock:
            rt = self.manager.runtimes[name]
            es = rt.ctx.error_store
            if es is None:
                return []
            return [{"id": e.id, "timestamp": e.timestamp,
                     "stream": e.stream_name, "kind": e.kind,
                     "events": len(e.events), "cause": e.cause}
                    for e in es.load(name, stream, kind)]

    def replay_errors(self, name: str, *, stream: str | None = None,
                      kind: str | None = None,
                      ids: list | None = None) -> dict:
        """Re-send matching stored entries into their original streams with
        their original timestamps; each entry is discarded only once all its
        rows were accepted (ErrorStore.replay)."""
        with self.lock:
            rt = self.manager.runtimes[name]
            es = rt.ctx.error_store
            if es is None:
                return {"replayed_entries": 0, "replayed_events": 0}
            entries = es.load(name, stream, kind)
            if ids:
                wanted = {int(i) for i in ids}
                entries = [e for e in entries if e.id in wanted]
            n_entries = n_events = 0
            for e in entries:
                es.replay(e, rt)
                n_entries += 1
                n_events += len(e.events)
            rt.flush()
            return {"replayed_entries": n_entries,
                    "replayed_events": n_events}

    def validate(self, siddhi_ql: str) -> dict:
        """Static lint WITHOUT deploying (no runtime is created, nothing
        starts): the CLI's report shape over HTTP. Parse failures come back
        as an SL000 diagnostic in the same shape, not an HTTP error."""
        from .lint import lint_text
        report = lint_text(siddhi_ql)
        return report.to_dict()

    def health(self) -> dict:
        """Liveness: no lock — the process answering IS the signal (a
        liveness probe must not hang behind a long deploy)."""
        return {"status": "up", "apps": len(self.manager.runtimes)}

    def readiness(self) -> tuple[int, dict]:
        """Readiness: (http_status, body). 200 only when every deployed app
        reports "running"; a breaker-open/degraded or recovering app answers
        503 so load balancers drain traffic while the engine sheds load.

        Lock-free like /health: a wedged deploy holding the service lock
        must not 503-flap probes — runtime.health() reads GIL-atomic
        snapshots, and iterating a point-in-time copy of the runtime table
        tolerates concurrent deploy/undeploy (an app mid-removal simply
        drops out of this probe)."""
        apps = {}
        for name, rt in list(self.manager.runtimes.items()):
            try:
                apps[name] = rt.health()
            except Exception:  # racing undeploy/shutdown
                apps[name] = {"state": "stopped", "breakers": {},
                              "queues": {}}
        ready = all(a["state"] == "running" for a in apps.values())
        return (200 if ready else 503), {"ready": ready, "apps": apps}

    def slo(self) -> tuple[int, dict]:
        """SLO probe: (http_status, body). 200 while no declared objective
        is breached (apps without @slo annotations count as compliant);
        503 lets alerting/load-balancing key off burn-rate breaches the
        same way /ready keys off breaker state. Lock-free like /ready."""
        apps = {}
        breaching = False
        for name, rt in list(self.manager.runtimes.items()):
            eng = getattr(rt, "slo_engine", None)
            if eng is None:
                continue
            try:
                rep = eng.report()
            except Exception:  # racing undeploy/shutdown
                continue
            apps[name] = rep
            breaching = breaching or rep.get("breaching", False)
        return (503 if breaching else 200), {"ok": not breaching,
                                             "apps": apps}

    def diagnostics(self, name: str, reason: str = "api") -> dict:
        """Force a diagnostic bundle for one app (bypasses the recorder's
        de-dup/rate-limit gates — an operator asking for evidence gets
        evidence)."""
        with self.lock:
            rt = self.manager.runtimes[name]
        return rt.diagnostics(reason=reason)

    def metrics_text(self) -> str:
        """Prometheus text exposition for every deployed app. Lock-free:
        a scrape must never queue behind a deploy or a device step."""
        from .telemetry import prometheus
        return prometheus.render_manager(self.manager)

    # ---------------------------------------------------------------- server

    def make_server(self, port: int = 9090,
                    host: str = "127.0.0.1") -> ThreadingHTTPServer:
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n).decode()

            def _raw_body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n)

            def _route(self):
                """(path_parts, query_dict) — the path may carry a query
                string (?force=true, ?kind=sink); parse_qs flattens each
                key to its first value."""
                from urllib.parse import parse_qs, urlsplit
                u = urlsplit(self.path)
                parts = u.path.strip("/").split("/")
                q = {k: v[0] for k, v in parse_qs(u.query).items()}
                return parts, q

            def _authorized(self) -> bool:
                if service.token is None:
                    return True
                import hmac
                got = self.headers.get("Authorization", "")
                want = f"Bearer {service.token}"
                if hmac.compare_digest(got.encode(), want.encode()):
                    return True
                self._reply(401, {"error": "missing or bad bearer token"})
                return False

            def do_GET(self):
                note_blocking("http.handle")
                parts, query = self._route()
                # probe endpoints skip auth (orchestrator probes carry no
                # credentials; bodies expose names + states only)
                if parts == ["health"]:
                    self._reply(200, service.health())
                    return
                if parts == ["ready"]:
                    code, body = service.readiness()
                    self._reply(code, body)
                    return
                if parts == ["slo"]:
                    # auth-exempt like /ready: burn rates and objective IDs,
                    # never data or query text
                    code, body = service.slo()
                    self._reply(code, body)
                    return
                if parts == ["metrics"]:
                    # auth-exempt like /health: scrapers carry no bearer
                    # token; the body exposes names + aggregates, not data
                    from .telemetry import prometheus
                    body = service.metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     prometheus.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parts == ["shard-host", "ping"]:
                    # auth-exempt liveness for the front tier's failure
                    # detector (same contract as /health)
                    self._reply(200, service.shard_host.ping())
                    return
                if not self._authorized():
                    return
                try:
                    if parts == ["shard-host", "state"]:
                        self._reply(200, service.shard_host.state(
                            query.get("app", "")))
                    elif parts == ["shard-host", "outputs"]:
                        shard = query.get("shard")
                        self._reply(200, service.shard_host.outputs(
                            query.get("app", ""),
                            int(shard) if shard is not None else None))
                    elif parts == ["siddhi-apps"]:
                        self._reply(200, {"apps": service.list_apps()})
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "statistics"):
                        self._reply(200, service.statistics(parts[1]))
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "errors"):
                        self._reply(200, {"errors": service.errors(
                            parts[1], stream=query.get("stream"),
                            kind=query.get("kind"))})
                    else:
                        self._reply(404, {"error": "not found"})
                except KeyError:
                    self._reply(404, {"error": "unknown app"})

            def do_POST(self):
                note_blocking("http.handle")
                if not self._authorized():
                    return
                parts, query = self._route()
                try:
                    if (len(parts) == 4 and parts[0] == "shard-host"
                            and parts[1] == "frames"):
                        seq = query.get("seq")
                        code, body = service.shard_host.deliver(
                            parts[2], parts[3],
                            shard=int(query.get("shard", 0)),
                            epoch=int(query.get("epoch", 0)),
                            seq=int(seq) if seq is not None else None,
                            body=self._raw_body())
                        self._reply(code, body)
                    elif parts == ["shard-host", "apps"]:
                        data = json.loads(self._body())
                        self._reply(200, service.shard_host.deploy(
                            data["app"], data.get("shards", []),
                            data.get("wal_dir"),
                            epoch=int(data.get("epoch", 0)),
                            shard_epochs=data.get("shard_epochs"),
                            capture=data.get("capture", ()),
                            runtime_kwargs=data.get("runtime_kwargs")))
                    elif parts == ["shard-host", "adopt"]:
                        data = json.loads(self._body())
                        self._reply(200, service.shard_host.adopt(
                            data["app"], int(data["shard"]),
                            int(data["epoch"]), data["wal_dir"],
                            capture=data.get("capture", ()),
                            runtime_kwargs=data.get("runtime_kwargs")))
                    elif parts == ["shard-host", "fence"]:
                        data = json.loads(self._body())
                        self._reply(200, service.shard_host.fence(
                            data["app"], data.get("shard_epochs")))
                    elif parts == ["shard-host", "drain"]:
                        data = json.loads(self._body())
                        self._reply(200, service.shard_host.drain(
                            data["app"]))
                    elif parts == ["siddhi-apps"]:
                        name = service.deploy(self._body())
                        self._reply(201, {"app": name})
                    elif parts == ["siddhi-apps", "validate"]:
                        self._reply(200, service.validate(self._body()))
                    elif (len(parts) == 4 and parts[0] == "siddhi-apps"
                          and parts[2] == "streams"):
                        ctype = (self.headers.get("Content-Type") or "")
                        if ctype.split(";")[0].strip() == \
                                "application/x-siddhi-frames":
                            # zero-copy columnar path: raw SXF1 frames
                            n = service.send_frames(parts[1], parts[3],
                                                    self._raw_body())
                        else:
                            data = json.loads(self._body())
                            n = service.send(parts[1], parts[3],
                                             data.get("events", []))
                        self._reply(200, {"accepted": n})
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "query"):
                        data = json.loads(self._body())
                        rows = service.query(parts[1], data["query"])
                        self._reply(200, {"records": rows})
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "queries"):
                        # attach: JSON {"query": ..., "name": ...} or a
                        # raw SiddhiQL query body
                        body = self._body()
                        ctype = (self.headers.get("Content-Type") or "")
                        if ctype.split(";")[0].strip() == \
                                "application/json":
                            data = json.loads(body)
                            out = service.attach_query(
                                parts[1], data["query"],
                                name=data.get("name"))
                        else:
                            out = service.attach_query(parts[1], body)
                        self._reply(201, out)
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "persist"):
                        self._reply(200,
                                    {"revision": service.persist(parts[1])})
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "recover"):
                        self._reply(200, service.recover(parts[1]))
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "diagnostics"):
                        body = self._body()
                        data = json.loads(body) if body.strip() else {}
                        self._reply(200, service.diagnostics(
                            parts[1], reason=data.get("reason", "api")))
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "upgrade"):
                        force = query.get("force", "").lower() \
                            in ("1", "true", "yes")
                        self._reply(200, service.upgrade(
                            parts[1], self._body(), force=force))
                    elif (len(parts) == 3 and parts[0] == "siddhi-apps"
                          and parts[2] == "replay"):
                        body = self._body()
                        data = json.loads(body) if body.strip() else {}
                        speed = data.get("speed")
                        self._reply(200, service.replay(
                            parts[1], siddhi_ql=data.get("app"),
                            wal_dir=data.get("wal_dir"),
                            speed=float(speed) if speed is not None
                            else None))
                    elif (len(parts) == 4 and parts[0] == "siddhi-apps"
                          and parts[2] == "errors"
                          and parts[3] == "replay"):
                        body = self._body()
                        data = json.loads(body) if body.strip() else {}
                        self._reply(200, service.replay_errors(
                            parts[1], stream=data.get("stream"),
                            kind=data.get("kind"), ids=data.get("ids")))
                    else:
                        self._reply(404, {"error": "not found"})
                except KeyError as e:
                    self._reply(404, {"error": f"unknown: {e}"})
                except json.JSONDecodeError as e:
                    self._reply(400, {"error": f"bad JSON body: {e}"})
                except ValueError as e:  # bad SXF1 framing / column shape
                    self._reply(400, {"error": str(e)})
                except SiddhiError as e:
                    self._reply(400, {"error": str(e)})

            def do_DELETE(self):
                note_blocking("http.handle")
                if not self._authorized():
                    return
                parts, _query = self._route()
                try:
                    if len(parts) == 2 and parts[0] == "siddhi-apps":
                        ok = service.undeploy(parts[1])
                        self._reply(200 if ok else 404,
                                    {"undeployed": ok})
                    elif (len(parts) == 4 and parts[0] == "siddhi-apps"
                          and parts[2] == "queries"):
                        self._reply(200, service.detach_query(
                            parts[1], parts[3]))
                    else:
                        self._reply(404, {"error": "not found"})
                except KeyError as e:
                    self._reply(404, {"error": f"unknown: {e}"})
                except SiddhiError as e:
                    self._reply(400, {"error": str(e)})

        return ThreadingHTTPServer((host, port), Handler)


def main(argv=None) -> None:
    import os
    import sys
    argv = argv if argv is not None else sys.argv[1:]
    from .telemetry.logs import configure_logging
    configure_logging()  # SIDDHI_LOG_FORMAT=json → structured one-liners
    allow_scripts = "--allow-scripts" in argv
    argv = [a for a in argv if a != "--allow-scripts"]
    port = int(argv[0]) if argv else 9090
    token = os.environ.get("SIDDHI_SERVICE_TOKEN") or None
    server = SiddhiService(token=token,
                           allow_scripts=allow_scripts).make_server(port)
    auth = "token auth" if token else "NO AUTH (loopback only!)"
    print(f"siddhi_tpu service on :{port} [{auth}]")
    server.serve_forever()


if __name__ == "__main__":
    main()
