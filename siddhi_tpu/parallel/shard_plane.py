"""Sharded execution plane: partition-key sharding of stateful queries.

One `ShardPlane` runs N full replicas of an app's pipeline (windows,
group-bys, joins, breakers, SLO engines, telemetry — everything a
`SiddhiAppRuntime` owns), and routes every ingress row to exactly one
replica by partition-key hash BEFORE interning: each shard's string table
holds only the dictionary values its keys reference, each shard journals
its own subset into its own WAL directory (`<wal_dir>/<App>@s<i>/`,
extending the journal naming of state/wal.py), and each shard trips its
own breakers and burns its own SLO budget. The plane itself duck-types the
`SiddhiAppRuntime` surface the service layer and the manager use —
`SiddhiManager.create_siddhi_app_runtime` builds one transparently when
the app carries `@app:shards(n=, key=)` (SIDDHI_SHARDS overrides n).

Correctness envelope: only key-local plans are admitted
(`analysis.sharding.check_shardable` refuses global operators loudly —
SL601). For an admitted plan, the merged output is a key-interleaving of
per-key output sequences that are bit-identical to the serial engine's:
per-key input order is preserved by the router, per-key state never leaves
its shard, and windowless running aggregates emit per input row.

Rebalancing: `slot = hash(key) % n_slots` is fixed; `assignment[slot] ->
shard` is the mutable table. `rebalance()` consults the router's skew
counters, computes a greedy LPT re-assignment, and performs a tiny
blue-green swap in the spirit of core/upgrade.py: pause intake at the
gate, drain every shard, rebuild the fleet from the full per-shard WAL
history re-routed through the NEW assignment (device state is not
key-addressable, so slot moves reconstruct state from the journal — which
is why rebalance() requires WAL-backed planes with an unrotated journal),
commit the new epoch's meta file atomically, cut the router over, retire
the old replicas. `move_shard()` is the single-shard primitive that DOES
reuse the per-element snapshot/restore + WAL-handover recipe verbatim
(same epoch, same keys, fresh runtime) — the building block for moving a
replica off a sick device.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional

import numpy as np

from ..analysis.sharding import ShardConfig, check_shardable, shard_config
from ..core.ingress import ShardRouter
from ..errors import SiddhiAppCreationError
from ..query_api import SiddhiApp
from ..util.locks import named_condition

log = logging.getLogger("siddhi_tpu")

#: slots in the hash ring (env-tunable; more slots = finer-grained
#: rebalancing at the cost of a bigger assignment table)
DEFAULT_SLOTS = 64


def _n_slots() -> int:
    v = os.environ.get("SIDDHI_SHARD_SLOTS", "").strip()
    try:
        return max(1, int(v)) if v else DEFAULT_SLOTS
    except ValueError:
        return DEFAULT_SLOTS


def shard_app_name(name: str, i: int) -> str:
    return f"{name}@s{i}"


def shard_app(app: SiddhiApp, i: int) -> SiddhiApp:
    """The replica app for shard `i`: renamed `<app>@s<i>` (per-shard WAL
    directory and persistence revisions fall out of the app name) with
    @app:shards stripped (a replica must never build its own plane or
    fleet-multiply its own cost report). Module-level so the multi-host
    worker side (parallel/front_tier.py's ShardHost) builds replicas with
    the exact same identity a local plane would."""
    import dataclasses as dc

    from ..query_api.annotation import Annotation, Element
    anns = [a for a in (app.annotations or ())
            if a.name.lower() not in ("app:shards", "app:name")]
    anns.insert(0, Annotation(
        "app:name", (Element(None, shard_app_name(app.name, i)),)))
    return dc.replace(app, annotations=anns)


def epoch_wal_dir(base: Optional[str], epoch: int) -> Optional[str]:
    """Epoch 0 journals directly under the user's wal_dir (the PR 7
    layout, suffixed app names); later epochs live in `e<N>/` so a
    rebalance or a shard takeover can write the new epoch's journal
    WITHOUT touching the old epoch's segments until the meta commit
    point — and so a fenced zombie's late appends land in a directory
    no adoption will ever read again."""
    if base is None:
        return None
    return base if epoch == 0 else os.path.join(base, f"e{epoch}")


class _IngressGate:
    """Pause/resume gate for routed sends: senders pass through
    concurrently (work fans out to per-shard runtimes, each with its own
    controller lock); `pause()` blocks new sends and waits out in-flight
    ones so a rebalance/move sees a quiesced router."""

    def __init__(self) -> None:
        self._cond = named_condition("shard.ingress_gate")
        self._active = 0
        self._paused = False

    def __enter__(self):
        with self._cond:
            while self._paused:
                self._cond.wait()
            self._active += 1
        return self

    def __exit__(self, *exc):
        with self._cond:
            self._active -= 1
            self._cond.notify_all()
        return False

    def pause(self) -> None:
        with self._cond:
            self._paused = True
            while self._active:
                self._cond.wait()

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()


class ShardInputHandler:
    """The plane's routing input handler: same send surface as
    `core.stream.InputHandler`, but every path hashes the partition key
    over ORIGINAL values and fans per-shard subsets out to the replica
    handlers. `wire.deliver_frames` dispatches to `deliver_frames` here,
    so SXF1 frames are split (dictionaries compacted per shard) before any
    interning."""

    def __init__(self, plane: "ShardPlane", stream_id: str) -> None:
        self.plane = plane
        self.stream_id = stream_id
        defn = plane.shards[0].junctions[stream_id].definition
        self.definition = defn
        names = [a.name for a in defn.attributes]
        if plane.key not in names:
            raise SiddhiAppCreationError(
                f"stream {stream_id!r} has no partition-key attribute "
                f"{plane.key!r}; it cannot be routed (docs/SHARDING.md)")
        self._key_index = names.index(plane.key)

    def _shard_handler(self, shard: int):
        return self.plane.shards[shard].get_input_handler(self.stream_id)

    def send(self, data, timestamp: Optional[int] = None) -> None:
        from ..core.event import Event
        if isinstance(data, Event):
            self.send_batch([tuple(data.data)], timestamps=[data.timestamp])
            return
        if isinstance(data, list) and data and isinstance(data[0], Event):
            self.send_batch([tuple(e.data) for e in data],
                            timestamps=[e.timestamp for e in data])
            return
        self.send_batch([tuple(data)], timestamps=timestamp)

    def send_batch(self, rows, timestamps=None) -> None:
        n = len(rows)
        if n == 0:
            return
        plane = self.plane
        with plane.gate:
            if timestamps is None or isinstance(timestamps, int):
                ts = timestamps if timestamps is not None else \
                    plane.shards[0].ctx.timestamp_generator.current_time()
                tss = [ts] * n
            else:
                tss = [int(t) for t in timestamps]
            for shard, (stss, srows) in plane.router.split_rows(
                    tss, rows, self._key_index).items():
                self._shard_handler(shard).send_batch(srows, timestamps=stss)

    def send_columns(self, columns: dict, timestamps=None,
                     count: Optional[int] = None) -> None:
        n = count if count is not None else \
            min(len(v[2]) if isinstance(v, tuple) else len(v)
                for v in columns.values())
        if n == 0:
            return
        plane = self.plane
        with plane.gate:
            if timestamps is None or isinstance(timestamps, int):
                ts = timestamps if timestamps is not None else \
                    plane.shards[0].ctx.timestamp_generator.current_time()
                ts_arr = np.full(n, ts, dtype=np.int64)
            else:
                ts_arr = np.asarray(timestamps, dtype=np.int64)
            from ..io import wire
            for shard, (ts_sub, cols_sub, cnt) in plane.router.split_columns(
                    columns, ts_arr, n).items():
                plain = {k: (wire.materialize_strings(v)
                             if isinstance(v, tuple) else v)
                         for k, v in cols_sub.items()}
                self._shard_handler(shard).send_columns(
                    plain, timestamps=ts_sub, count=cnt)

    def deliver_frames(self, body) -> int:
        """SXF1 frame path: decode once, hash the key column's DISTINCT
        dictionary values, split per shard with compacted dictionaries,
        deliver each subset through the shard's own frame-speed path."""
        from ..io import wire
        plan = wire.schema_plan(self.definition)
        total = 0
        plane = self.plane
        for payload in wire.iter_frames(body):
            ts, cols, n = wire.decode_frame(payload, plan)
            if n == 0:
                continue
            with plane.gate:
                if ts is None:
                    now = plane.shards[0].ctx.timestamp_generator \
                        .current_time()
                    ts = np.full(n, now, dtype=np.int64)
                for shard, (ts_sub, cols_sub, cnt) in \
                        plane.router.split_columns(cols, ts, n).items():
                    h = self._shard_handler(shard)
                    plain = {
                        k: (wire.materialize_strings(v)
                            if isinstance(v, tuple) else v)
                        for k, v in cols_sub.items()}
                    h.send_columns(plain, timestamps=ts_sub, count=cnt)
                total += n
        return total


class ShardPlane:
    """N-replica sharded runtime behind the `SiddhiAppRuntime` duck-typed
    surface (service.py, manager registry, persist/recover, statistics all
    work unchanged)."""

    is_shard_plane = True

    def __init__(self, app: SiddhiApp, registry, *,
                 config: Optional[ShardConfig] = None,
                 wal_dir: Optional[str] = None,
                 persistence_interval_s=None, **runtime_kwargs) -> None:
        if config is None:
            config = shard_config(app, strict=True)
        if config is None:
            raise SiddhiAppCreationError(
                f"app {app.name!r} has no @app:shards annotation")
        check_shardable(app, config.key)  # refuse global plans loudly
        self.app = app
        self.name = app.name
        self.config = config
        self.n_shards = config.n
        self.key = config.key
        self.wal_base = wal_dir
        self._registry = registry
        self._runtime_kwargs = dict(runtime_kwargs)
        self._persistence_interval_s = persistence_interval_s
        self._persistence_store = None
        self._callbacks: list[tuple] = []  # ("stream"|"query", id, args)
        self._handlers: dict[str, ShardInputHandler] = {}
        self.lint_report = None
        self.gate = _IngressGate()
        self.rebalances = 0
        self._persisted_since_epoch = False
        self._started = False

        self.epoch, assignment = self._read_meta()
        self.router = ShardRouter(config.key, config.n,
                                  n_slots=_n_slots(),
                                  assignment=assignment)
        self.shards = [self._build_shard(i) for i in range(self.n_shards)]

    # ------------------------------------------------------------- replicas

    def _shard_name(self, i: int) -> str:
        return shard_app_name(self.name, i)

    def _shard_app(self, i: int) -> SiddhiApp:
        return shard_app(self.app, i)

    def _epoch_wal_dir(self, epoch: int) -> Optional[str]:
        return epoch_wal_dir(self.wal_base, epoch)

    def _build_shard(self, i: int, *, epoch: Optional[int] = None,
                     with_wal: bool = True):
        from ..core.app_runtime import SiddhiAppRuntime
        wd = self._epoch_wal_dir(self.epoch if epoch is None else epoch) \
            if with_wal else None
        rt = SiddhiAppRuntime(
            self._shard_app(i), self._registry, wal_dir=wd,
            persistence_interval_s=self._persistence_interval_s,
            **self._runtime_kwargs)
        if self._persistence_store is not None:
            rt.persistence_store = self._persistence_store
        if not rt.ctx.statistics.enabled:
            # per-shard statistics sections and the conservation identity
            # need per-stream delivery counts; BASIC is dict increments
            rt.set_statistics_level("BASIC")
        return rt

    # ------------------------------------------------------------ meta file

    def _meta_path(self) -> Optional[str]:
        if self.wal_base is None:
            return None
        return os.path.join(self.wal_base, f"{self.name}.shardmeta.json")

    def _read_meta(self):
        path = self._meta_path()
        if path is None or not os.path.exists(path):
            return 0, None
        try:
            with open(path) as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError):
            log.warning("shard meta %s unreadable; starting at epoch 0",
                        path)
            return 0, None
        if meta.get("n_shards") != self.config.n or \
                meta.get("n_slots") != _n_slots() or \
                meta.get("key") != self.config.key:
            raise SiddhiAppCreationError(
                f"shard meta {path} was written for "
                f"n={meta.get('n_shards')} key={meta.get('key')!r} "
                f"slots={meta.get('n_slots')}; the app now asks for "
                f"n={self.config.n} key={self.config.key!r} "
                f"slots={_n_slots()} — recover with the original layout "
                "first (docs/SHARDING.md)")
        return int(meta.get("epoch", 0)), meta.get("assignment")

    def _write_meta(self, epoch: int, assignment) -> None:
        path = self._meta_path()
        if path is None:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "n_shards": self.n_shards,
                       "n_slots": self.router.n_slots,
                       "key": self.key,
                       "assignment": [int(s) for s in assignment]}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # the rebalance commit point

    # ------------------------------------------------------------ lifecycle

    def start(self, **kw) -> None:
        for rt in self.shards:
            rt.start(**kw)
        self._started = True

    def shutdown(self, *, flush_durable: bool = True, **kw) -> None:
        for rt in self.shards:
            if rt is not None:
                rt.shutdown(flush_durable=flush_durable, **kw)
        self._started = False

    def flush(self, now: Optional[int] = None) -> None:
        for rt in self.shards:
            rt.flush(now)

    def drain(self) -> None:
        for rt in self.shards:
            rt.drain()

    def warmup(self, buckets=None) -> dict:
        return {f"s{i}": rt.warmup(buckets)
                for i, rt in enumerate(self.shards)}

    def connect_sources(self) -> None:  # duck-typing: planes have none
        pass

    # ----------------------------------------------------------- ingestion

    def get_input_handler(self, stream_id: str) -> ShardInputHandler:
        h = self._handlers.get(stream_id)
        if h is None:
            h = self._handlers[stream_id] = ShardInputHandler(
                self, stream_id)
        return h

    # ----------------------------------------------------------- callbacks

    def add_callback(self, stream_id: str, callback,
                     columnar: bool = False) -> None:
        self._callbacks.append(("stream", stream_id, (callback, columnar)))
        for rt in self.shards:
            rt.add_callback(stream_id, callback, columnar=columnar)

    def add_query_callback(self, query_name: str, callback) -> None:
        self._callbacks.append(("query", query_name, (callback,)))
        for rt in self.shards:
            rt.add_query_callback(query_name, callback)

    def _resubscribe(self, rt) -> None:
        for kind, name, args in self._callbacks:
            if kind == "stream":
                cb, columnar = args
                rt.add_callback(name, cb, columnar=columnar)
            else:
                rt.add_query_callback(name, args[0])

    # ---------------------------------------------------------- durability

    @property
    def persistence_store(self):
        return self._persistence_store

    @persistence_store.setter
    def persistence_store(self, store) -> None:
        self._persistence_store = store
        for rt in self.shards:
            rt.persistence_store = store

    def persist(self) -> dict:
        """Snapshot + journal-rotate every shard. NOTE: rotation prunes
        each shard's full WAL history, which `rebalance()` needs — a
        post-persist rebalance is refused until the next epoch."""
        out = {f"s{i}": rt.persist() for i, rt in enumerate(self.shards)}
        self._persisted_since_epoch = True
        return out

    def restore_last_revision(self) -> dict:
        return {f"s{i}": rt.restore_last_revision()
                for i, rt in enumerate(self.shards)}

    def recover(self) -> dict:
        """Per-shard crash recovery (restore last revision + replay the
        shard's own journal). Total `wal_replayed` sums the fleet."""
        per = {}
        replayed = 0
        for i, rt in enumerate(self.shards):
            r = rt.recover()
            per[f"s{i}"] = r
            replayed += int(r.get("wal_replayed", 0))
        return {"revision": {k: v.get("revision") for k, v in per.items()},
                "wal_replayed": replayed, "shards": per}

    # -------------------------------------------------------------- health

    def health(self) -> dict:
        """Worst-state merge: one degraded/recovering shard degrades the
        plane (load balancers should drain while a shard sheds load).
        Breakers/queues are namespaced `s<i>/...`."""
        order = {"stopped": 3, "recovering": 2, "degraded": 1, "running": 0}
        state = "stopped" if not self.shards else "running"
        breakers: dict = {}
        queues: dict = {}
        for i, rt in enumerate(self.shards):
            if rt is None:
                state = "stopped"
                continue
            h = rt.health()
            if order.get(h["state"], 3) > order.get(state, 0):
                state = h["state"]
            for k, v in h["breakers"].items():
                breakers[f"s{i}/{k}"] = v
            for k, v in h["queues"].items():
                queues[f"s{i}/{k}"] = v
        return {"state": state, "breakers": breakers, "queues": queues}

    # ---------------------------------------------------------- statistics

    @property
    def cost_report(self) -> dict:
        """Fleet-priced static prediction: `@app:shards` makes
        analysis/cost.py multiply state and compile ladders by the shard
        count, so this is the number admission control charges."""
        rep = getattr(self, "_cost_report", None)
        if rep is None:
            from ..analysis.cost import compute_cost
            ctx = self.shards[0].ctx
            rep = compute_cost(self.app, batch_size=ctx.batch_size,
                               group_capacity=ctx.group_capacity).to_dict()
            self._cost_report = rep
        return rep

    def conservation_report(self) -> dict:
        """The routing conservation identity, checkable after `drain()`:
        every routed row is delivered to, dropped by, or diverted from
        exactly one shard — `sent == sum(delivered + dropped + diverted)`
        per routed stream and in total."""
        routed_streams = set(self._handlers)
        per_shard = {}
        delivered = dropped = diverted = 0
        for i, rt in enumerate(self.shards):
            st = rt.ctx.statistics
            d = sum(int(st.events_in.get(s, 0)) for s in routed_streams)
            dr = sum(sum(pol.values())
                     for s, pol in st.ingress_dropped.items()
                     if s in routed_streams)
            dv = sum(int(n) for s, n in st.late_events.items()
                     if s in routed_streams)
            per_shard[f"s{i}"] = {"delivered": d, "dropped": dr,
                                  "diverted": dv,
                                  "routed": int(self.router.routed[i])}
            delivered += d
            dropped += dr
            diverted += dv
        sent = int(self.router.total_rows)
        return {"sent": sent, "delivered": delivered, "dropped": dropped,
                "diverted": diverted,
                "conserved": sent == delivered + dropped + diverted,
                "per_shard": per_shard}

    def skew_report(self) -> dict:
        rep = self.router.skew_report()
        rep["epoch"] = self.epoch
        rep["rebalances"] = self.rebalances
        return rep

    def statistics_report(self) -> dict:
        """Per-shard sections + the plane's own routing/skew/conservation
        view (the service's /statistics endpoint serves this verbatim)."""
        return {
            "app": self.name,
            "shard_plane": {
                "n_shards": self.n_shards,
                "key": self.key,
                "epoch": self.epoch,
                "n_slots": self.router.n_slots,
                "rebalances": self.rebalances,
                "skew": self.router.skew_report(),
            },
            "conservation": self.conservation_report(),
            "shards": {f"s{i}": rt.statistics_report()
                       for i, rt in enumerate(self.shards)
                       if rt is not None},
            "cost": self.cost_report,
        }

    # --------------------------------------------------------- shard moves

    def move_shard(self, i: int) -> dict:
        """Blue-green swap of ONE shard replica onto a fresh runtime —
        core/upgrade.py's recipe at shard granularity: shadow-build, pause
        intake, drain, per-element snapshot/restore, WAL handover (the new
        runtime ADOPTS the journal object — no re-journaling, no second
        append handle), callback re-subscription, atomic cutover, retire.
        The key->shard assignment does not change; this moves the replica,
        e.g. off a sick device."""
        old = self.shards[i]
        new = self._build_shard(i, with_wal=False)
        new.start(connect_sources=False, start_persist_scheduler=False)
        self.gate.pause()
        try:
            old.flush()
            old.drain()
            blob = old.snapshot()
            new.restore(blob)
            wal = old.wal
            if wal is not None:
                old.wal = None
                for j in old.junctions.values():
                    j.wal = None
                new.wal = wal
                for sid in new.app.stream_definitions:
                    j2 = new.junctions.get(sid)
                    if j2 is not None:
                        j2.wal = wal
            self._resubscribe(new)
            self.shards[i] = new
        except Exception:
            new.shutdown(flush_durable=False)
            raise
        finally:
            self.gate.resume()
        old.shutdown(flush_durable=False)
        return {"moved": i, "epoch": self.epoch}

    def kill_shard(self, i: int) -> None:
        """Chaos helper: simulate a shard replica dying without any clean
        shutdown (its WAL handle is released the way process death would
        release it; staged-but-unflushed work is lost). Pair with
        `recover_shard`."""
        rt = self.shards[i]
        if rt is None:
            return
        try:
            if rt.wal is not None:
                rt.wal.close()
        except Exception:
            pass
        self.shards[i] = None

    def recover_shard(self, i: int) -> dict:
        """Rebuild a dead shard from its durable state: fresh replica on
        the same WAL directory (torn tails truncate on resume), restore
        the last persisted revision, replay the journal — the per-shard
        half of `recover()`."""
        if self.shards[i] is not None:
            raise SiddhiAppCreationError(
                f"shard {i} of {self.name!r} is alive; kill it first")
        rt = self._build_shard(i)
        rt.start()
        self._resubscribe(rt)
        out = rt.recover()
        self.shards[i] = rt
        return out

    # ----------------------------------------------------------- rebalance

    def rebalance(self, assignment=None, *, force: bool = False,
                  threshold: float = 1.25) -> dict:
        """Skew-triggered live resharding. Consults the router's skew
        counters; below `threshold` imbalance (max shard load over the
        even-split ideal) it is a no-op unless `force`d or given an
        explicit `assignment`. The move itself is a fleet-wide blue-green
        swap: pause intake, drain, rebuild every replica from the full
        per-shard WAL history re-routed through the new assignment, commit
        the epoch meta atomically, cut over, retire the old fleet. Refused
        without a WAL or after a `persist()` rotated the journal (device
        state is not key-addressable — the journal IS the migration
        format)."""
        skew = self.router.skew_report()
        if assignment is None:
            if not force and skew["imbalance"] < threshold:
                return {"rebalanced": False, "reason":
                        f"imbalance {skew['imbalance']:.2f} below "
                        f"threshold {threshold:.2f}", "skew": skew}
            proposal = self.router.propose_assignment()
        else:
            proposal = np.asarray(assignment, dtype=np.int64)
            if proposal.shape[0] != self.router.n_slots or \
                    (len(proposal) and proposal.max() >= self.n_shards):
                raise SiddhiAppCreationError(
                    f"rebalance: assignment must map "
                    f"{self.router.n_slots} slots to [0, {self.n_shards})")
        moved = [s for s in range(self.router.n_slots)
                 if int(proposal[s]) != int(self.router.assignment[s])]
        if not moved:
            return {"rebalanced": False, "reason": "assignment unchanged",
                    "skew": skew}
        if self.wal_base is None:
            raise SiddhiAppCreationError(
                f"rebalance of {self.name!r} needs a WAL (wal_dir=): "
                "device state is reconstructed by re-routing the journal")
        if self._persisted_since_epoch:
            raise SiddhiAppCreationError(
                f"rebalance of {self.name!r} refused: persist() rotated "
                "the journal this epoch, so the full per-key history is "
                "gone — rebalance before persisting (docs/SHARDING.md)")

        new_epoch = self.epoch + 1
        old_router = self.router
        new_router = ShardRouter(self.key, self.n_shards,
                                 n_slots=old_router.n_slots,
                                 assignment=proposal)
        self.gate.pause()
        new_shards: list = []
        try:
            for rt in self.shards:
                rt.flush()
                rt.drain()
            # shadow fleet on the NEW epoch's journal directory; replayed
            # sends re-journal themselves there (the recover() idiom)
            for i in range(self.n_shards):
                rt = self._build_shard(i, epoch=new_epoch)
                rt.start(connect_sources=False,
                         start_persist_scheduler=False)
                new_shards.append(rt)
            replayed = 0
            for old_rt in self.shards:
                if old_rt is None or old_rt.wal is None:
                    continue
                for kind, sid, tss, data in old_rt.wal.records():
                    # key-local plans make cross-key interleaving
                    # irrelevant: a key's records are contiguous within
                    # ONE old shard's journal, so shard-by-shard replay
                    # preserves every per-key sequence
                    if kind == "rows":
                        key_idx = [a.name for a in old_rt.junctions[sid]
                                   .definition.attributes].index(self.key)
                        for shard, (stss, srows) in new_router.split_rows(
                                tss, data, key_idx).items():
                            new_shards[shard].get_input_handler(sid) \
                                .send_batch(srows, timestamps=stss)
                    elif kind == "cols":
                        ts_arr = np.asarray(tss, dtype=np.int64)
                        for shard, (ts_sub, cols_sub, cnt) in \
                                new_router.split_columns(
                                    data, ts_arr, len(tss)).items():
                            new_shards[shard].get_input_handler(sid) \
                                .send_columns(cols_sub, timestamps=ts_sub,
                                              count=cnt)
                    else:  # generic journal marks are not events
                        continue
                    replayed += len(tss)
            for rt in new_shards:
                rt.flush()
                rt.drain()
            # replay accounting is not live traffic: the new router starts
            # the epoch with clean skew counters
            new_router.reset_counters()
            # COMMIT: the meta rename is the atomic cutover point — a
            # crash before it recovers the old epoch, after it the new
            self._write_meta(new_epoch, proposal)
            for rt in new_shards:
                self._resubscribe(rt)
            old_shards, self.shards = self.shards, new_shards
            self.router = new_router
            self.epoch = new_epoch
            self.rebalances += 1
            self._persisted_since_epoch = False
            self._handlers.clear()
        except Exception:
            for rt in new_shards:
                try:
                    rt.shutdown(flush_durable=False)
                except Exception:  # pragma: no cover — best-effort rollback
                    pass
            raise
        finally:
            self.gate.resume()
        for rt in old_shards:
            if rt is not None:
                try:
                    rt.shutdown(flush_durable=False)
                except Exception:  # pragma: no cover
                    pass
        log.info("rebalance %s: epoch %d -> %d, %d slot(s) moved, "
                 "%d event(s) re-routed", self.name, new_epoch - 1,
                 new_epoch, len(moved), replayed)
        return {"rebalanced": True, "epoch": new_epoch,
                "moved_slots": len(moved), "replayed": replayed,
                "assignment": [int(s) for s in proposal], "skew": skew}
