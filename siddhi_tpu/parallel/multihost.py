"""Multi-host execution over DCN — jax.distributed bring-up + mesh builder.

Reference counterpart: none — the reference is single-JVM (SURVEY §2.5); its
scale-out story is keyed partitions and sharded aggregations, which this
framework already runs over an ICI mesh (parallel/sharded.py,
core/aggregation.py mesh mode). This module extends the SAME mesh programming
model across hosts: every host runs the same single-controller program,
`jax.distributed` connects the processes, and `global_mesh()` lays the
partition axis over ALL devices so shard_map collectives ride ICI within a
slice and DCN across slices — exactly the "pick a mesh, annotate shardings,
let XLA insert collectives" recipe.

Deployment (one process per host, identical code):

    from siddhi_tpu.parallel.multihost import init_distributed, global_mesh

    init_distributed(coordinator="10.0.0.1:8476",
                     num_processes=4, process_id=HOST_INDEX)
    mesh = global_mesh()                      # all hosts' devices, one axis
    rt = SiddhiManager().create_siddhi_app_runtime(app, mesh=mesh, ...)

Each host feeds ITS OWN events through its InputHandlers; key-hash ownership
(parallel/sharded.shard_owned) makes every shard process only its keys, so a
round-robin (or any) external partitioner in front of the hosts yields the
same results as one big host. On-demand reads that merge shards
(aggregation find(), partition state) execute as global programs — call them
from every process collectively, per SPMD rules.

Caveats (documented, enforced where cheap):
- all hosts must run the SAME app and the SAME sequence of global programs
  (standard jax multi-process discipline);
- host-side state (tables without mesh sharding, record stores, string
  interning) is per-host; multi-host apps should key all cross-host state by
  the mesh (partitions, sharded aggregations) or an external store;
- this module only wires processes together — single-host multi-chip apps
  never need it.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def init_distributed(coordinator: str, num_processes: int, process_id: int,
                     local_device_ids: Optional[list[int]] = None) -> None:
    """Connect this process to the jax.distributed cluster (idempotent).

    coordinator: "host:port" of process 0; every process passes the same.
    """
    import jax

    try:
        already = jax.distributed.is_initialized()
    except AttributeError:  # older jax: no public probe
        from jax._src import distributed as _dist
        already = getattr(_dist.global_state, "client", None) is not None
    if already:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def global_mesh(axis_name: str = "part"):
    """One-axis mesh over every device of every connected process — the
    partition/shard axis used by mesh-enabled runtimes. Within a slice the
    axis rides ICI; across slices XLA routes collectives over DCN."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis_name,))


def global_lane_batch(codec, timestamps, cols: dict, mesh, key_attrs,
                      lane_width: int):
    """Per-host SHARDED ingestion: encode THIS host's rows, route each to
    its owning shard (the same key-hash rule as shard_owned), and assemble
    one lane-sharded global EventBatch via
    jax.make_array_from_process_local_data — each host moves only its own
    bytes over DCN (SURVEY §2.5's per-host half; replicated ingestion
    re-encodes the full stream on every host).

    Contract: this host's rows must be OWNED by this host's addressable
    shards (an external key partitioner in front of the hosts); rows owned
    elsewhere are dropped with a count in the returned tuple. STRING key
    columns must intern to IDENTICAL codes on every host (pre-encode the
    symbol universe in one agreed order).

    Returns (global_batch, n_dropped_foreign)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..core.event import EventBatch
    from .sharded import np_shard_of

    axis = mesh.axis_names[0]
    n_shards = mesh.shape[axis]
    ts = np.asarray(timestamps, dtype=np.int64)
    n = ts.shape[0]
    enc = codec.encode_columns(cols, n)
    shard_of = np_shard_of([enc[a] for a in key_attrs], n_shards)

    mesh_flat = list(mesh.devices.flat)
    local_ids = [i for i, d in enumerate(mesh_flat)
                 if d.process_index == jax.process_index()]
    n_local = len(local_ids)
    dropped = 0

    lane_ts = np.zeros((n_local, lane_width), np.int64)
    lane_valid = np.zeros((n_local, lane_width), bool)
    lane_cols = {k: np.zeros((n_local, lane_width), v.dtype)
                 for k, v in enc.items()}
    truncated = 0
    for li, sid in enumerate(local_ids):
        idx = np.nonzero(shard_of == sid)[0]
        if idx.size > lane_width:
            import warnings
            truncated += idx.size - lane_width
            warnings.warn(
                f"global_lane_batch: shard {sid} got {idx.size} rows but "
                f"lane_width={lane_width}; excess dropped — raise "
                "lane_width or split the send", stacklevel=2)
            idx = idx[:lane_width]
        m = idx.size
        lane_ts[li, :m] = ts[idx]
        lane_valid[li, :m] = True
        for k in lane_cols:
            lane_cols[k][li, :m] = enc[k][idx]
    # total rows NOT ingested: foreign-shard rows + lane-width truncation
    dropped = int(np.sum(~np.isin(shard_of, local_ids))) + truncated

    sharding = NamedSharding(mesh, P(axis))

    def put(local2d):
        flat = local2d.reshape(n_local * lane_width)
        return jax.make_array_from_process_local_data(
            sharding, flat, (n_shards * lane_width,))

    batch = EventBatch(
        ts=put(lane_ts),
        cols={k: put(v) for k, v in lane_cols.items()},
        valid=put(lane_valid),
        types=put(np.zeros((n_local, lane_width), np.int8)),
    )
    return batch, dropped


def is_coordinator() -> bool:
    """True on process 0 — the conventional place for host-only side effects
    (REST service, persistence-store writes, log sinks)."""
    import jax

    return jax.process_index() == 0
