"""Multi-host execution over DCN — jax.distributed bring-up + mesh builder.

Reference counterpart: none — the reference is single-JVM (SURVEY §2.5); its
scale-out story is keyed partitions and sharded aggregations, which this
framework already runs over an ICI mesh (parallel/sharded.py,
core/aggregation.py mesh mode). This module extends the SAME mesh programming
model across hosts: every host runs the same single-controller program,
`jax.distributed` connects the processes, and `global_mesh()` lays the
partition axis over ALL devices so shard_map collectives ride ICI within a
slice and DCN across slices — exactly the "pick a mesh, annotate shardings,
let XLA insert collectives" recipe.

Deployment (one process per host, identical code):

    from siddhi_tpu.parallel.multihost import init_distributed, global_mesh

    init_distributed(coordinator="10.0.0.1:8476",
                     num_processes=4, process_id=HOST_INDEX)
    mesh = global_mesh()                      # all hosts' devices, one axis
    rt = SiddhiManager().create_siddhi_app_runtime(app, mesh=mesh, ...)

Each host feeds ITS OWN events through its InputHandlers; key-hash ownership
(parallel/sharded.shard_owned) makes every shard process only its keys, so a
round-robin (or any) external partitioner in front of the hosts yields the
same results as one big host. On-demand reads that merge shards
(aggregation find(), partition state) execute as global programs — call them
from every process collectively, per SPMD rules.

Caveats (documented, enforced where cheap):
- all hosts must run the SAME app and the SAME sequence of global programs
  (standard jax multi-process discipline);
- host-side state (tables without mesh sharding, record stores, string
  interning) is per-host; multi-host apps should key all cross-host state by
  the mesh (partitions, sharded aggregations) or an external store;
- this module only wires processes together — single-host multi-chip apps
  never need it.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def init_distributed(coordinator: str, num_processes: int, process_id: int,
                     local_device_ids: Optional[list[int]] = None) -> None:
    """Connect this process to the jax.distributed cluster (idempotent).

    coordinator: "host:port" of process 0; every process passes the same.
    """
    import jax

    try:
        already = jax.distributed.is_initialized()
    except AttributeError:  # older jax: no public probe
        from jax._src import distributed as _dist
        already = getattr(_dist.global_state, "client", None) is not None
    if already:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def global_mesh(axis_name: str = "part"):
    """One-axis mesh over every device of every connected process — the
    partition/shard axis used by mesh-enabled runtimes. Within a slice the
    axis rides ICI; across slices XLA routes collectives over DCN."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis_name,))


def is_coordinator() -> bool:
    """True on process 0 — the conventional place for host-only side effects
    (REST service, persistence-store writes, log sinks)."""
    import jax

    return jax.process_index() == 0
