"""siddhi_tpu.parallel — multi-device and multi-replica execution.

`sharded.py` shards columnar batches across mesh devices inside ONE
runtime; `multihost.py` coordinates multi-process ingestion; and
`shard_plane.py` runs N full pipeline replicas behind a partition-key
router (`@app:shards(n=, key=)` — the manager builds a `ShardPlane`
transparently)."""

from __future__ import annotations

__all__ = ["ShardPlane", "ShardInputHandler"]


def __getattr__(name: str):
    # lazy: importing the plane pulls in the whole runtime stack, which
    # the light-weight mesh helpers in sharded.py must not pay for
    if name in __all__:
        from . import shard_plane
        return getattr(shard_plane, name)
    raise AttributeError(name)
