"""Multi-host shard serving tier: SXF1 frame routing with failover.

PR 14 built the sharded execution plane, but every replica still lives in
one process. This module stretches the ShardRouter across processes:

  ``FrontTier``   the router — speaks SXF1, hashes partition keys with the
                  SAME FNV-1a two-level slot map as the in-process plane
                  (``ShardRouter.split_columns`` reused verbatim, hashing
                  ORIGINAL pre-interning values), re-encodes each shard's
                  subset as its own frame, and forwards it over HTTP to
                  the worker host that owns the shard.
  ``ShardHost``   the worker side — lives inside ``service.py`` behind
                  ``/shard-host/*`` endpoints; builds replica runtimes
                  (``shard_plane.shard_app`` — identical identity to a
                  local plane's replicas, per-shard WAL dirs and all),
                  validates the epoch stamped on every frame, journals a
                  per-frame ``"mark"`` seq record for duplicate detection,
                  and performs shard adoption after a host death.

Delivery semantics (the operator-semantics survey's vocabulary, arXiv
2303.00793): **at-least-once across the ack window, exactly-once
everywhere else**. A worker ack implies the frame's rows are journaled
(the WAL append in the send path is synchronous), so a frame whose ack
was lost is spooled by the router and — on replay — rejected by the
worker as a duplicate via the journaled seq mark. The only unclosable
window is a SIGKILL that lands between the rows append and the mark
append of one frame: that frame replays twice (never zero times).

Failure handling:

  * per-host heartbeat (``/shard-host/ping``) with a miss-count deadline
    detector; forwards use bounded exponential-backoff retries;
  * frames addressed to an unreachable owner land in a durable per-shard
    **spool** (the state/wal.py segment format, generic ``"frame"``
    records) in arrival order, and replay — original seqs, re-stamped
    epochs — when the owner recovers or a survivor adopts the shard;
  * on detector-confirmed death the router drives **takeover**: bump the
    dead shards' epochs, commit the new ``<App>.shardmeta.json``
    atomically (the fence point), have a surviving worker adopt each
    shard by replaying its per-shard WAL dirs (the recover_shard /
    move_shard journal-is-the-migration-format discipline), then replay
    the spool with the adoption's ``last_seq`` deduping the ack window;
  * a zombie host returning mid-takeover is fenced by epoch: its deploy
    at a stale epoch is refused against the durable meta, stale-epoch
    frames are rejected at the worker (409), counted, and re-routed by
    the sender after it refreshes its view from the meta file;
  * slots whose shard has NO live owner divert to the replayable
    ErrorStore (kind="unowned") instead of blocking — and ``/ready``
    answers 503 while any plane is degraded.

Conservation identity, checkable after ``drain()``::

    sent == delivered + spool_replayed + diverted        (+ spooled_pending
                                                          before drain)

Shared-filesystem contract: the router and every worker see the same
``wal_dir`` (one machine, or a shared mount). The meta file doubles as
the fence ledger, and adoption reads the dead host's journals directly.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
import urllib.error
import urllib.request
from typing import Optional
from urllib.parse import quote

import numpy as np

from ..analysis.sharding import check_shardable, shard_config
from ..core.ingress import ShardRouter
from ..errors import SiddhiAppCreationError, SiddhiError
from ..io import wire
from ..state.wal import WriteAheadLog, read_records
from ..util.locks import named_lock, named_rlock, note_blocking
from .shard_plane import _n_slots, epoch_wal_dir, shard_app, shard_app_name

log = logging.getLogger("siddhi_tpu")

#: spool journal sub-directory under the front tier's wal_dir
SPOOL_DIR = "_router_spool"


def _meta_path(wal_dir: str, app_name: str) -> str:
    return os.path.join(wal_dir, f"{app_name}.shardmeta.json")


def _read_meta_file(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        log.warning("shard meta %s unreadable", path)
        return None


def _py(v):
    """JSON-safe scalar (numpy → python)."""
    return v.item() if hasattr(v, "item") else v


def _http(method: str, url: str, *, body: Optional[bytes] = None,
          ctype: str = "application/json", token: Optional[str] = None,
          timeout: float = 5.0) -> tuple[int, dict]:
    """One HTTP exchange. 4xx/5xx come back as (status, body) — only
    transport-level failures raise (OSError/URLError)."""
    headers = {"Content-Type": ctype}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            status = resp.status
    except urllib.error.HTTPError as e:
        raw = e.read()
        status = e.code
    try:
        payload = json.loads(raw) if raw else {}
    except (ValueError, UnicodeDecodeError):
        payload = {"raw": repr(raw[:200])}
    return status, payload


# ========================================================================= #
# worker side
# ========================================================================= #


class ShardHost:
    """The worker-side adoption hooks: owns this process's shard replicas
    for any number of sharded apps, enforces epoch fencing against the
    durable shardmeta ledger, and journals per-frame seq marks so an
    adoption can report ``last_seq`` for spool dedupe. Constructed lazily
    by SiddhiService and driven through the ``/shard-host/*`` routes."""

    def __init__(self, manager) -> None:
        self.manager = manager
        self._lock = named_rlock("shard_host.registry")
        #: (app, shard) -> {"epoch", "runtime", "wal_base", "capture"}
        self.owned: dict = {}
        #: (app, shard) -> last frame seq journaled as a "mark"
        self.last_seq: dict = {}
        #: (app, shard) -> list of [stream, ts, [values...]] in emit order
        self.captured: dict = {}
        #: app -> (meta path, last seen mtime_ns)
        self._meta_seen: dict = {}
        self._app_texts: dict = {}
        self.stale_rejected = 0
        self.fenced_shards = 0
        self.fenced_deploys = 0
        self.duplicate_frames = 0

    # ---------------------------------------------------------------- meta

    def _meta_epoch_for(self, meta: Optional[dict], shard: int) -> int:
        if not meta:
            return 0
        eps = meta.get("shard_epochs")
        if isinstance(eps, list) and shard < len(eps):
            return int(eps[shard])
        return int(meta.get("epoch", 0))

    def _check_meta(self, app_name: str, *, force: bool = False) -> None:
        """Self-fencing: re-read the durable meta when its mtime moved (or
        on demand) and drop any owned shard whose committed epoch has
        advanced past ours — a zombie learns of its own death here."""
        seen = self._meta_seen.get(app_name)
        if seen is None:
            return
        path, mtime = seen
        try:
            now = os.stat(path).st_mtime_ns
        except OSError:
            return
        if not force and now == mtime:
            return
        self._meta_seen[app_name] = (path, now)
        meta = _read_meta_file(path)
        if meta is None:
            return
        with self._lock:
            for (a, i), ent in list(self.owned.items()):
                if a != app_name:
                    continue
                want = self._meta_epoch_for(meta, i)
                if ent["epoch"] < want:
                    self._drop_replica(a, i, reason=f"meta epoch {want}")

    def _drop_replica(self, app_name: str, shard: int, reason: str) -> None:
        ent = self.owned.pop((app_name, shard), None)
        if ent is None:
            return
        self.fenced_shards += 1
        rname = shard_app_name(app_name, shard)
        self.manager.runtimes.pop(rname, None)
        try:
            ent["runtime"].shutdown(flush_durable=False)
        except Exception:  # noqa: BLE001 — fencing must not wedge
            pass
        log.warning("shard host: fenced %s shard %d at epoch %d (%s)",
                    app_name, shard, ent["epoch"], reason)

    # -------------------------------------------------------------- deploy

    def _build_replica(self, app, shard: int, wal_base: Optional[str],
                       epoch: int, capture, runtime_kwargs: dict):
        replica = shard_app(app, shard)
        wd = epoch_wal_dir(wal_base, epoch)
        rt = self.manager.create_siddhi_app_runtime(
            replica, wal_dir=wd, **runtime_kwargs)
        if rt is None:  # budget-queued — not a serving replica
            raise SiddhiError(
                f"replica {replica.name} was queued by admission control; "
                "a shard host cannot defer a shard")
        rt.start()
        key = (app.name, shard)
        self.captured.setdefault(key, [])
        sink = self.captured[key]
        for sid in capture or ():
            rt.add_callback(sid, self._make_capture(sink, sid))
        # env-driven chaos (SIDDHI_FAULT_SPEC) applies per replica, so the
        # kill-one-host drill runs with the same seeded faults a local
        # soak run would inject
        from ..util.faults import apply_fault_spec
        apply_fault_spec(rt)
        return rt

    @staticmethod
    def _make_capture(sink: list, stream: str):
        def cb(events):
            for e in events:
                sink.append([stream, int(e.timestamp),
                             [_py(v) for v in e.data]])
        return cb

    def deploy(self, app_text: str, shards, wal_dir: Optional[str],
               epoch: int = 0, shard_epochs: Optional[dict] = None,
               capture=(), runtime_kwargs: Optional[dict] = None) -> dict:
        """Build + start replicas for `shards` of the app in `app_text`.
        Each shard's epoch is fence-checked against the durable meta: a
        deploy at a stale epoch (a zombie re-announcing itself after a
        takeover) is refused and counted."""
        from .. import compiler
        app = compiler.parse(app_text)
        kwargs = dict(runtime_kwargs or {})
        if wal_dir is not None:
            path = _meta_path(wal_dir, app.name)
            try:
                mtime = os.stat(path).st_mtime_ns
            except OSError:
                mtime = 0
            self._meta_seen[app.name] = (path, mtime)
        self._app_texts[app.name] = app_text
        meta = _read_meta_file(_meta_path(wal_dir, app.name)) \
            if wal_dir is not None else None
        deployed, fenced = [], []
        with self._lock:
            for i in shards:
                i = int(i)
                ep = int((shard_epochs or {}).get(str(i), epoch))
                want = self._meta_epoch_for(meta, i)
                if ep < want:
                    self.fenced_deploys += 1
                    fenced.append({"shard": i, "epoch": ep,
                                   "committed_epoch": want})
                    log.warning(
                        "shard host: REFUSED deploy of %s shard %d at "
                        "stale epoch %d (committed epoch %d) — zombie "
                        "fenced", app.name, i, ep, want)
                    continue
                if (app.name, i) in self.owned:
                    self._drop_replica(app.name, i, reason="redeploy")
                    self.fenced_shards -= 1  # a redeploy is not a fence
                rt = self._build_replica(app, i, wal_dir, ep, capture,
                                         kwargs)
                self.owned[(app.name, i)] = {
                    "epoch": ep, "runtime": rt, "wal_base": wal_dir,
                    "capture": list(capture or ())}
                deployed.append(i)
        return {"app": app.name, "deployed": deployed, "fenced": fenced}

    # --------------------------------------------------------------- adopt

    def adopt(self, app_name: str, shard: int, epoch: int,
              wal_dir: str, capture=(),
              runtime_kwargs: Optional[dict] = None) -> dict:
        """Take over a dead host's shard: build a fresh replica journaling
        into the NEW epoch's WAL dir, then replay the newest prior-epoch
        journal (which is always the complete history: an adoption
        re-journals everything it replays, so each epoch's journal
        subsumes the ones before it). Returns ``last_seq`` — the highest
        frame seq the dead host journaled a mark for — so the router's
        spool replay can skip frames that were applied but whose ack was
        lost."""
        app_text = self._app_texts.get(app_name)
        if app_text is None:
            raise SiddhiError(
                f"shard host has no app text for {app_name!r}; deploy at "
                "least one shard of the app before adopting others")
        from .. import compiler
        app = compiler.parse(app_text)
        meta = _read_meta_file(_meta_path(wal_dir, app_name))
        want = self._meta_epoch_for(meta, int(shard))
        if int(epoch) < want:
            self.fenced_deploys += 1
            raise SiddhiError(
                f"adopt of {app_name} shard {shard} at epoch {epoch} is "
                f"fenced: committed epoch is {want}")
        rname = shard_app_name(app_name, int(shard))
        # a failed earlier adoption attempt at this epoch leaves a partial
        # re-journal; wipe it so replay starts from the intact prior epoch
        target_dir = os.path.join(epoch_wal_dir(wal_dir, int(epoch)), rname)
        shutil.rmtree(target_dir, ignore_errors=True)
        with self._lock:
            if (app_name, int(shard)) in self.owned:
                self._drop_replica(app_name, int(shard), reason="re-adopt")
                self.fenced_shards -= 1
            rt = self._build_replica(app, int(shard), wal_dir, int(epoch),
                                     capture, dict(runtime_kwargs or {}))
            last_seq = -1
            replayed = 0
            # newest prior epoch with a journal = the complete history
            for e in range(int(epoch) - 1, -1, -1):
                src = epoch_wal_dir(wal_dir, e)
                recs = list(read_records(src, rname))
                if not recs:
                    continue
                for kind, sid, tss, data in recs:
                    if kind == "mark":
                        last_seq = max(last_seq, int(data))
                    elif kind == "rows":
                        rt.get_input_handler(sid).send_batch(
                            data, timestamps=tss)
                        replayed += len(data)
                    elif kind == "cols":
                        rt.get_input_handler(sid).send_columns(
                            data,
                            timestamps=np.asarray(tss, dtype=np.int64))
                        replayed += len(tss)
                break
            rt.flush()
            rt.drain()
            self.owned[(app_name, int(shard))] = {
                "epoch": int(epoch), "runtime": rt, "wal_base": wal_dir,
                "capture": list(capture or ())}
            self.last_seq[(app_name, int(shard))] = last_seq
        log.warning("shard host: adopted %s shard %d at epoch %d "
                    "(%d event(s) replayed, last_seq=%d)",
                    app_name, shard, epoch, replayed, last_seq)
        return {"app": app_name, "shard": int(shard), "epoch": int(epoch),
                "replayed": replayed, "last_seq": last_seq}

    # --------------------------------------------------------------- fence

    def fence(self, app_name: str,
              shard_epochs: Optional[dict] = None) -> dict:
        """Drop every owned shard of `app_name` whose epoch is behind the
        committed one (from the request, falling back to the durable
        meta). Idempotent; the takeover flow broadcasts this to every
        reachable host."""
        dropped = []
        with self._lock:
            for (a, i), ent in list(self.owned.items()):
                if a != app_name:
                    continue
                want = None
                if shard_epochs is not None:
                    want = shard_epochs.get(str(i))
                if want is None:
                    seen = self._meta_seen.get(app_name)
                    if seen:
                        meta = _read_meta_file(seen[0])
                        want = self._meta_epoch_for(meta, i)
                if want is not None and ent["epoch"] < int(want):
                    self._drop_replica(a, i,
                                       reason=f"fence to epoch {want}")
                    dropped.append(i)
        return {"app": app_name, "fenced": dropped}

    # ------------------------------------------------------------- deliver

    def deliver(self, app_name: str, stream: str, shard: int,
                epoch: int, seq: Optional[int],
                body: bytes) -> tuple[int, dict]:
        """One forwarded frame. Epoch-validated (409 for a stale or
        unowned stamp — the router recounts and re-routes), seq-deduped
        (200 with ``duplicate: true`` when the frame's rows are already
        journaled), and mark-journaled after the rows land."""
        self._check_meta(app_name)
        key = (app_name, int(shard))
        ent = self.owned.get(key)
        if ent is not None and int(epoch) != ent["epoch"]:
            # maybe we are the zombie: re-check the ledger before ruling
            self._check_meta(app_name, force=True)
            ent = self.owned.get(key)
        if ent is None:
            self.stale_rejected += 1
            return 409, {"error": "not-owner", "app": app_name,
                         "shard": int(shard)}
        if int(epoch) != ent["epoch"]:
            self.stale_rejected += 1
            return 409, {"error": "stale-epoch", "app": app_name,
                         "shard": int(shard), "got": int(epoch),
                         "want": ent["epoch"]}
        if seq is not None and seq <= self.last_seq.get(key, -1):
            self.duplicate_frames += 1
            return 200, {"accepted": 0, "duplicate": True}
        rt = ent["runtime"]
        n = wire.deliver_frames(rt.get_input_handler(stream), body)
        if seq is not None:
            if rt.wal is not None:
                rt.wal.append_record("mark", stream, [], int(seq))
            self.last_seq[key] = int(seq)
        return 200, {"accepted": n}

    # ------------------------------------------------------------ plumbing

    def ping(self) -> dict:
        apps: dict = {}
        for (a, i) in list(self.owned):
            apps.setdefault(a, []).append(i)
        return {"ok": True,
                "apps": {a: sorted(s) for a, s in apps.items()}}

    def state(self, app_name: str) -> dict:
        with self._lock:
            return {
                "app": app_name,
                "owned": {str(i): {"epoch": ent["epoch"],
                                   "last_seq": self.last_seq.get((a, i), -1)}
                          for (a, i), ent in self.owned.items()
                          if a == app_name},
                "stale_rejected": self.stale_rejected,
                "fenced_shards": self.fenced_shards,
                "fenced_deploys": self.fenced_deploys,
                "duplicate_frames": self.duplicate_frames,
            }

    def outputs(self, app_name: str,
                shard: Optional[int] = None) -> dict:
        out = {}
        for (a, i), rows in self.captured.items():
            if a != app_name or (shard is not None and i != int(shard)):
                continue
            out[str(i)] = list(rows)
        return {"app": app_name, "outputs": out}

    def drain(self, app_name: str) -> dict:
        drained = []
        for (a, i), ent in list(self.owned.items()):
            if a != app_name:
                continue
            ent["runtime"].flush()
            ent["runtime"].drain()
            drained.append(i)
        return {"app": app_name, "drained": sorted(drained)}


# ========================================================================= #
# router side
# ========================================================================= #


class _HostState:
    def __init__(self, url: str) -> None:
        self.url = url.rstrip("/")
        self.up = True
        self.confirmed_dead = False
        self.misses = 0
        self.first_miss_t: Optional[float] = None


class _RoutingHandler:
    """Input-handler duck type over the front tier: rows are encoded into
    one SXF1 frame and routed like any external frame — which is what
    makes ``ErrorStore.replay`` (and the JSON ingestion path of the
    router's own HTTP server) work against the tier."""

    def __init__(self, front: "FrontTier", stream_id: str) -> None:
        self.front = front
        self.stream_id = stream_id

    def send(self, data, timestamp: Optional[int] = None) -> None:
        self.send_batch([tuple(data)], timestamps=timestamp)

    def send_batch(self, rows, timestamps=None) -> None:
        n = len(rows)
        if n == 0:
            return
        if timestamps is None or isinstance(timestamps, int):
            ts = timestamps if timestamps is not None \
                else int(time.time() * 1000)
            tss = np.full(n, ts, dtype=np.int64)
        else:
            tss = np.asarray([int(t) for t in timestamps], dtype=np.int64)
        plan = self.front._plan(self.stream_id)
        cols = {}
        for k, (name, _dt, code) in enumerate(plan):
            vals = [r[k] for r in rows]
            cols[name] = np.array(vals, dtype=object) if code == "s" \
                else np.asarray(vals)
        body = wire.encode_frame(plan, cols, n, tss)
        self.front.deliver_frames(self.stream_id, body)


class FrontTier:
    """The multi-host router. See the module docstring for the protocol;
    the public surface is deliberately runtime-shaped (`app`,
    `get_input_handler`, `statistics_report`, `conservation_report`,
    `flush`) so the flight recorder, the error store, and the service
    idioms all compose with it."""

    def __init__(self, app_text: str, hosts, *, wal_dir: str,
                 token: Optional[str] = None,
                 heartbeat_interval_s: float = 0.5,
                 miss_threshold: int = 3,
                 request_timeout_s: float = 5.0,
                 max_retries: int = 2,
                 retry_initial_s: float = 0.05,
                 retry_max_s: float = 0.4,
                 capture=(), runtime_kwargs: Optional[dict] = None,
                 auto_failover: bool = True,
                 error_store=None,
                 bundle_dir: Optional[str] = None,
                 recorder_cooldown_s: Optional[float] = None,
                 recorder_min_interval_s: Optional[float] = None) -> None:
        from .. import compiler
        self.app_text = app_text
        self.app = compiler.parse(app_text)
        self.name = self.app.name
        cfg = shard_config(self.app, strict=True)
        if cfg is None:
            raise SiddhiAppCreationError(
                f"app {self.name!r} has no @app:shards annotation; the "
                "front tier routes by partition key (docs/SHARDING.md)")
        check_shardable(self.app, cfg.key)
        self.key = cfg.key
        self.n_shards = cfg.n
        if not hosts:
            raise SiddhiAppCreationError("front tier needs >= 1 host URL")
        self.hosts = [_HostState(u) for u in hosts]
        self.wal_dir = wal_dir
        os.makedirs(wal_dir, exist_ok=True)
        self.token = token
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.miss_threshold = int(miss_threshold)
        self.request_timeout_s = float(request_timeout_s)
        self.max_retries = int(max_retries)
        self.retry_initial_s = float(retry_initial_s)
        self.retry_max_s = float(retry_max_s)
        self.capture = list(capture or ())
        self.runtime_kwargs = dict(runtime_kwargs or {})
        self.auto_failover = auto_failover

        self._state = named_rlock("front_tier.state")
        self._shard_locks = [named_lock("front_tier.shard_dispatch")
                             for _ in range(self.n_shards)]
        self._plans: dict = {}

        meta = _read_meta_file(_meta_path(wal_dir, self.name))
        assignment = None
        self.epoch = 0
        self.shard_epochs = [0] * self.n_shards
        owners = [i % len(self.hosts) for i in range(self.n_shards)]
        if meta is not None:
            self._validate_meta(meta)
            assignment = meta.get("assignment")
            self.epoch = int(meta.get("epoch", 0))
            eps = meta.get("shard_epochs")
            if isinstance(eps, list) and len(eps) == self.n_shards:
                self.shard_epochs = [int(e) for e in eps]
            hosts_m = meta.get("shard_hosts")
            if isinstance(hosts_m, list) and len(hosts_m) == self.n_shards:
                by_url = {h.url: k for k, h in enumerate(self.hosts)}
                owners = [by_url.get(u) if u is not None else None
                          for u in hosts_m]
        #: shard -> host index (None = no live owner; frames divert)
        self.shard_owner: list = owners
        self.router = ShardRouter(self.key, self.n_shards,
                                  n_slots=_n_slots(),
                                  assignment=assignment)

        # durable per-shard spool (lazy) + in-memory pending accounting
        self._spools: dict = {}
        self._spool_frames = [0] * self.n_shards
        self._spool_rows = [0] * self.n_shards
        base = (int(time.time() * 1000) & 0x7FFFFFFFF) << 20
        self._seq = [base] * self.n_shards

        # counters (conservation identity + observability)
        self.frames_in = 0
        self.sent_rows = 0
        self.delivered_rows = 0
        self.replayed_rows = 0
        self.diverted_rows = 0
        self.spooled_frames_total = 0
        self.spooled_rows_total = 0
        self.deduped_frames = 0
        self.stale_epoch_rejections = 0
        self.reroutes = 0
        self.forward_errors = 0
        self.failovers_total = 0
        self.unowned_diverts = 0
        #: per-failover timing samples (bench's advisory failover leg)
        self.failover_timings: list = []
        self._load_spools()

        if error_store is None:
            from ..state.error_store import InMemoryErrorStore
            error_store = InMemoryErrorStore()
        self.error_store = error_store

        from ..telemetry.recorder import FlightRecorder
        self.recorder = FlightRecorder(
            self, bundle_dir=bundle_dir, cooldown_s=recorder_cooldown_s,
            min_interval_s=recorder_min_interval_s)

        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._started = False

    # ------------------------------------------------------------ metadata

    def _validate_meta(self, meta: dict) -> None:
        if meta.get("n_shards") != self.n_shards or \
                meta.get("n_slots") != _n_slots() or \
                meta.get("key") != self.key:
            raise SiddhiAppCreationError(
                f"shard meta for {self.name!r} was written for "
                f"n={meta.get('n_shards')} key={meta.get('key')!r} "
                f"slots={meta.get('n_slots')}; the app now asks for "
                f"n={self.n_shards} key={self.key!r} slots={_n_slots()}")

    def _write_meta(self) -> None:
        """Commit the routing view durably — same atomic tmp+fsync+replace
        discipline as ShardPlane._write_meta, extended with the per-shard
        epoch and owner-host columns the fence protocol needs. THE commit
        point of a takeover."""
        path = _meta_path(self.wal_dir, self.name)
        tmp = path + ".tmp"
        with self._state:
            doc = {"epoch": self.epoch, "n_shards": self.n_shards,
                   "n_slots": self.router.n_slots, "key": self.key,
                   "assignment": [int(s) for s in self.router.assignment],
                   "shard_epochs": list(self.shard_epochs),
                   "shard_hosts": [
                       self.hosts[o].url if o is not None else None
                       for o in self.shard_owner]}
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _refresh_view(self) -> bool:
        """Reload the durable meta (another router instance may have
        committed a newer epoch — the stale-router path of the fence
        protocol). Returns True when the view changed."""
        meta = _read_meta_file(_meta_path(self.wal_dir, self.name))
        if meta is None:
            return False
        eps = meta.get("shard_epochs") or []
        if int(meta.get("epoch", 0)) <= self.epoch and \
                [int(e) for e in eps] == self.shard_epochs:
            return False
        self._validate_meta(meta)
        by_url = {h.url: k for k, h in enumerate(self.hosts)}
        with self._state:
            self.epoch = int(meta.get("epoch", 0))
            if isinstance(eps, list) and len(eps) == self.n_shards:
                self.shard_epochs = [int(e) for e in eps]
            hosts_m = meta.get("shard_hosts")
            if isinstance(hosts_m, list) and len(hosts_m) == self.n_shards:
                self.shard_owner = [
                    by_url.get(u) if u is not None else None
                    for u in hosts_m]
            asg = meta.get("assignment")
            if asg is not None:
                self.router.republish(asg)
        log.warning("front tier %s: refreshed routing view to epoch %d "
                    "from shardmeta", self.name, self.epoch)
        return True

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Commit the initial view, push the app to every worker host,
        start the heartbeat detector."""
        self._write_meta()
        for k, host in enumerate(self.hosts):
            shards = [i for i, o in enumerate(self.shard_owner) if o == k]
            if not shards:
                continue
            status, body = self._post_json(host.url, "/shard-host/apps", {
                "app": self.app_text, "shards": shards,
                "wal_dir": self.wal_dir,
                "shard_epochs": {str(i): self.shard_epochs[i]
                                 for i in shards},
                "capture": self.capture,
                "runtime_kwargs": self.runtime_kwargs})
            if status != 200 or body.get("fenced"):
                raise SiddhiError(
                    f"front tier bring-up: deploy to {host.url} failed "
                    f"({status}): {body}")
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name=f"front-tier-hb-{self.name}",
            daemon=True)
        self._hb_thread.start()
        self._started = True

    def shutdown(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        self.recorder.close()
        for wal in self._spools.values():
            wal.close()
        self._started = False

    def flush(self, now=None) -> None:  # runtime duck-typing (error replay)
        pass

    # ------------------------------------------------------------- HTTP io

    def _post_json(self, base: str, path: str, obj: dict,
                   timeout: Optional[float] = None) -> tuple[int, dict]:
        return self._post(base + path, json.dumps(obj).encode(),
                          ctype="application/json", timeout=timeout)

    def _post(self, url: str, body: bytes, *, ctype: str,
              timeout: Optional[float] = None) -> tuple[int, dict]:
        """One POST exchange (instance method so chaos tests can wrap it —
        e.g. raise AFTER the worker processed the request to simulate a
        lost ack)."""
        note_blocking("front_tier.forward",
                      allow=("front_tier.shard_dispatch",
                             "front_tier.state"))
        return _http("POST", url, body=body, ctype=ctype, token=self.token,
                     timeout=timeout if timeout is not None
                     else self.request_timeout_s)

    def _get_json(self, base: str, path: str,
                  timeout: Optional[float] = None) -> tuple[int, dict]:
        note_blocking("front_tier.forward",
                      allow=("front_tier.shard_dispatch",
                             "front_tier.state"))
        return _http("GET", base + path, token=self.token,
                     timeout=timeout if timeout is not None
                     else self.request_timeout_s)

    # ------------------------------------------------------------ ingestion

    def _plan(self, stream: str):
        plan = self._plans.get(stream)
        if plan is None:
            defn = self.app.stream_definitions.get(stream)
            if defn is None:
                raise KeyError(f"stream {stream!r} is not defined on "
                               f"{self.name!r}")
            names = [a.name for a in defn.attributes]
            if self.key not in names:
                raise SiddhiAppCreationError(
                    f"stream {stream!r} has no partition-key attribute "
                    f"{self.key!r}; it cannot be routed")
            plan = self._plans[stream] = wire.schema_plan(defn)
        return plan

    def get_input_handler(self, stream_id: str) -> _RoutingHandler:
        self._plan(stream_id)  # validate early
        return _RoutingHandler(self, stream_id)

    def deliver_frames(self, stream: str, body) -> int:
        """SXF1 ingress: decode once, split per shard on ORIGINAL values
        (``ShardRouter.split_columns`` — compacted dictionaries), re-encode
        each shard's subset as its own frame, dispatch."""
        plan = self._plan(stream)
        total = 0
        for payload in wire.iter_frames(body):
            ts, cols, n = wire.decode_frame(payload, plan)
            if n == 0:
                continue
            if ts is None:
                ts = np.full(n, int(time.time() * 1000), dtype=np.int64)
            self.frames_in += 1
            with self._state:
                self.sent_rows += n
            split = self.router.split_columns(cols, ts, n)
            for shard, (ts_sub, cols_sub, cnt) in split.items():
                plain = {k: (wire.materialize_strings(v)
                             if isinstance(v, tuple) else v)
                         for k, v in cols_sub.items()}
                frame = wire.encode_frame(plan, plain, cnt, ts_sub)
                self._dispatch(shard, stream, frame, cnt)
            total += n
        return total

    # ------------------------------------------------------------- dispatch

    def _next_seq(self, shard: int) -> int:
        with self._state:
            self._seq[shard] += 1
            return self._seq[shard]

    def _dispatch(self, shard: int, stream: str, frame: bytes,
                  rows: int) -> None:
        with self._shard_locks[shard]:
            self._dispatch_locked(shard, stream, frame, rows, depth=0)

    def _dispatch_locked(self, shard: int, stream: str, frame: bytes,
                         rows: int, depth: int) -> None:
        with self._state:
            owner = self.shard_owner[shard]
            epoch = self.shard_epochs[shard]
            spooling = self._spool_frames[shard] > 0
            host = self.hosts[owner] if owner is not None else None
        if owner is None:
            self._divert(shard, stream, frame, rows)
            return
        seq = self._next_seq(shard)
        if spooling or not host.up:
            # spool-first: arrival order through the spool is the
            # ordering contract — a live frame must not overtake one
            # waiting for replay
            self._spool(shard, stream, frame, rows, seq)
            return
        outcome, dup = self._send(host, shard, epoch, seq, stream, frame)
        if outcome == "ok":
            with self._state:
                self.delivered_rows += rows
                if dup:
                    self.deduped_frames += 1
            return
        if outcome == "stale" and depth == 0:
            with self._state:
                self.stale_epoch_rejections += 1
            self._refresh_view()
            with self._state:
                self.reroutes += 1
            self._dispatch_locked(shard, stream, frame, rows, depth=1)
            return
        # transport failure (or a second stale bounce): spool + let the
        # detector decide about the host
        self._note_forward_failure(host)
        self._spool(shard, stream, frame, rows, seq)

    def _send(self, host: _HostState, shard: int, epoch: int, seq: int,
              stream: str, frame: bytes) -> tuple[str, bool]:
        """Bounded exponential-backoff forward of ONE frame.
        Returns ("ok", duplicate) | ("stale", False) | ("fail", False)."""
        url = (f"{host.url}/shard-host/frames/{quote(self.name)}/"
               f"{quote(stream)}?shard={shard}&epoch={epoch}&seq={seq}")
        delay = self.retry_initial_s
        for attempt in range(self.max_retries + 1):
            try:
                status, body = self._post(
                    url, frame, ctype="application/x-siddhi-frames")
            except OSError:
                with self._state:
                    self.forward_errors += 1
                if attempt < self.max_retries:
                    time.sleep(delay)
                    delay = min(delay * 2, self.retry_max_s)
                continue
            if status == 200:
                return "ok", bool(body.get("duplicate"))
            if status == 409:
                return "stale", False
            with self._state:
                self.forward_errors += 1
            if attempt < self.max_retries:
                time.sleep(delay)
                delay = min(delay * 2, self.retry_max_s)
        return "fail", False

    def _note_forward_failure(self, host: _HostState) -> None:
        with self._state:
            host.misses += 1
            if host.first_miss_t is None:
                host.first_miss_t = time.monotonic()

    # --------------------------------------------------------------- spool

    def _spool_wal(self, shard: int) -> WriteAheadLog:
        wal = self._spools.get(shard)
        if wal is None:
            wal = self._spools[shard] = WriteAheadLog(
                os.path.join(self.wal_dir, SPOOL_DIR),
                shard_app_name(self.name, shard))
        return wal

    def _load_spools(self) -> None:
        """Adopt a previous incarnation's pending spool (a router restart
        must not orphan spooled frames). Adopted frames count as sent AND
        spooled in THIS incarnation so the conservation identity balances
        from the first report; new seqs start above the highest spooled
        one, keeping the worker-side dedupe monotone across restarts."""
        base = os.path.join(self.wal_dir, SPOOL_DIR)
        if not os.path.isdir(base):
            return
        for shard in range(self.n_shards):
            d = os.path.join(base, shard_app_name(self.name, shard))
            if not os.path.isdir(d):
                continue
            for kind, _sid, _tss, data in read_records(d):
                if kind != "frame":
                    continue
                seq, _stream, rows, _fb = data
                self._spool_frames[shard] += 1
                self._spool_rows[shard] += int(rows)
                self.sent_rows += int(rows)
                self.spooled_frames_total += 1
                self.spooled_rows_total += int(rows)
                self._seq[shard] = max(self._seq[shard], int(seq))

    def _spool(self, shard: int, stream: str, frame: bytes, rows: int,
               seq: int) -> None:
        wal = self._spool_wal(shard)
        wal.append_record("frame", stream, [],
                          (int(seq), stream, int(rows), bytes(frame)))
        with self._state:
            self._spool_frames[shard] += 1
            self._spool_rows[shard] += rows
            self.spooled_frames_total += 1
            self.spooled_rows_total += rows

    def _replay_spool_locked(self, shard: int,
                             min_seq: Optional[int] = None) -> bool:
        """Replay the shard's spool — in order, original seqs, epochs
        re-stamped to the CURRENT shard epoch — to the current owner.
        Caller holds the shard's dispatch lock. Frames with seq <=
        `min_seq` (the adoption's last journaled mark) are already in the
        adopted journal: counted as replayed without a resend. Returns
        True when the spool fully drained."""
        if self._spool_frames[shard] == 0:
            return True
        with self._state:
            owner = self.shard_owner[shard]
            epoch = self.shard_epochs[shard]
        if owner is None:
            return False
        host = self.hosts[owner]
        wal = self._spool_wal(shard)
        recs = [r for r in wal.records() if r[0] == "frame"]
        sent = 0
        failed_at: Optional[int] = None
        for k, (_kind, _sid, _tss, data) in enumerate(recs):
            seq, stream, rows, fb = data
            if min_seq is not None and int(seq) <= min_seq:
                with self._state:
                    self.replayed_rows += int(rows)
                    self.deduped_frames += 1
                sent += 1
                continue
            outcome, dup = self._send(host, shard, epoch, int(seq),
                                      stream, bytes(fb))
            if outcome == "stale":
                self._refresh_view()
                with self._state:
                    self.stale_epoch_rejections += 1
                    owner2 = self.shard_owner[shard]
                    epoch2 = self.shard_epochs[shard]
                if owner2 is None:
                    failed_at = k
                    break
                host = self.hosts[owner2]
                epoch = epoch2
                outcome, dup = self._send(host, shard, epoch, int(seq),
                                          stream, bytes(fb))
            if outcome != "ok":
                failed_at = k
                break
            with self._state:
                self.replayed_rows += int(rows)
                if dup:
                    self.deduped_frames += 1
            sent += 1
        remainder = recs[sent if failed_at is None else failed_at:]
        wal.rotate(f"e{epoch}")
        with self._state:
            self._spool_frames[shard] = 0
            self._spool_rows[shard] = 0
        for _kind, _sid, _tss, data in remainder:
            seq, stream, rows, fb = data
            wal.append_record("frame", stream, [],
                              (int(seq), stream, int(rows), bytes(fb)))
            with self._state:
                self._spool_frames[shard] += 1
                self._spool_rows[shard] += int(rows)
        if failed_at is not None:
            self._note_forward_failure(host)
            return False
        return True

    # -------------------------------------------------------------- divert

    def _divert(self, shard: int, stream: str, frame: bytes,
                rows: int) -> None:
        """No live owner: decode the sub-frame back to rows and park them
        in the replayable ErrorStore (kind="unowned") — degradation, not
        loss; `replay_errors` re-routes them once an owner exists."""
        plan = self._plan(stream)
        for payload in wire.iter_frames(frame):
            ts, cols, n = wire.decode_frame(payload, plan)
            plain = {k: (wire.materialize_strings(v)
                         if isinstance(v, tuple) else v)
                     for k, v in cols.items()}
            names = [p[0] for p in plan]
            if ts is None:
                ts = np.full(n, int(time.time() * 1000), dtype=np.int64)
            events = [(int(ts[r]),
                       tuple(_py(plain[nm][r]) for nm in names))
                      for r in range(n)]
            self.error_store.save(
                self.name, stream, events,
                cause=f"no live owner for shard {shard}", kind="unowned")
        with self._state:
            self.diverted_rows += rows
            self.unowned_diverts += 1

    # ----------------------------------------------------------- heartbeat

    def _ping(self, host: _HostState) -> bool:
        try:
            status, body = self._get_json(
                host.url, "/shard-host/ping",
                timeout=max(0.25, min(self.heartbeat_interval_s * 2, 2.0)))
        except OSError:
            return False
        return status == 200 and bool(body.get("ok"))

    def _hb_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval_s):
            for k, host in enumerate(self.hosts):
                try:
                    self._hb_tick(k, host)
                except Exception:  # noqa: BLE001 — detector must survive
                    log.exception("front tier %s: heartbeat tick failed "
                                  "for %s", self.name, host.url)

    def _hb_tick(self, k: int, host: _HostState) -> None:
        if self._ping(host):
            was_dead = host.confirmed_dead
            with self._state:
                host.misses = 0
                host.first_miss_t = None
                host.up = True
                host.confirmed_dead = False
            if was_dead:
                # a zombie (or a healed partition): fence it to the
                # committed epochs before it can accept anything stale
                self._post_json(host.url, "/shard-host/fence", {
                    "app": self.name,
                    "shard_epochs": {str(i): e for i, e in
                                     enumerate(self.shard_epochs)}})
                log.warning("front tier %s: host %s came back — fenced "
                            "to committed epochs", self.name, host.url)
            # recovery replay: spooled frames whose owner is healthy again
            for shard in range(self.n_shards):
                if self.shard_owner[shard] == k and \
                        self._spool_frames[shard] > 0:
                    with self._shard_locks[shard]:
                        self._replay_spool_locked(shard)
            return
        with self._state:
            host.misses += 1
            if host.first_miss_t is None:
                host.first_miss_t = time.monotonic()
            newly_dead = (not host.confirmed_dead
                          and host.misses >= self.miss_threshold)
            if newly_dead:
                host.up = False
                host.confirmed_dead = True
        if newly_dead:
            detect_ms = (time.monotonic() - (host.first_miss_t or
                                             time.monotonic())) * 1e3
            log.warning("front tier %s: host %s confirmed dead "
                        "(%d missed heartbeats)", self.name, host.url,
                        host.misses)
            # bundle #1: the pre-takeover state (dead owner, spool depth)
            self.recorder.trigger(
                "shard_failover",
                reason=f"host {host.url} confirmed dead after "
                       f"{host.misses} missed heartbeats")
            if self.auto_failover:
                self.failover(k, detect_ms=detect_ms)

    # ------------------------------------------------------------- takeover

    def failover(self, dead_idx: int,
                 detect_ms: Optional[float] = None) -> dict:
        """Shard takeover of every shard owned by host `dead_idx`: bump
        the shards' epochs, COMMIT the meta (the fence point — a zombie
        deploy/adopt after this instant is refused), have survivors adopt
        the shards by WAL replay, drain the spool through the adoption's
        last_seq, and fence every other host. With no survivors the
        shards become unowned (divert-to-ErrorStore degradation)."""
        t0 = time.monotonic()
        with self._state:
            dead = self.hosts[dead_idx]
            dead.up = False
            dead.confirmed_dead = True
            dead_shards = [i for i, o in enumerate(self.shard_owner)
                           if o == dead_idx]
            survivors = [k for k, h in enumerate(self.hosts)
                         if k != dead_idx and h.up]
        if not dead_shards:
            return {"failover": False, "reason": "host owned no shards"}
        if not survivors:
            with self._state:
                for i in dead_shards:
                    self.shard_owner[i] = None
                self.epoch += 1
                for i in dead_shards:
                    self.shard_epochs[i] += 1
            self._write_meta()
            self.failovers_total += 1
            log.error("front tier %s: host %s died with NO survivors — "
                      "shards %s unowned; frames divert to the error "
                      "store", self.name, dead.url, dead_shards)
            return {"failover": True, "adopted": [],
                    "unowned": dead_shards}
        # balance adoptions across survivors by current ownership count
        with self._state:
            load = {k: sum(1 for o in self.shard_owner if o == k)
                    for k in survivors}
            plan = {}
            for i in dead_shards:
                k = min(survivors, key=lambda s: load[s])
                plan[i] = k
                load[k] += 1
            self.epoch += 1
            for i in dead_shards:
                self.shard_epochs[i] += 1
                self.shard_owner[i] = plan[i]
        # COMMIT — after this rename a zombie is fenced by epoch
        self._write_meta()
        # bundle #2: the takeover commit (standard per-kind cooldown may
        # coalesce it with the detection bundle)
        self.recorder.trigger(
            "shard_failover",
            reason=f"takeover committed: shards {dead_shards} from "
                   f"{dead.url} at epoch {self.epoch}")
        adopted, lost = [], []
        for i in dead_shards:
            k = plan[i]
            with self._shard_locks[i]:
                try:
                    status, body = self._post_json(
                        self.hosts[k].url, "/shard-host/adopt", {
                            "app": self.name, "shard": i,
                            "epoch": self.shard_epochs[i],
                            "wal_dir": self.wal_dir,
                            "capture": self.capture,
                            "runtime_kwargs": self.runtime_kwargs},
                        timeout=max(self.request_timeout_s, 60.0))
                except OSError:
                    status, body = 0, {}
                if status != 200:
                    log.error("front tier %s: adoption of shard %d by %s "
                              "failed (%s): %s — shard is unowned",
                              self.name, i, self.hosts[k].url, status,
                              body)
                    with self._state:
                        self.shard_owner[i] = None
                    lost.append(i)
                    continue
                adopted.append(i)
                last_seq = int(body.get("last_seq", -1))
                self._replay_spool_locked(
                    i, min_seq=last_seq if last_seq >= 0 else None)
        if lost:
            self._write_meta()  # record the unowned outcome durably
        # fence everything else (best-effort, incl. the dead host)
        eps = {str(i): e for i, e in enumerate(self.shard_epochs)}
        for k, h in enumerate(self.hosts):
            try:
                self._post_json(h.url, "/shard-host/fence",
                                {"app": self.name, "shard_epochs": eps},
                                timeout=2.0)
            except OSError:
                pass
        with self._state:
            self.failovers_total += 1
        takeover_ms = (time.monotonic() - t0) * 1e3
        timing = {"detect_ms": detect_ms, "takeover_ms": takeover_ms,
                  "shards": len(dead_shards)}
        self.failover_timings.append(timing)
        log.warning("front tier %s: takeover complete — %d shard(s) "
                    "adopted in %.1f ms (epoch %d)", self.name,
                    len(adopted), takeover_ms, self.epoch)
        return {"failover": True, "adopted": adopted, "unowned": lost,
                "epoch": self.epoch, "timing": timing}

    # ---------------------------------------------------------------- drain

    def drain(self, timeout_s: float = 60.0) -> None:
        """Replay every drainable spool, then drain every live worker —
        after this, the conservation identity must balance exactly."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            pending = [i for i in range(self.n_shards)
                       if self._spool_frames[i] > 0
                       and self.shard_owner[i] is not None
                       and self.hosts[self.shard_owner[i]].up]
            if not pending:
                break
            for i in pending:
                with self._shard_locks[i]:
                    self._replay_spool_locked(i)
            time.sleep(0.02)
        for h in self.hosts:
            if not h.up:
                continue
            try:
                self._post_json(h.url, "/shard-host/drain",
                                {"app": self.name},
                                timeout=max(self.request_timeout_s, 60.0))
            except OSError:
                pass

    # ---------------------------------------------------------- reporting

    def conservation_report(self) -> dict:
        """sent == delivered + spool_replayed + diverted (+ pending)."""
        with self._state:
            pending = sum(self._spool_rows)
            sent = self.sent_rows
            delivered = self.delivered_rows
            replayed = self.replayed_rows
            diverted = self.diverted_rows
        return {
            "sent": sent, "delivered": delivered,
            "spool_replayed": replayed, "diverted": diverted,
            "spooled_pending": pending,
            "deduped_frames": self.deduped_frames,
            "conserved":
                sent == delivered + replayed + diverted + pending,
        }

    def _degraded_slots(self) -> tuple[list, list]:
        """(unowned_slots, dead_owner_slots) by the two-level map."""
        unowned, dead = [], []
        with self._state:
            assignment = self.router.assignment.copy()
            owner = list(self.shard_owner)
            up = [h.up for h in self.hosts]
        for slot in range(len(assignment)):
            s = int(assignment[slot])
            o = owner[s]
            if o is None:
                unowned.append(slot)
            elif not up[o]:
                dead.append(slot)
        return unowned, dead

    def ready(self) -> tuple[int, dict]:
        """(http_status, body): 200 only with every shard owned by a live
        host and no spooled backlog — load balancers drain a degraded tier
        the same way /ready drains a degraded app. A dead host that owns
        nothing (post-takeover) does NOT hold readiness hostage: the tier
        is serving; the loss shows in metrics and the doctor finding."""
        unowned, dead = self._degraded_slots()
        with self._state:
            hosts = {h.url: {"up": h.up,
                             "confirmed_dead": h.confirmed_dead}
                     for h in self.hosts}
            pending = sum(self._spool_frames)
        ok = not unowned and not dead and pending == 0
        return (200 if ok else 503), {
            "ready": ok, "hosts": hosts, "unowned_slots": unowned,
            "dead_owner_slots": dead, "spooled_frames": pending}

    def statistics_report(self) -> dict:
        unowned, dead = self._degraded_slots()
        with self._state:
            hosts = {}
            for k, h in enumerate(self.hosts):
                hosts[h.url] = {
                    "up": h.up, "misses": h.misses,
                    "confirmed_dead": h.confirmed_dead,
                    "shards": [i for i, o in enumerate(self.shard_owner)
                               if o == k]}
            spool_per_shard = {
                f"s{i}": {"frames": self._spool_frames[i],
                          "rows": self._spool_rows[i]}
                for i in range(self.n_shards) if self._spool_frames[i]}
            front = {
                "n_shards": self.n_shards,
                "key": self.key,
                "epoch": self.epoch,
                "shard_epochs": list(self.shard_epochs),
                "shard_hosts": [
                    self.hosts[o].url if o is not None else None
                    for o in self.shard_owner],
                "hosts": hosts,
                "unowned_slots": unowned,
                "dead_owner_slots": dead,
                "spool": {"frames": sum(self._spool_frames),
                          "rows": sum(self._spool_rows),
                          "per_shard": spool_per_shard},
                "frames_in": self.frames_in,
                "failovers_total": self.failovers_total,
                "stale_epoch_rejections": self.stale_epoch_rejections,
                "reroutes": self.reroutes,
                "forward_errors": self.forward_errors,
                "spooled_frames_total": self.spooled_frames_total,
                "spooled_rows_total": self.spooled_rows_total,
                "deduped_frames": self.deduped_frames,
                "unowned_diverts": self.unowned_diverts,
            }
        return {
            "app": self.name,
            "front_tier": front,
            "conservation": self.conservation_report(),
            "skew": self.router.skew_report(),
            "recorder": self.recorder.report(),
        }

    def metrics_text(self) -> str:
        from ..telemetry import prometheus
        return prometheus.render_front_tier(self)

    # ---------------------------------------------------------------- HTTP

    def make_server(self, port: int, host: str = "127.0.0.1"):
        """The tier's own serving surface: the service.py stream-ingestion
        contract (SXF1 or JSON) plus the probe endpoints, minus the
        deployment surface (the tier serves exactly one app)."""
        import hmac
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        front = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                if front.token is None:
                    return True
                got = self.headers.get("Authorization", "")
                want = f"Bearer {front.token}"
                if hmac.compare_digest(got.encode(), want.encode()):
                    return True
                self._reply(401, {"error": "missing or bad bearer token"})
                return False

            def do_GET(self):
                note_blocking("http.handle")
                path = self.path.split("?", 1)[0].strip("/")
                if path == "health":
                    self._reply(200, {"status": "up", "app": front.name})
                elif path == "ready":
                    code, body = front.ready()
                    self._reply(code, body)
                elif path == "metrics":
                    from ..telemetry import prometheus
                    body = front.metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     prometheus.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "statistics":
                    if self._authorized():
                        self._reply(200, front.statistics_report())
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                note_blocking("http.handle")
                if not self._authorized():
                    return
                path = self.path.split("?", 1)[0].strip("/")
                parts = path.split("/")
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                try:
                    if len(parts) == 4 and parts[0] == "siddhi-apps" \
                            and parts[2] == "streams":
                        if parts[1] != front.name:
                            self._reply(404, {"error": "unknown app"})
                            return
                        ctype = (self.headers.get("Content-Type") or "")
                        if ctype.split(";")[0].strip() == \
                                "application/x-siddhi-frames":
                            accepted = front.deliver_frames(parts[3], raw)
                        else:
                            data = json.loads(raw.decode())
                            h = front.get_input_handler(parts[3])
                            events = data.get("events", [])
                            h.send_batch([tuple(r) for r in events])
                            accepted = len(events)
                        self._reply(200, {"accepted": accepted})
                    elif parts == ["drain"]:
                        front.drain()
                        self._reply(200, front.conservation_report())
                    else:
                        self._reply(404, {"error": "not found"})
                except KeyError as e:
                    self._reply(404, {"error": f"unknown: {e}"})
                except (ValueError, SiddhiError) as e:
                    self._reply(400, {"error": str(e)})

        return ThreadingHTTPServer((host, port), Handler)
