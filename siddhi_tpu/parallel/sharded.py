"""Sharded (multi-chip) query execution over a `jax.sharding.Mesh`.

Reference counterpart: `partition with (attr of Stream)` clones query runtimes
per key and routes events by key (PartitionStreamReceiver.java:82-141,
PartitionRuntimeImpl.java:75) — thread-level data parallelism inside one JVM.

The TPU-native redesign: the partition key space is **hashed onto a mesh
axis**. Every device holds a shard of the query state (group tables, window
rings); each micro-batch is broadcast to all devices and each device masks the
batch down to the lanes it owns (`hash(key) % n_shards == my_shard`). Because
filters/windows/selectors are all mask-based, shard-local execution is just the
ordinary single-chip step on a thinner mask — no per-key cloning, no routing
queues. Output lanes are disjoint across shards, so the merged output is an
`psum` over the mesh axis of zero-masked columns (one XLA collective riding
ICI, not host gather).

`ShardedQueryStep` below shards ONE query's state by key hash (each shard runs
the ordinary step on the lanes it owns — keys co-located on a shard share that
shard's state, matching unpartitioned GROUP BY semantics at scale).

`PartitionedQueryStep` is the `partition with (key of Stream)` runtime over a
mesh: state carries a leading KEY-SLOT axis (`[n_slots, ...]` pytree), sharded
over the mesh axis with `shard_map` and vmapped over the local slots — every
key gets its own fully isolated window/selector/limiter state, exactly the
reference's per-key runtime clones, but as one SPMD step (SURVEY §7 "a key
axis in state arrays"). Keys map to slots through a replicated device
KeyTable in first-appearance order.

This module is used by the driver's `dryrun_multichip`, by
`core/partition.py` when a mesh is configured, and by tests on a virtual
CPU mesh; the same code compiles unchanged for a real TPU slice.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
    _SHARD_KW = {"check_vma": False}
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
    _SHARD_KW = {"check_rep": False}
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.event import EventBatch
from ..ops.groupby import (DenseKeyTable, dense_key_lookup_or_insert,
                           hash_columns, init_dense_key_table)


def np_shard_of(key_cols, n_shards: int):
    """HOST-side (numpy) mirror of `shard_owned`'s key-hash ownership —
    per-host sharded ingestion routes rows to their owning shard BEFORE
    device_put, so the device mask is a no-op guard. Must stay bit-exact
    with ops/groupby.hash_columns."""
    import numpy as np
    with np.errstate(over="ignore"):
        h = np.uint64(0xCBF29CE484222325)
        h = np.broadcast_to(h, np.shape(key_cols[0])).copy()
        for c in key_cols:
            c = np.asarray(c)
            if c.dtype.kind == "f":
                bits = c.view(np.int32 if c.dtype == np.float32
                              else np.int64)
                x = bits.astype(np.int64).astype(np.uint64)
            else:
                x = c.astype(np.int64).astype(np.uint64)
            h = (h ^ x) * np.uint64(0x100000001B3)
            h = h ^ (h >> np.uint64(29))
        keys = h.astype(np.int64)
        return keys.astype(np.uint32) % np.uint32(n_shards)


def shard_owned(batch: EventBatch, key_cols, axis_name: str,
                n_shards: int) -> EventBatch:
    """Mask a replicated batch down to the lanes THIS shard owns by key-hash
    ownership. The single definition of shard assignment — queries
    (ShardedQueryStep) and distributed aggregations must agree on it."""
    my_shard = jax.lax.axis_index(axis_name)
    keys = hash_columns(key_cols)
    owned = (keys.astype(jnp.uint32) % n_shards) == my_shard.astype(jnp.uint32)
    return batch.where_valid(owned)


def _zero_masked(batch: EventBatch) -> EventBatch:
    """Zero every lane that is invalid so cross-shard psum merges cleanly."""
    v = batch.valid
    return EventBatch(
        ts=jnp.where(v, batch.ts, 0),
        cols={k: jnp.where(v, c, jnp.zeros((), c.dtype)) for k, c in batch.cols.items()},
        valid=v,
        types=jnp.where(v, batch.types, 0).astype(jnp.int8),
    )


def merge_shard_outputs(out: EventBatch, axis_name: str) -> EventBatch:
    """psum-merge disjoint per-shard outputs into the full output batch."""
    z = _zero_masked(out)
    return EventBatch(
        ts=jax.lax.psum(z.ts, axis_name),
        cols={k: jax.lax.psum(c, axis_name) for k, c in z.cols.items()},
        valid=jax.lax.psum(z.valid.astype(jnp.int8), axis_name) > 0,
        types=jax.lax.psum(z.types.astype(jnp.int32), axis_name).astype(jnp.int8),
    )


def stack_states(state, n_shards: int):
    """Replicate a single-shard init state into an [n_shards, ...] stacked
    pytree (each shard starts from the same empty state)."""
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (n_shards,) + jnp.shape(x)), state)


class ShardedQueryStep:
    """Wraps a pure per-query step `(state, batch, now) -> (state', out)` into
    an SPMD step over `mesh[axis_name]`, partitioned by a key-column hash.

    `key_attrs` are the partition-key column names in the input batch.
    """

    def __init__(self, step_fn: Callable, mesh: Mesh, axis_name: str,
                 key_attrs: Sequence[str]):
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_shards = mesh.shape[axis_name]
        self.key_attrs = tuple(key_attrs)

        n_shards = self.n_shards

        def shard_step(state, batch: EventBatch, now):
            # state arrives with a leading local axis of size 1 — unstack
            local = jax.tree_util.tree_map(lambda x: x[0], state)
            mine = shard_owned(batch, [batch.cols[a] for a in self.key_attrs],
                               axis_name, n_shards)
            local, out = step_fn(local, mine, now)
            merged = merge_shard_outputs(out, axis_name)
            restacked = jax.tree_util.tree_map(lambda x: x[None], local)
            return restacked, merged

        state_spec = P(axis_name)
        repl = P()
        self._step = jax.jit(
            shard_map(
                shard_step, mesh=mesh,
                in_specs=(state_spec, repl, repl),
                out_specs=(state_spec, repl),
                **_SHARD_KW,
            ),
            donate_argnums=(0,),
        )

    def init_state(self, single_state):
        """Place a replicated-from-empty stacked state onto the mesh."""
        stacked = stack_states(single_state, self.n_shards)
        sharding = NamedSharding(self.mesh, P(self.axis_name))
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), stacked)

    def __call__(self, state, batch: EventBatch, now):
        return self._step(state, batch, now)


class PartitionedQueryStep:
    """`partition with (key of Stream)` over a mesh: a key-slot axis in state.

    Wraps a pure per-query step `(state, batch, now) -> (state', out)` so that
    `n_slots` independent copies of its state live stacked on a leading axis,
    sharded over `mesh[axis_name]`; each step vmaps the query over the local
    slots with per-slot lane masks. A lane belongs to exactly one slot (dense
    id from a replicated KeyTable, assigned in first-appearance order), so
    every partition key has fully isolated window/selector/limiter state —
    the reference's per-key QueryRuntime clones
    (PartitionStreamReceiver.java:82-141) as one SPMD step.

    An all-invalid batch acts as a timer heartbeat: every slot's step runs
    with `now`, so per-key time windows flush without a host loop over keys.

    The merged output is the per-slot outputs flattened to one
    `[n_slots * chunk_width]` batch, ordered by slot id (key first-appearance
    order) — the host loop it replaces orders by sorted key value, both are
    batched reorderings of the reference's arrival-order interleave.
    """

    def __init__(self, step_fn: Callable, mesh: Mesh, axis_name: str,
                 n_slots: int, key_fn: Callable[[EventBatch], jax.Array]):
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_shards = mesh.shape[axis_name]
        if n_slots % self.n_shards != 0:
            raise ValueError(
                f"partition capacity {n_slots} must be divisible by the mesh "
                f"axis size {self.n_shards}")
        self.n_slots = n_slots
        slots_local = n_slots // self.n_shards

        def shard_step(states, batch: EventBatch, slots, now):
            base = jax.lax.axis_index(axis_name).astype(jnp.int32) * slots_local

            def per_slot(state, j):
                owned = batch.valid & (slots == base + j)
                return step_fn(state, batch.where_valid(owned), now)

            return jax.vmap(per_slot)(
                states, jnp.arange(slots_local, dtype=jnp.int32))

        spec, repl = P(axis_name), P()
        sharded = shard_map(
            shard_step, mesh=mesh,
            in_specs=(spec, repl, repl, repl),
            out_specs=(spec, spec),
            **_SHARD_KW,
        )

        def full_step(states, key_table: DenseKeyTable, batch: EventBatch, now):
            keys = key_fn(batch)
            key_table, slots = dense_key_lookup_or_insert(
                key_table, keys, batch.valid)
            states, outs = sharded(states, batch, slots, now)
            # flatten [n_slots, C] per-slot outputs into one wide batch
            flat = jax.tree_util.tree_map(
                lambda x: x.reshape((-1,) + x.shape[2:]), outs)
            return states, key_table, flat

        self._step = jax.jit(full_step, donate_argnums=(0, 1))

    def init_state(self, single_state):
        """Stack the per-key template state onto the sharded slot axis."""
        stacked = stack_states(single_state, self.n_slots)
        sharding = NamedSharding(self.mesh, P(self.axis_name))
        return (
            jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), stacked),
            init_dense_key_table(self.n_slots),
        )

    def __call__(self, states, key_table, batch: EventBatch, now):
        return self._step(states, key_table, batch, now)
