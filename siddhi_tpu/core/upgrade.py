"""Blue-green app upgrade + deterministic WAL replay.

Reference analogue: the Java engine upgrades by stop → redeploy → restore,
accepting a downtime window and replaying from a durable transport (Kafka).
The TPU build is fed through InputHandlers and journals ingress in its own
WAL (state/wal.py), so the swap can be done live:

    upgrade_app(): diff the plan graphs (analysis/upgrade.py SL3xx rules),
    shadow-start v2 (built, processing-capable, no transports), pause v1's
    sources, drain v1, persist v1 and restore the revision into v2 with a
    per-element state mapping, hand the ingress journal over, replay its
    tail with original timestamps, re-point user callbacks, atomically
    redirect every v1 ingress junction to its v2 twin, swap the manager /
    REST routing entry, resume — and on ANY failure before the swap commits,
    undo everything and leave v1 exactly as it was.

Conservation invariant: every event accepted by the engine is processed by
EXACTLY ONE version. Pre-pause sends are drained through v1 and captured in
the handoff snapshot; the journaled suffix is replayed into v2 exactly once
(persist() rotates the journal inside the same critical section); post-swap
sends — including payloads buffered in paused sources — forward through the
junction redirect into v2 with their ORIGINAL (pre-interning) values, since
v1 and v2 own separate string tables.

    replay_wal(): drive a CANDIDATE app from recorded WAL segments on a
    virtual clock — sandboxed (no sources/sinks/stores), read-only on the
    journal, per-record flush for deterministic batch boundaries, playback
    timestamps so time windows fire on record time. Bit-identical output
    digest across runs of the same segments; `speed` paces the virtual
    clock against the wall clock via an injectable sleep (util/faults.py
    virtual-time idiom), default is as-fast-as-possible.

    shuffled_replay(): the @app:eventTime determinism oracle — replay one
    event set in event-time order, then in N seed-permuted arrival orders
    whose displacement stays inside allowed.lateness, and assert every
    run's per-stream output digest is bit-identical with zero late
    diversions (docs/EVENT_TIME.md; CLI: tools/shuffled_replay.py).
"""

from __future__ import annotations

import dataclasses as dc
import hashlib
import logging
import os
import pickle
import random
import signal
import time
from typing import Callable, Optional

from ..errors import SiddhiAppCreationError
from ..query_api import SiddhiApp

log = logging.getLogger("siddhi_tpu")


def _crash_point(name: str) -> None:
    """Fault-injection hook for the upgrade-under-chaos tests: SIGKILL the
    process at a named point when SIDDHI_UPGRADE_CRASH selects it. Points:
    after-pause | after-persist | after-cutover."""
    if os.environ.get("SIDDHI_UPGRADE_CRASH") == name:
        os.kill(os.getpid(), signal.SIGKILL)


def _detach_persist(app: SiddhiApp):
    """Split @app:persist off the app definition: the v2 runtime must NEVER
    open its own WriteAheadLog on the live journal directory (two append
    handles; resume-truncation of the live segment) — it inherits v1's
    journal object at cutover instead. Returns (app_without_persist_ann,
    interval_s_or_None)."""
    interval_s = None
    ann = app.annotation("app:persist")
    if ann is None:
        return app, interval_s
    from .partition import _parse_annotation_time
    iv = ann.element("interval") or ann.element()
    if iv:
        interval_s = _parse_annotation_time(iv) / 1000.0
    anns = tuple(a for a in (app.annotations or ())
                 if a.name.lower() != "app:persist")
    return dc.replace(app, annotations=anns), interval_s


def _migrate_callbacks(rt1, rt2) -> list:
    """Re-subscribe user stream/query callbacks from v1 onto the matching v2
    junctions/queries. Sink-owned callbacks (wiring's _SinkCallback, marked
    _is_sink) stay put — v2 built and connected its own sinks. Returns an
    undo list for rollback."""
    from .stream import BatchStreamCallback, StreamCallback
    undo: list = []

    def move_stream_cbs(j1, j2) -> None:
        for r in list(j1.receivers):
            if not isinstance(r, (StreamCallback, BatchStreamCallback)):
                continue  # engine-internal receivers (query runtimes, taps)
            if getattr(r, "_is_sink", False):
                continue
            j2.subscribe(r)  # re-points r._junction at j2
            undo.append(("stream", r, j1, j2))

    for sid, j1 in rt1.junctions.items():
        j2 = rt2.junctions.get(sid)
        if j2 is not None:
            move_stream_cbs(j1, j2)
        elif any(isinstance(r, (StreamCallback, BatchStreamCallback))
                 and not getattr(r, "_is_sink", False)
                 for r in j1.receivers):
            log.warning("upgrade: stream %r does not exist in the new app; "
                        "its callbacks are dropped with it", sid)
    for sid, f1 in rt1.fault_junctions.items():
        f2 = rt2.fault_junctions.get(sid)
        if f2 is not None:
            move_stream_cbs(f1, f2)
    for name, qr1 in rt1.query_runtimes.items():
        qr2 = rt2.query_runtimes.get(name)
        if qr2 is None:
            if qr1.callbacks:
                log.warning("upgrade: query %r does not exist in the new "
                            "app; its callbacks are dropped with it", name)
            continue
        for cb in qr1.callbacks:
            qr2.add_callback(cb)
            undo.append(("query", cb, qr1, qr2))
    return undo


def _undo_callbacks(undo: list) -> None:
    for kind, cb, old, new in reversed(undo):
        if kind == "stream":
            try:
                new.receivers.remove(cb)
            except ValueError:  # pragma: no cover
                pass
            cb._junction = old
        else:
            try:
                new.callbacks.remove(cb)
            except ValueError:  # pragma: no cover
                pass


def upgrade_app(manager, rt1, new_app: SiddhiApp, *,
                force: bool = False) -> dict:
    """Hot-swap running `rt1` to `new_app` (same app name). See the module
    docstring for the sequence; raises (with v1 fully restored) when the
    diff is incompatible, when a state-losing swap lacks force=True, or
    when any step before the swap commits fails."""
    from ..analysis.upgrade import diff_apps
    from .app_runtime import SiddhiAppRuntime

    diff = diff_apps(rt1.app, new_app)
    if diff.is_incompatible:
        raise SiddhiAppCreationError(
            f"cannot upgrade {rt1.app.name!r}: incompatible change(s):\n" +
            "\n".join(d.format() for d in diff.report.sorted()))
    if diff.classification == "state-migratable" and not force:
        raise SiddhiAppCreationError(
            f"upgrade of {rt1.app.name!r} is state-migratable (changed: "
            f"{sorted(diff.changed)}; removed: {sorted(diff.removed)}) — "
            "their state restarts empty/is dropped. Pass force=True to "
            "accept, or keep the element definitions identical.")

    build_app, new_interval_s = _detach_persist(new_app)
    lint_report = manager._lint_gate(build_app)
    ctx1 = rt1.ctx
    rt2 = SiddhiAppRuntime(
        build_app, manager.registry,
        batch_size=ctx1.batch_size, group_capacity=ctx1.group_capacity,
        error_store=ctx1.error_store, config_manager=ctx1.config_manager,
        mesh=ctx1.mesh, partition_capacity=ctx1.partition_capacity,
        async_callbacks=ctx1.async_callbacks,
        auto_flush_ms=rt1.auto_flush_ms or 0,
        wal_dir=None,
        persistence_interval_s=(new_interval_s
                                if new_interval_s is not None
                                else rt1.persistence_interval_s))
    rt2.persistence_store = rt1.persistence_store
    rt2.lint_report = lint_report
    # shadow: fully built and able to process; no transports, no revisions
    rt2.start(connect_sources=False, start_persist_scheduler=False)

    ingress1 = [rt1.junctions[sid] for sid in rt1.app.stream_definitions]
    paused: list = []
    undo_cbs: list = []
    wal_moved = False
    sources_moved = False
    new_sources = list(rt2.sources)  # v2's own (not-yet-connected) sources
    revision = None
    replayed = 0
    swapped = False
    t_pause = time.perf_counter()
    try:
        # 1. quiesce v1 ingress: pause transports (payloads buffer in the
        #    sources, bounded), stop async pipelines/feeders
        for j in ingress1:
            for s in j.attached_sources:
                s.pause()
                paused.append(s)
        _crash_point("after-pause")
        for j in ingress1:
            j.stop_async()

        with rt1.ctx.controller_lock:      # lock order: v1 -> v2, matching
            with rt2.ctx.controller_lock:  # the redirected send path
                # 2. drain everything already accepted through v1
                rt1.drain()

                # 3. state handoff
                elements = diff.restore_elements()
                wal = rt1.wal
                if rt1.persistence_store is not None:
                    # persist() snapshots + rotates the journal in ONE
                    # critical section (re-entrant lock), so the journal
                    # tail after this is exactly the not-yet-snapshotted
                    # suffix (normally empty: nothing can append here)
                    revision = rt1.persist()
                    _crash_point("after-persist")
                    blob = rt1.persistence_store.load(rt1.app.name, revision)
                else:
                    blob = rt1.snapshot()
                rt2.restore(blob, elements=elements)
                rt2._last_rev_ms = getattr(rt1, "_last_rev_ms", 0)

                # 4. journal handover (+ tail replay when a store rotated)
                if wal is not None:
                    rt1.wal = None
                    for j in ingress1:
                        j.wal = None
                    rt2.wal = wal
                    for sid in build_app.stream_definitions:
                        j2 = rt2.junctions.get(sid)
                        if j2 is not None:
                            j2.wal = wal
                    wal_moved = True
                    if rt1.persistence_store is not None:
                        # replayed sends re-journal via v2's junctions —
                        # the recover() idiom; with the store-backed rotate
                        # above this is normally zero events
                        replayed = wal.replay(rt2)
                    # without a store the snapshot carried the journal's
                    # whole span: replaying it into v2 would double-apply,
                    # so v2 adopts the journal as-is

                # 5. re-point user callbacks, then cut over
                undo_cbs = _migrate_callbacks(rt1, rt2)
                for j in ingress1:
                    j2 = rt2.junctions.get(j.definition.id)
                    if j2 is not None:
                        j.redirect_to(j2)
                # live transports carry over (their junction redirects);
                # they must survive rt1.shutdown and obey v2 backpressure
                moved = rt1.sources
                rt1.sources = []
                rt2.sources.extend(moved)
                sources_moved = True
                for j in ingress1:
                    j2 = rt2.junctions.get(j.definition.id)
                    if j2 is None:
                        continue
                    for s in j.attached_sources:
                        if s not in j2.attached_sources:
                            j2.attached_sources.append(s)
                manager.runtimes[build_app.name] = rt2
                swapped = True
        _crash_point("after-cutover")
    except BaseException:
        if swapped:  # post-commit failures must not yank v2 back out
            raise
        # ---- rollback: undo in reverse, leave v1 exactly as it was ----
        if sources_moved:
            rt1.sources = rt2.sources[len(new_sources):]
            del rt2.sources[len(new_sources):]
            for j in ingress1:
                j2 = rt2.junctions.get(j.definition.id)
                if j2 is None:
                    continue
                for s in j.attached_sources:
                    if s in j2.attached_sources:
                        j2.attached_sources.remove(s)
        for j in ingress1:
            j.redirect_to(None)
        _undo_callbacks(undo_cbs)
        if wal_moved:
            wal = rt2.wal
            rt2.wal = None
            for j2 in rt2.junctions.values():
                j2.wal = None
            rt1.wal = wal
            for j in ingress1:
                j.wal = wal
        for j in ingress1:
            j.start_async()
        for s in paused:
            s.resume()
        rt1.ctx.statistics.track_upgrade(
            (time.perf_counter() - t_pause) * 1000.0, 0, rollback=True)
        rec = getattr(rt1.ctx, "recorder", None)
        if rec is not None:
            # evidence for the post-mortem: why did the swap come back?
            rec.trigger("upgrade_rollback",
                        reason=f"hot-swap of {rt1.app.name!r} rolled back")
        try:
            rt2.shutdown(flush_durable=False)
        except Exception:  # noqa: BLE001 — rollback must complete
            log.exception("upgrade rollback: shadow v2 shutdown failed")
        raise

    # ---- post-swap: failures here are warnings, never a rollback ----
    rt2._start_persist_scheduler()
    transferred_sids = {s.definition.id for s in rt2.sources
                        if s not in new_sources}
    for s in new_sources:
        # connect only sources on streams with no carried-over transport —
        # a carried transport + a fresh connect would double-deliver
        if s.definition.id in transferred_sids:
            continue
        try:
            s.connect_with_retry()
        except Exception:  # noqa: BLE001
            log.exception("upgrade: connecting new source on %r failed "
                          "(its retry schedule continues)", s.definition.id)
    for s in paused:
        try:
            s.resume()  # buffered payloads drain through the redirect
        except Exception:  # noqa: BLE001
            log.exception("upgrade: resuming a source failed")
    cutover_pause_ms = (time.perf_counter() - t_pause) * 1000.0
    try:
        rt1.shutdown(flush_durable=False)
    except Exception:  # noqa: BLE001
        log.exception("upgrade: v1 teardown failed (v2 is live)")
    rt2.ctx.statistics.track_upgrade(cutover_pause_ms, replayed)
    tele = getattr(rt2.ctx, "telemetry", None)
    summary = {
        "app": build_app.name,
        "status": "swapped",
        "classification": diff.classification,
        "old_fingerprint": diff.old_fingerprint,
        "new_fingerprint": diff.new_fingerprint,
        "migrated": sorted(diff.migratable),
        "changed": sorted(diff.changed),
        "removed": sorted(diff.removed),
        "added": sorted(diff.added),
        "revision": revision,
        "wal_tail_replayed": replayed,
        "cutover_pause_ms": cutover_pause_ms,
        "diagnostics": [d.format() for d in diff.report.sorted()],
    }
    if tele is not None:
        try:
            tele.observe_upgrade(cutover_pause_ms)
        except AttributeError:  # pragma: no cover — older telemetry
            pass
    log.info("upgraded %r (%s) in %.1f ms source-paused time",
             build_app.name, diff.classification, cutover_pause_ms)
    return summary


def replay_wal(manager, app: SiddhiApp, wal_dir: str, *,
               app_name: Optional[str] = None,
               speed: Optional[float] = None,
               sleep: Callable[[float], None] = time.sleep) -> dict:
    """Deterministic accelerated-clock replay of recorded WAL segments
    against a candidate `app`. Sandboxed (sources/sinks/stores stripped,
    @app:persist detached — the journal is read-only via
    state/wal.read_records), virtual playback clock, per-record flush.
    `speed` scales record time against wall time (2.0 = twice realtime;
    None/inf = as fast as possible); `sleep` is injectable for tests
    (util/faults.py virtual-time idiom). Returns the replay summary; the
    `digest` field is bit-identical across runs of the same segments."""
    import numpy as np

    from ..errors import DefinitionNotExistError
    from ..state.wal import read_records
    from .app_runtime import SiddhiAppRuntime
    from .manager import sandbox_app
    from .stream import StreamCallback

    app, _interval = _detach_persist(sandbox_app(app))
    rt = SiddhiAppRuntime(app, manager.registry,
                          config_manager=manager.config_manager,
                          auto_flush_ms=0)  # no flusher thread: batch
    #                                         boundaries must be replay-driven
    tg = rt.ctx.timestamp_generator
    tg.playback = True  # current_time() == last event ts (virtual clock)
    rt.ctx.playback = True

    sha = hashlib.sha256()
    counts: dict[str, int] = {}

    class _Recorder(StreamCallback):
        def __init__(self, sid: str) -> None:
            self.sid = sid

        def receive(self, events) -> None:
            counts[self.sid] = counts.get(self.sid, 0) + len(events)
            sha.update(pickle.dumps(
                (self.sid,
                 [(e.timestamp, tuple(e.data), e.is_expired)
                  for e in events]),
                protocol=4))

    for sid, j in rt.junctions.items():
        j.subscribe(_Recorder(sid))
    for sid, f in rt.fault_junctions.items():
        f.subscribe(_Recorder(f"!{sid}"))

    rt.start()  # sandboxed: no transports; auto_flush 0: no flusher
    pace = (float(speed) if speed not in (None, 0)
            and speed != float("inf") else None)
    n = records = skipped = 0
    first_ts: Optional[int] = None
    last_ts: Optional[int] = None
    unknown: set = set()
    t0 = time.perf_counter()
    try:
        for kind, sid, tss, data in read_records(wal_dir,
                                                 app_name or app.name):
            if kind not in ("rows", "cols"):
                continue  # generic journal marks are not events
            records += 1
            try:
                handler = rt.get_input_handler(sid)
            except DefinitionNotExistError:
                if sid not in unknown:
                    unknown.add(sid)
                    log.warning("replay: stream %r is not defined on the "
                                "candidate app; its records are skipped",
                                sid)
                skipped += len(tss)
                continue
            if tss:
                if first_ts is None:
                    first_ts = tss[0]
                if pace is not None and last_ts is not None:
                    dt_s = max(0, tss[0] - last_ts) / 1000.0 / pace
                    if dt_s > 0:
                        sleep(dt_s)
                last_ts = tss[-1]
            if kind == "rows":
                handler.send_batch(data, timestamps=tss)
                n += len(data)
            else:  # "cols"
                handler.send_columns(
                    data, timestamps=np.asarray(tss, dtype=np.int64))
                n += len(tss)
            # one flush per journal record: batch boundaries — and with
            # them window/expiry phasing — depend only on the journal
            rt.flush()
        rt.drain()
    finally:
        rt.shutdown(flush_durable=False)
    wall_s = time.perf_counter() - t0
    virtual_ms = (last_ts - first_ts) if first_ts is not None else 0
    live = manager.runtimes.get(app_name or app.name)
    (live.ctx.statistics if live is not None
     else rt.ctx.statistics).track_replay(n)
    return {
        "app": app.name,
        "events": n,
        "records": records,
        "skipped": skipped,
        "outputs": dict(sorted(counts.items())),
        "digest": sha.hexdigest(),
        "virtual_ms": int(virtual_ms),
        "wall_s": wall_s,
        "speedup": (virtual_ms / 1000.0 / wall_s) if wall_s > 0 else None,
    }


def _bounded_shuffle(ordered: list, lateness_ms: int, seed: int,
                     fanout: int = 64) -> list:
    """Permute a ts-ascending arrival list under the bounded-disorder model:
    repeatedly pick uniformly from the eligible prefix — entries whose event
    time is within `lateness_ms` of the oldest pending entry (capped at
    `fanout` for O(n·fanout)). Every emitted entry therefore satisfies
    ts ≤ min(pending ts) + lateness at pick time, so when any later entry u
    arrives the gate's max_ts ≤ u.ts + lateness ⇒ watermark ≤ u.ts ⇒ u is
    never late. That is the displacement bound @app:eventTime promises to
    absorb — the oracle asserts the absorption is bit-exact."""
    rng = random.Random(seed)
    pending = list(ordered)
    out = []
    while pending:
        bound = pending[0][1] + lateness_ms
        hi = 1
        while hi < len(pending) and hi < fanout and pending[hi][1] <= bound:
            hi += 1
        out.append(pending.pop(rng.randrange(hi)))
    return out


def shuffled_replay(manager, app: SiddhiApp, wal_dir: Optional[str] = None,
                    *, app_name: Optional[str] = None, seeds: int = 16,
                    arrivals: Optional[list] = None) -> dict:
    """Determinism oracle for @app:eventTime: replay the same event set
    once in event-time order (the oracle) and `seeds` more times in
    seed-permuted arrival orders whose displacement is bounded by
    allowed.lateness, asserting every run's output digest is bit-identical
    to the oracle's and that no run diverted a single row as late.

    Events come from the app's WAL (`wal_dir`, via state/wal.read_records)
    or an explicit `arrivals` list of ``(stream_id, event_ts, row)``. Each
    run is sandboxed (transports stripped, @app:persist detached), on the
    virtual playback clock, one flush per arrival; after the last arrival
    `release_watermarks()` drains the gates, and the digest hashes each
    stream's CONCATENATED output event list — batch-boundary-insensitive
    by construction, though with the gate's per-event-time delivery
    grouping the boundaries themselves are invariant too.

    Returns a summary dict; ``matched`` is the verdict and ``violations``
    lists any conservation breaks (late diversions, rows still buffered
    after the drain). tools/shuffled_replay.py exits nonzero on either."""
    from ..errors import DefinitionNotExistError
    from .app_runtime import SiddhiAppRuntime
    from .manager import sandbox_app
    from .stream import StreamCallback

    app, _interval = _detach_persist(sandbox_app(app))

    if arrivals is None:
        if wal_dir is None:
            raise ValueError("shuffled_replay needs wal_dir or arrivals")
        from ..state.wal import read_records
        attr_order = {sid: [a.name for a in d.attributes]
                      for sid, d in app.stream_definitions.items()}
        arrivals = []
        for kind, sid, tss, data in read_records(wal_dir,
                                                 app_name or app.name):
            if kind == "rows":
                for ts, row in zip(tss, data):
                    arrivals.append((sid, int(ts), tuple(row)))
            elif kind == "cols":  # dict of columns, attribute order
                names = attr_order.get(sid)
                if names is None:
                    continue  # stream not on the candidate app
                cols = [data[nm] for nm in names]
                for i, ts in enumerate(tss):
                    row = tuple(c[i].item() if hasattr(c[i], "item")
                                else c[i] for c in cols)
                    arrivals.append((sid, int(ts), row))

    def _canon(a):  # deterministic total order; ts is the major key
        return (a[1], a[0], repr(a[2]))

    ordered = sorted(arrivals, key=_canon)

    def _run(order: list) -> tuple:
        rt = SiddhiAppRuntime(app, manager.registry,
                              config_manager=manager.config_manager,
                              auto_flush_ms=0)
        et = rt.ctx.event_time
        if et is None or not et.lateness_ms:
            rt.shutdown(flush_durable=False)
            raise ValueError(
                "shuffled_replay requires @app:eventTime with "
                "allowed.lateness > 0 — without a disorder budget there is "
                "nothing for the oracle to certify")
        tg = rt.ctx.timestamp_generator
        tg.playback = True
        rt.ctx.playback = True
        outputs: dict[str, list] = {}

        class _Tap(StreamCallback):
            def __init__(self, sid: str) -> None:
                self.sid = sid

            def receive(self, events) -> None:
                outputs.setdefault(self.sid, []).extend(
                    (e.timestamp, tuple(e.data), e.is_expired)
                    for e in events)

        for sid, j in rt.junctions.items():
            j.subscribe(_Tap(sid))
        for sid, f in rt.fault_junctions.items():
            f.subscribe(_Tap(f"!{sid}"))
        rt.start()
        skipped = 0
        try:
            for sid, ts, row in order:
                try:
                    handler = rt.get_input_handler(sid)
                except DefinitionNotExistError:
                    skipped += 1
                    continue
                handler.send_batch([row], timestamps=[ts])
                rt.flush()  # arrival granularity == flush granularity
            rt.release_watermarks()
            gates = {sid: j._et.snapshot()
                     for sid, j in rt.junctions.items()
                     if j._et is not None}
        finally:
            rt.shutdown(flush_durable=False)
        sha = hashlib.sha256()
        for sid in sorted(outputs):
            sha.update(pickle.dumps((sid, outputs[sid]), protocol=4))
        counts = {sid: len(evs) for sid, evs in sorted(outputs.items())}
        return sha.hexdigest(), counts, gates, et.lateness_ms, skipped

    def _conservation(seed, gates) -> list:
        out = []
        for sid, g in sorted(gates.items()):
            if g["late"]:
                out.append(f"seed={seed} stream={sid}: {g['late']} rows "
                           f"diverted late inside the disorder bound")
            if g["buffered"]:
                out.append(f"seed={seed} stream={sid}: {g['buffered']} rows "
                           f"still buffered after release_watermarks()")
            if g["admitted"] != g["released"] + g["late"] + g["buffered"]:
                out.append(f"seed={seed} stream={sid}: conservation broke "
                           f"(admitted {g['admitted']} != released "
                           f"{g['released']} + late {g['late']} + buffered "
                           f"{g['buffered']})")
        return out

    t0 = time.perf_counter()
    oracle_digest, counts, gates, lateness_ms, skipped = _run(ordered)
    violations = _conservation("oracle", gates)
    runs = []
    for seed in range(int(seeds)):
        shuffled = _bounded_shuffle(ordered, lateness_ms, seed)
        permuted = sum(1 for a, b in zip(ordered, shuffled) if a is not b)
        digest, _counts, g, _l, _s = _run(shuffled)
        violations.extend(_conservation(seed, g))
        runs.append({"seed": seed, "digest": digest,
                     "match": digest == oracle_digest,
                     "permuted": permuted})
    matched = all(r["match"] for r in runs) and not violations
    log.info("shuffled replay of %r: %d events x %d seeds, lateness %d ms "
             "-> %s", app.name, len(ordered), len(runs), lateness_ms,
             "bit-identical" if matched else "MISMATCH")
    return {
        "app": app.name,
        "events": len(ordered),
        "skipped": skipped,
        "lateness_ms": lateness_ms,
        "seeds": int(seeds),
        "oracle_digest": oracle_digest,
        "outputs": counts,
        "runs": runs,
        "violations": violations,
        "matched": matched,
        "wall_s": time.perf_counter() - t0,
    }
