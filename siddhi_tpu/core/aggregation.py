"""Incremental aggregation — `define aggregation A from S select ... group by
... aggregate [by tsAttr] every sec ... year`.

Reference: core/aggregation/ — AggregationRuntime.java:82 (per-duration
executor chain + tables), IncrementalExecutor.java:50,111 (bucket state,
rollover dispatch into the next-coarser duration),
OutOfOrderEventsDataAggregator (late-event merge),
IncrementalExecutorsInitialiser (restart rebuild), and the incremental
aggregator SPI under core/query/selector/attribute/aggregator/incremental/
(avg decomposes into sum+count, etc.).

TPU re-design — no cascade, no rollover events: because `bucket_start(d, ts)`
is a pure function of the event timestamp, each micro-batch scatters directly
into EVERY duration's bucket store (6 fused scatter-adds per batch instead of
an event-at-a-time rollover chain). Consequences, all deliberate:
  * out-of-order events need no special path — a late event's bucket is
    derived from its own timestamp and the scatter-add is order-invariant
    (replaces OutOfOrderEventsDataAggregator);
  * restart needs no rebuild — the stores ARE the persistent state, snapshot
    like every other pytree (replaces IncrementalExecutorsInitialiser);
  * `within ... per ...` reads are a mask over one duration's store, not a
    multi-table merge (replaces IncrementalAggregateCompileCondition).
Month/year buckets use Hinnant civil-calendar integer arithmetic on device
(GMT, matching the reference's default timezone —
core/util/IncrementalTimeConverterUtil.java).
"""

from __future__ import annotations

import dataclasses
from datetime import datetime, timezone
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..errors import DefinitionNotExistError, SiddhiAppCreationError
from ..extension.registry import ExtensionKind, Registry
from ..ops.aggregators import AggregatorFactory, AggregatorSpec
from ..ops.expr_compile import Scope, TypeResolver, compile_expression
from ..ops.groupby import KeyTable, hash_columns, init_key_table, key_lookup_or_insert
from ..query_api.definition import (
    AggregationDefinition,
    Attribute,
    AttributeType,
    Duration,
    StreamDefinition,
)
from ..query_api.expression import AttributeFunction, Constant, Expression, Variable
from . import dtypes
from .context import SiddhiAppContext
from .event import EventBatch, StreamCodec
from .stream import Receiver

AGG_TIMESTAMP = "AGG_TIMESTAMP"

_MS_WIDTH = {
    Duration.SECONDS: 1_000,
    Duration.MINUTES: 60_000,
    Duration.HOURS: 3_600_000,
    Duration.DAYS: 86_400_000,
}

_DAY_MS = 86_400_000


def _civil_from_days(days):
    """Hinnant civil_from_days: epoch day count → (year, month). Pure int64
    arithmetic, vectorized (GMT)."""
    z = days + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = jnp.floor_divide(5 * doy + 2, 153)
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    doy = jnp.floor_divide(153 * (m + jnp.where(m > 2, -3, 9)) + 2, 5) + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def bucket_start(duration: Duration, ts):
    """Bucket start (epoch ms) containing each timestamp, per duration.
    Reference: IncrementalTimeConverterUtil.getStartTimeOfAggregates."""
    ts = ts.astype(jnp.int64)
    if duration in _MS_WIDTH:
        w = _MS_WIDTH[duration]
        return ts - jnp.remainder(ts, w)
    days = jnp.floor_divide(ts, _DAY_MS)
    y, m = _civil_from_days(days)
    if duration == Duration.MONTHS:
        return _days_from_civil(y, m, jnp.ones_like(m)) * _DAY_MS
    if duration == Duration.YEARS:
        return _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y)) * _DAY_MS
    raise SiddhiAppCreationError(f"unsupported duration {duration}")


def bucket_bounds(duration: Duration, t: int) -> tuple[int, int]:
    """[start, end) of the bucket containing instant `t` (host scalars)."""
    lo = int(bucket_start(duration, jnp.array([t], jnp.int64))[0])
    if duration in _MS_WIDTH:
        return lo, lo + _MS_WIDTH[duration]
    probe = lo + (40 if duration == Duration.MONTHS else 370) * _DAY_MS
    hi = int(bucket_start(duration, jnp.array([probe], jnp.int64))[0])
    return lo, hi


def parse_time_constant(value) -> int:
    """`within` bound → epoch ms. Accepts epoch millis (int) or the
    reference's datetime string formats `yyyy-MM-dd HH:mm:ss` (GMT) with
    optional `+HH:MM` offset (reference: AggregationParser within handling)."""
    if isinstance(value, int):
        return value
    if isinstance(value, str):
        s = value.strip()
        for fmt in ("%Y-%m-%d %H:%M:%S %z", "%Y-%m-%d %H:%M:%S"):
            try:
                dt = datetime.strptime(s, fmt)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=timezone.utc)
                return int(dt.timestamp() * 1000)
            except ValueError:
                continue
        raise SiddhiAppCreationError(
            f"cannot parse within bound {value!r} (epoch ms or "
            "'yyyy-MM-dd HH:mm:ss [+HH:MM]')")
    raise SiddhiAppCreationError(f"bad within bound {value!r}")


class DurationStore(NamedTuple):
    """One duration's bucket table: composite (bucket, group-key) → dense slot.

    Replaces the reference's per-duration in-memory BaseIncrementalValueStore +
    backing table pair with one device hash table."""

    key_table: KeyTable
    bucket_ts: jax.Array  # int64[K] bucket start per slot
    group_cols: dict  # name -> [K] group attribute value per slot
    comps: tuple  # per flattened component: [K] accumulator
    alive: jax.Array  # bool[K] (False = never used or purged)


@dataclasses.dataclass
class _OutputSpec:
    """One select item of the aggregation definition."""

    name: str
    type: AttributeType
    is_group: bool = False
    group_attr: Optional[str] = None
    spec: Optional[AggregatorSpec] = None
    comp_offset: int = 0  # index of first component in the flat comp list


class AggregationRuntime(Receiver):
    """Runtime for one `define aggregation` (reference:
    core/aggregation/AggregationRuntime.java:82)."""

    def __init__(self, definition: AggregationDefinition, ctx: SiddhiAppContext,
                 input_junction, registry: Registry) -> None:
        self.definition = definition
        self.ctx = ctx
        self.junction = input_junction
        self.durations = tuple(definition.durations)
        if not self.durations:
            raise SiddhiAppCreationError(
                f"aggregation {definition.id!r} needs `aggregate every ...`")

        in_def: StreamDefinition = input_junction.definition
        self.codec_in = input_junction.codec
        attr_types = {a.name: a.type for a in in_def.attributes
                      if a.type != AttributeType.OBJECT}
        frames = {in_def.id: attr_types}
        self.resolver = TypeResolver(frames, in_def.id,
                                     {in_def.id: self.codec_in})
        self.frame_ref = in_def.id

        # --- aggregate by <attr> ---
        self.ts_attr = definition.aggregate_attribute
        if self.ts_attr is not None and attr_types.get(self.ts_attr) != AttributeType.LONG:
            raise SiddhiAppCreationError(
                f"aggregate by {self.ts_attr!r}: attribute must be long epoch ms")

        # --- group-by ---
        self.group_attrs: list[str] = []
        for g in definition.group_by or ():
            if not isinstance(g, Variable):
                raise SiddhiAppCreationError("aggregation group by must be attributes")
            if g.attribute not in attr_types:
                raise DefinitionNotExistError(
                    f"group by attribute {g.attribute!r} not in {in_def.id!r}")
            self.group_attrs.append(g.attribute)

        # --- outputs: group attrs pass through; aggregator calls decompose ---
        self.outputs: list[_OutputSpec] = []
        self._comp_args: list = []  # compiled arg executor per flat component
        self._comp_meta: list = []  # Component per flat component
        sel = definition.selector
        for oa in sel.attributes:
            name = oa.rename or self._infer_name(oa.expression)
            expr = oa.expression
            if isinstance(expr, Variable) and expr.attribute in self.group_attrs:
                self.outputs.append(_OutputSpec(
                    name=name, type=attr_types[expr.attribute],
                    is_group=True, group_attr=expr.attribute))
                continue
            if isinstance(expr, AttributeFunction):
                factory = registry.lookup(ExtensionKind.AGGREGATOR,
                                          expr.namespace, expr.name)
                if isinstance(factory, AggregatorFactory):
                    args = [compile_expression(p, self.resolver, registry)
                            for p in expr.parameters]
                    spec = factory.make([a.type for a in args])
                    if spec.custom_scan is not None:
                        # distinctCount et al. don't decompose into additive
                        # bucket components (reference gets per-bucket distinct
                        # sets via its incremental aggregator SPI — not built)
                        raise SiddhiAppCreationError(
                            f"aggregation {definition.id!r}: {expr.name!r} is "
                            "not supported in incremental aggregations")
                    off = len(self._comp_meta)
                    for comp in spec.components:
                        self._comp_meta.append(comp)
                        self._comp_args.append(args[0] if args else None)
                    self.outputs.append(_OutputSpec(
                        name=name, type=spec.return_type, spec=spec,
                        comp_offset=off))
                    continue
            raise SiddhiAppCreationError(
                f"aggregation {definition.id!r} select item {name!r}: must be "
                "a group-by attribute or an aggregator call (the reference's "
                "last-value semantics for other attributes is not supported)")

        # --- output frame (the store-query surface) ---
        out_attrs = [Attribute(o.name, o.type) for o in self.outputs]
        out_attrs.append(Attribute(AGG_TIMESTAMP, AttributeType.LONG))
        self.output_attr_types = {a.name: a.type for a in out_attrs}
        self.output_definition = StreamDefinition(
            id=definition.id, attributes=tuple(out_attrs))
        self.output_codec = StreamCodec(self.output_definition, ctx.global_strings)
        # group attr name -> stored column dtype (store group cols under their
        # INPUT attribute name so duplicates across outputs share storage)
        self._group_layout = {g: dtypes.device_dtype(attr_types[g])
                              for g in self.group_attrs}

        self.capacity = max(ctx.effective_group_capacity, 4096)
        self.state = tuple(self._init_store() for _ in self.durations)
        self._batches_since_check = 0
        #: retention per duration (@purge/@retentionPeriod), ms; None = keep
        self.retention_ms = self._parse_retention(definition)

        # --- distributed (sharded) mode over a device mesh ---
        # The reference's `isDistributed` (AggregationRuntime.java:87,266,384):
        # each shard owns the (bucket, group) rows whose GROUP-key hash lands
        # on it, scatters locally, and `find()` merges shard stores. Here the
        # duration stores gain a leading mesh-sharded shard axis; ingest runs
        # as one shard_map step (each shard masks the replicated batch down to
        # its keys), and reads flatten [n_shards, K] -> [n_shards*K] — the
        # flatten IS the gather, inserted by XLA where the read computes.
        self.mesh = getattr(ctx, "mesh", None) if self.group_attrs else None
        self.n_shards = 1
        if self.mesh is not None:
            self.n_shards = self.mesh.shape[self.mesh.axis_names[0]]
        self._build_steps()
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.sharded import stack_states

            sharding = NamedSharding(self.mesh, P(self.mesh.axis_names[0]))
            self.state = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding),
                stack_states(self.state, self.n_shards))

        # --- durable duration stores (@store on the aggregation) ---
        # reference: aggregations persist per-duration tables
        # (<AggName>_SECONDS, ...) in an external store and REBUILD their
        # in-memory buckets from them on restart
        # (core/aggregation/IncrementalExecutorsInitialiser.java). Here:
        # `@store(type='x', ...)` on `define aggregation` creates one
        # RecordStore per duration; flush_durable() (shutdown + persist)
        # writes bucket rows through, and construction rebuilds from any
        # rows found.
        self._durable_stores = None
        #: rows held back at a capacity-truncated rebuild, re-merged at flush
        self._unrestored: dict = {}
        store_ann = next((a for a in (definition.annotations or ())
                          if a.name.lower() == "store"), None)
        if store_ann is not None:
            self._init_durable(store_ann)

        input_junction.subscribe(self)
        if self._durable_stores is not None:
            self._rebuild_from_durable()

    def _init_durable(self, ann) -> None:
        from ..extension.registry import ExtensionKind
        from ..io.record_table import RecordStore
        from ..query_api.definition import Attribute, TableDefinition

        props = {e.key: e.value for e in ann.elements if e.key}
        store_type = props.pop("type", None)
        if not store_type:
            raise SiddhiAppCreationError(
                f"aggregation {self.definition.id!r}: @store needs "
                "type='<registered store>'")
        factory = self.ctx.registry.require(ExtensionKind.STORE, "",
                                            store_type)
        self._durable_stores = {}
        attrs = [Attribute(AGG_TIMESTAMP, AttributeType.LONG)]
        attrs += [Attribute(g, self._group_attr_type(g))
                  for g in self.group_attrs]
        for ci in range(len(self._comp_meta)):
            attrs.append(Attribute(f"_c{ci}", AttributeType.DOUBLE))
        for dur in self.durations:
            td = TableDefinition(
                id=f"{self.definition.id}_{dur.value}",
                attributes=tuple(attrs))
            store: RecordStore = factory()
            store.init(td, dict(props),
                       self.ctx.config_reader(f"store:{store_type}")
                       if hasattr(self.ctx, "config_reader") else None)
            store.connect()
            self._durable_stores[dur] = store

    def _group_attr_type(self, name):
        in_def = self.junction.definition
        for a in in_def.attributes:
            if a.name == name:
                return a.type
        raise DefinitionNotExistError(name)

    def export_rows(self) -> dict:
        """Decode every duration store into host bucket rows:
        {duration: [ {AGG_TIMESTAMP, <groups...>, _c0.._cN} ]}."""
        import numpy as np
        out = {}
        for d_idx, dur in enumerate(self.durations):
            flat = self.state[d_idx]
            if self.n_shards > 1:  # drop the shard axis: disjoint union
                flat = jax.tree_util.tree_map(
                    lambda x: x.reshape((-1,) + x.shape[2:]), flat)
            alive = np.asarray(flat.alive)
            bts = np.asarray(flat.bucket_ts)
            groups = {g: np.asarray(v) for g, v in flat.group_cols.items()}
            comps = [np.asarray(c) for c in flat.comps]
            gtypes = {g: self._group_attr_type(g) for g in self.group_attrs}
            rows = []
            for i in np.nonzero(alive)[0]:
                row = {AGG_TIMESTAMP: int(bts[i])}
                for g, arr in groups.items():
                    v = arr[i]
                    if gtypes[g] == AttributeType.STRING:
                        row[g] = self.ctx.global_strings.decode(int(v))
                    else:
                        row[g] = v.item()
                for ci, c in enumerate(comps):
                    row[f"_c{ci}"] = float(c[i])
                rows.append(row)
            out[dur] = rows
        return out

    def flush_durable(self) -> None:
        """Overwrite the durable duration tables with the current buckets.
        If the last REBUILD truncated (more durable rows than device
        capacity), merge instead — an authoritative overwrite would
        permanently erase the buckets that never fit."""
        if self._durable_stores is None:
            return
        exported = self.export_rows()
        for dur, store in self._durable_stores.items():
            tid = f"{self.definition.id}_{dur.value}"
            rows = exported[dur]
            held = self._unrestored.get(dur)
            if held:
                # buckets held back at a capacity-truncated rebuild re-join
                # the durable set (device rows win on key collisions);
                # retention uses the STREAM clock — wall time would wrongly
                # purge playback/external-time apps
                def _k(r):
                    return (r[AGG_TIMESTAMP],
                            tuple(r[g] for g in self.group_attrs))
                merged = {_k(r): r for r in held}
                for r in rows:
                    merged[_k(r)] = r
                rows = list(merged.values())
                retention = self.retention_ms.get(dur)
                if retention is not None:
                    cutoff = (self.ctx.timestamp_generator.current_time()
                              - retention)
                    rows = [r for r in rows if r[AGG_TIMESTAMP] >= cutoff]
            store.delete(store.compile_condition(None, tid))
            if rows:
                store.add(rows)

    def close_durable(self) -> None:
        if self._durable_stores is None:
            return
        for store in self._durable_stores.values():
            store.disconnect()

    def _rebuild_from_durable(self) -> None:
        """Re-scatter durable rows into fresh device stores (the reference's
        IncrementalExecutorsInitialiser restart path)."""
        import numpy as np
        gtypes = {g: self._group_attr_type(g) for g in self.group_attrs}
        for d_idx, dur in enumerate(self.durations):
            store = self._durable_stores.get(dur)
            if store is None:
                continue
            rows = list(store.find(store.compile_condition(
                None, f"{self.definition.id}_{dur.value}")))
            if not rows:
                continue
            fit = int(0.7 * self.capacity * self.n_shards)
            if len(rows) > fit:
                # restore the NEWEST buckets that fit; hold the rest
                # host-side so flush_durable never erases them
                rows.sort(key=lambda r: r[AGG_TIMESTAMP], reverse=True)
                self._unrestored[dur] = rows[fit:]
                rows = rows[:fit]
                import warnings
                warnings.warn(
                    f"aggregation {self.definition.id!r} [{dur.value}]: "
                    f"{len(self._unrestored[dur])} durable buckets exceed "
                    "device capacity on rebuild; oldest held host-side "
                    "(raise group_capacity)", stacklevel=2)
            n = len(rows)
            bts = np.asarray([r[AGG_TIMESTAMP] for r in rows], np.int64)
            gcols = {}
            for g in self.group_attrs:
                if gtypes[g] == AttributeType.STRING:
                    gcols[g] = np.asarray(
                        [self.ctx.global_strings.encode(r[g]) for r in rows],
                        np.int32)
                else:
                    gcols[g] = np.asarray(
                        [r[g] for r in rows],
                        dtypes.numpy_dtype(gtypes[g]))
            comps = [np.asarray([r[f"_c{ci}"] for r in rows], np.float64)
                     for ci in range(len(self._comp_meta))]
            new_store, n_restored = self._restore_fn(d_idx)(
                self.state[d_idx], jnp.asarray(bts),
                {g: jnp.asarray(v) for g, v in gcols.items()},
                [jnp.asarray(c) for c in comps], jnp.int32(n))
            if int(n_restored) < n:
                import warnings
                warnings.warn(
                    f"aggregation {self.definition.id!r} [{dur.value}]: only "
                    f"{int(n_restored)}/{n} durable buckets fit the "
                    f"{'sharded ' if self.mesh is not None else ''}store "
                    "capacity on rebuild — raise group_capacity",
                    stacklevel=2)
            self._replace_store(d_idx, new_store)

    def _restore_fn(self, d_idx):
        """Jitted bulk scatter of restored rows into one duration store."""
        group_attrs = self.group_attrs
        comp_meta = self._comp_meta
        K = self.capacity
        mesh = self.mesh
        n_shards = self.n_shards

        def restore(store: DurationStore, bts, gcols, comps, valid):
            keyparts = [bts] + [gcols[g] for g in group_attrs]
            key = hash_columns(keyparts)
            kt, ids, kres = key_lookup_or_insert(store.key_table, key, valid)
            widx = jnp.where(valid & kres, ids, K)
            new_bucket = store.bucket_ts.at[widx].set(bts, mode="drop")
            new_group = {g: store.group_cols[g].at[widx].set(
                gcols[g], mode="drop") for g in group_attrs}
            new_alive = store.alive.at[widx].set(True, mode="drop")
            new_comps = []
            for ci, comp in enumerate(comp_meta):
                new_comps.append(store.comps[ci].at[widx].set(
                    comps[ci].astype(comp.dtype), mode="drop"))
            n_ok = jnp.sum(valid & kres, dtype=jnp.int32)
            return DurationStore(kt, new_bucket, new_group,
                                 tuple(new_comps), new_alive), n_ok

        def plain_restore(store, bts, gcols, comps, n):
            valid = jnp.arange(bts.shape[0]) < n
            return restore(store, bts, gcols, comps, valid)

        if mesh is not None:
            # re-scatter restored rows to their OWNING shard by group hash —
            # the same ownership rule the sharded ingest uses
            # (parallel/sharded.shard_owned), so a restored mesh app starts
            # balanced instead of piling every durable row onto shard 0
            def sharded_restore(store, bts, gcols, comps, n):
                valid = jnp.arange(bts.shape[0]) < n
                keys = hash_columns([gcols[g] for g in group_attrs])
                shard_of = keys.astype(jnp.uint32) % jnp.uint32(n_shards)

                def one(local, sidx):
                    return restore(local, bts, gcols, comps,
                                   valid & (shard_of == sidx))

                new_store, n_ok = jax.vmap(one, in_axes=(0, 0))(
                    store, jnp.arange(n_shards, dtype=jnp.uint32))
                return new_store, jnp.sum(n_ok, dtype=jnp.int32)

            return jax.jit(sharded_restore)
        return jax.jit(plain_restore)

    def _build_steps(self) -> None:
        """(Re)build the jitted ingest/evict for the current capacity —
        plain single-device, or shard_map over the mesh in distributed
        mode."""
        if self.mesh is None:
            self._ingest = jax.jit(self._make_ingest(), donate_argnums=(0,))
            self._evict = jax.jit(self._make_evict())
            return
        from jax.sharding import PartitionSpec as P

        from ..parallel.sharded import _SHARD_KW, shard_map

        mesh = self.mesh
        axis = mesh.axis_names[0]
        n_shards = self.n_shards
        group_attrs = self.group_attrs
        ingest = self._make_ingest()

        def shard_ingest(state, batch: EventBatch, now):
            from ..parallel.sharded import shard_owned

            local = jax.tree_util.tree_map(lambda x: x[0], state)
            mine = shard_owned(batch, [batch.cols[g] for g in group_attrs],
                               axis, n_shards)
            local = ingest(local, mine, now)
            return jax.tree_util.tree_map(lambda x: x[None], local)

        self._ingest = jax.jit(
            shard_map(shard_ingest, mesh=mesh,
                      in_specs=(P(axis), P(), P()), out_specs=P(axis),
                      **_SHARD_KW),
            donate_argnums=(0,))

        def shard_ingest_lanes(state, batch: EventBatch, now):
            # per-host sharded ingestion: the batch arrives LANE-SHARDED
            # (each shard holds only its own pre-routed rows —
            # parallel/multihost.global_lane_batch). shard_owned stays as a
            # guard: mis-routed rows are dropped, never double-counted.
            from ..parallel.sharded import shard_owned

            local = jax.tree_util.tree_map(lambda x: x[0], state)
            mine = shard_owned(batch, [batch.cols[g] for g in group_attrs],
                               axis, n_shards)
            local = ingest(local, mine, now)
            return jax.tree_util.tree_map(lambda x: x[None], local)

        self._ingest_lanes = jax.jit(
            shard_map(shard_ingest_lanes, mesh=mesh,
                      in_specs=(P(axis), P(axis), P()), out_specs=P(axis),
                      **_SHARD_KW),
            donate_argnums=(0,))
        self._evict = jax.jit(jax.vmap(self._make_evict(), in_axes=(0, 0)))

    def ingest_global(self, batch: EventBatch, now: int) -> None:
        """Ingest a LANE-SHARDED global EventBatch (per-host sharded
        ingestion over a multi-host mesh: every process calls this with the
        same global program; each contributed only its own rows —
        parallel/multihost.global_lane_batch). Requires mesh mode."""
        import jax.numpy as jnp
        if self.mesh is None:
            raise SiddhiAppCreationError(
                "ingest_global needs a mesh-enabled aggregation "
                "(create the runtime with mesh=...)")
        self.state = self._ingest_lanes(self.state, batch, jnp.int64(now))

    @staticmethod
    def _parse_retention(definition) -> dict:
        """@purge(enable='true', @retentionPeriod(sec='120 min', min='24 hours',
        ...)) (reference: core/aggregation/IncrementalDataPurger.java)."""
        from .partition import _parse_annotation_time
        out: dict[Duration, int] = {}
        ann = next((a for a in definition.annotations or ()
                    if a.name.lower() == "purge"), None)
        if ann is None or (ann.element("enable") or "true").lower() == "false":
            return out
        rp = ann.nested_annotation("retentionPeriod")
        if rp is None:
            return out
        for e in rp.elements:
            if e.key:
                out[Duration.parse(e.key)] = _parse_annotation_time(e.value)
        return out

    # ------------------------------------------------------------------ build

    @staticmethod
    def _infer_name(expr: Expression) -> str:
        if isinstance(expr, Variable):
            return expr.attribute
        if isinstance(expr, AttributeFunction):
            return expr.name
        raise SiddhiAppCreationError(
            "aggregation select items need `as` names for expressions")

    def _init_store(self) -> DurationStore:
        K = self.capacity
        return DurationStore(
            key_table=init_key_table(K),
            bucket_ts=jnp.zeros((K,), jnp.int64),
            group_cols={g: jnp.zeros((K,), dt)
                        for g, dt in self._group_layout.items()},
            comps=tuple(jnp.zeros((K,), c.dtype) if c.op == "sum"
                        else jnp.full((K,), _monotone_identity(c), c.dtype)
                        for c in self._comp_meta),
            alive=jnp.zeros((K,), bool),
        )

    def _make_ingest(self):
        durations = self.durations
        frame_ref = self.frame_ref
        ts_attr = self.ts_attr
        group_attrs = self.group_attrs
        comp_meta = self._comp_meta
        comp_args = self._comp_args
        K = self.capacity

        def ingest(state, batch: EventBatch, now):
            scope = Scope()
            scope.add_frame(frame_ref, batch.cols, batch.ts, batch.valid,
                            default=True)
            scope.extras["now"] = now
            ts_src = (batch.cols[ts_attr] if ts_attr is not None else batch.ts)
            ts_src = ts_src.astype(jnp.int64)
            sign = jnp.ones_like(batch.ts, dtype=jnp.float32)
            arg_vals = [a(scope) if a is not None else None for a in comp_args]
            deltas = [c.delta(v, sign) for c, v in zip(comp_meta, arg_vals)]

            new_state = []
            for d_idx, dur in enumerate(durations):
                store: DurationStore = state[d_idx]
                bucket = bucket_start(dur, ts_src)
                keyparts = [bucket] + [batch.cols[g] for g in group_attrs]
                key = hash_columns(keyparts)
                kt, ids, kres = key_lookup_or_insert(
                    store.key_table, key, batch.valid)
                widx = jnp.where(batch.valid & kres, ids, K)
                new_bucket_ts = store.bucket_ts.at[widx].set(bucket, mode="drop")
                new_group = {g: store.group_cols[g].at[widx].set(
                    batch.cols[g], mode="drop") for g in group_attrs}
                new_alive = store.alive.at[widx].set(True, mode="drop")
                new_comps = []
                for ci, comp in enumerate(comp_meta):
                    acc = store.comps[ci]
                    d = deltas[ci].astype(acc.dtype)
                    if comp.op == "sum":
                        acc = acc.at[widx].add(d, mode="drop")
                    elif comp.op == "min":
                        acc = acc.at[widx].min(d, mode="drop")
                    else:
                        acc = acc.at[widx].max(d, mode="drop")
                    new_comps.append(acc)
                new_state.append(DurationStore(
                    kt, new_bucket_ts, new_group, tuple(new_comps), new_alive))
            return tuple(new_state)

        return ingest

    def _make_evict(self):
        """(store, cutoff) -> store' keeping only buckets >= cutoff, with a
        rebuilt key table (the reference's IncrementalDataPurger deletes rows
        from duration tables; here we re-hash the kept slots into a fresh
        store — one fused gather/scatter)."""
        group_attrs = self.group_attrs
        comp_meta = self._comp_meta
        K = self.capacity
        layout = self._group_layout

        def evict(store: DurationStore, cutoff):
            keep = store.alive & (store.bucket_ts >= cutoff)
            keys = hash_columns([store.bucket_ts]
                                + [store.group_cols[g] for g in group_attrs])
            kt, ids, kres = key_lookup_or_insert(init_key_table(K), keys, keep)
            widx = jnp.where(keep & kres, ids, K)
            new_bucket = jnp.zeros((K,), jnp.int64).at[widx].set(
                store.bucket_ts, mode="drop")
            new_group = {g: jnp.zeros((K,), layout[g]).at[widx].set(
                store.group_cols[g], mode="drop") for g in group_attrs}
            new_alive = jnp.zeros((K,), bool).at[widx].set(True, mode="drop")
            new_comps = []
            for ci, comp in enumerate(comp_meta):
                base = (jnp.zeros((K,), comp.dtype) if comp.op == "sum"
                        else jnp.full((K,), _monotone_identity(comp), comp.dtype))
                new_comps.append(base.at[widx].set(store.comps[ci], mode="drop"))
            return DurationStore(kt, new_bucket, new_group, tuple(new_comps),
                                 new_alive)

        return evict

    def _replace_store(self, d_idx: int, store: DurationStore) -> None:
        state = list(self.state)
        state[d_idx] = store
        self.state = tuple(state)

    def _grow(self) -> None:
        """Double every duration store's capacity (one retrace + rehash each).
        Taken when eviction cannot free slots — high *group* cardinality
        rather than bucket age (the reference grows its HashMaps the same way,
        implicitly)."""
        import warnings
        self.capacity *= 2
        warnings.warn(
            f"aggregation {self.definition.id!r}: growing bucket stores to "
            f"{self.capacity} slots (set group_capacity higher to avoid the "
            "rehash)", stacklevel=2)
        self._build_steps()
        # rehash every store into the new capacity (cutoff far in the past
        # keeps everything)
        keep_all = (jnp.full((self.n_shards,), -(1 << 62), jnp.int64)
                    if self.mesh is not None else jnp.int64(-(1 << 62)))
        self.state = tuple(
            self._evict(store, keep_all) for store in self.state)

    def _maybe_evict(self, now: int) -> None:
        """Retention purge + capacity-pressure handling: evict buckets older
        than the newest half when age explains the pressure, grow the store
        when group cardinality does — never silently drop or corrupt.

        All statistics are PER SHARD (capacity is a per-shard quantity in
        distributed mode; global math here would over-evict by ~n_shards)."""
        import numpy as np
        S, K = self.n_shards, self.capacity
        grow = False
        for d_idx, dur in enumerate(self.durations):
            store = self.state[d_idx]
            retention = self.retention_ms.get(dur)
            base_cutoff = (now - retention) if retention is not None else 0
            counts = np.atleast_1d(np.asarray(store.key_table.count))
            pressure = int(counts.max()) > int(0.85 * K)
            if retention is None and not pressure:
                # fast path: only the scalar count crosses to the host
                continue
            alive = np.asarray(store.alive).reshape(S, K)
            bts = np.asarray(store.bucket_ts).reshape(S, K)
            cutoffs = np.full((S,), base_cutoff, dtype=np.int64)
            for s in range(S):
                if int(counts[s]) <= int(0.85 * K):
                    continue
                live_b = np.sort(bts[s][alive[s]])[::-1]
                pressure_cutoff = int(live_b[:K // 2][-1])
                would_keep = int(
                    (live_b >= max(base_cutoff, pressure_cutoff)).sum())
                if would_keep > int(0.7 * K):
                    grow = True  # eviction can't help: too many live groups
                else:
                    cutoffs[s] = max(cutoffs[s], pressure_cutoff)
                    import warnings
                    warnings.warn(
                        f"aggregation {self.definition.id!r} [{dur.value}]"
                        f"{f' shard {s}' if S > 1 else ''}: store at "
                        f"capacity; evicting buckets older than "
                        f"{pressure_cutoff} (raise group_capacity or add "
                        "@purge retention)", stacklevel=2)
            evictable = (alive & (bts < cutoffs[:, None])).any()
            if (cutoffs > 0).any() and evictable:
                arg = (jnp.asarray(cutoffs) if self.mesh is not None
                       else jnp.int64(int(cutoffs[0])))
                self._replace_store(d_idx, self._evict(store, arg))
        if grow:
            self._grow()

    # ---------------------------------------------------------------- runtime

    def on_batch(self, batch: EventBatch, now: int) -> None:
        cap = self.junction.batch_size
        if batch.capacity < cap:
            # the jitted ingest is traced at the junction capacity; widen
            # shape-bucketed deliveries back (new lanes invalid)
            batch = batch.pad_to(cap)
        self.state = self._ingest(self.state, batch, jnp.int64(now))
        self._batches_since_check += 1
        if self._batches_since_check >= 32:
            self._batches_since_check = 0
            self._maybe_evict(now)

    # ------------------------------------------------------------------- find

    def duration_index(self, per) -> int:
        if isinstance(per, Expression):
            if not isinstance(per, Constant):
                raise SiddhiAppCreationError("per must be a constant duration")
            per = per.value
        if isinstance(per, str):
            per = Duration.parse(per)
        if per not in self.durations:
            raise SiddhiAppCreationError(
                f"aggregation {self.definition.id!r} has no duration {per}; "
                f"available: {[d.value for d in self.durations]}")
        return self.durations.index(per)

    def store_contents(self, store: DurationStore, now,
                       within: Optional[tuple[int, int]] = None):
        """Output-frame view over one duration's store: (cols, ts, valid) —
        the findable surface for store queries and joins (reference:
        AggregationRuntime.find / compileExpression:384+). In distributed
        mode the store arrives with a leading shard axis; flattening it to
        [n_shards*K] is the shard-merged `find()` — rows are disjoint across
        shards (group-hash ownership), so the union needs no combining."""
        if store.bucket_ts.ndim == 2:
            store = jax.tree_util.tree_map(
                lambda x: x.reshape((-1,) + x.shape[2:]), store)
        cols = {}
        for o in self.outputs:
            if o.is_group:
                cols[o.name] = store.group_cols[o.group_attr]
            else:
                parts = [store.comps[o.comp_offset + i]
                         for i in range(len(o.spec.components))]
                cols[o.name] = o.spec.finalize(parts)
        cols[AGG_TIMESTAMP] = store.bucket_ts
        valid = store.alive
        if within is not None:
            valid = valid & (store.bucket_ts >= jnp.int64(within[0])) \
                & (store.bucket_ts < jnp.int64(within[1]))
        return cols, store.bucket_ts, valid

    def view(self, per, within_range=None) -> "_AggregationView":
        """Bind a `per` duration (+ optional within bounds) into a store-like
        object OnDemandQueryRuntime / joins can probe."""
        d_idx = self.duration_index(per)
        within = None
        if within_range is not None:
            lo = parse_time_constant(_const_value(within_range[0]))
            if within_range[1] is None:
                # single-value within: the whole bucket containing the instant
                # (reference's `within <point>` form)
                lo, hi = bucket_bounds(self.durations[d_idx], lo)
            else:
                hi = parse_time_constant(_const_value(within_range[1]))
            within = (lo, hi)
        return _AggregationView(self, d_idx, within)


def _monotone_identity(comp):
    if comp.op == "min":
        return (jnp.iinfo(comp.dtype).max
                if jnp.issubdtype(comp.dtype, jnp.integer) else jnp.inf)
    return (jnp.iinfo(comp.dtype).min
            if jnp.issubdtype(comp.dtype, jnp.integer) else -jnp.inf)


def _const_value(expr):
    if isinstance(expr, Constant):
        return expr.value
    if isinstance(expr, (int, str)):
        return expr
    raise SiddhiAppCreationError(f"within bound must be a constant, got {expr!r}")


class _AggregationView:
    """Store adapter: quacks like a named window for OnDemandQueryRuntime
    (definition / attr_types / codec / state / contents)."""

    def __init__(self, runtime: AggregationRuntime, d_idx: int,
                 within: Optional[tuple[int, int]]) -> None:
        self.runtime = runtime
        self.d_idx = d_idx
        self.within = within
        self.definition = runtime.output_definition
        self.attr_types = dict(runtime.output_attr_types)
        self.codec = runtime.output_codec

    @property
    def state(self):
        return self.runtime.state[self.d_idx]

    def contents(self, store, now):
        return self.runtime.store_contents(store, now, self.within)
