"""Event-time processing: per-stream watermarks, allowed lateness, and a
deterministic reorder gate — the host half of out-of-order handling.

Reference semantics: the operator-semantics survey's bounded-disorder model
(watermark = max event time seen − allowed lateness; events older than the
watermark are LATE). The reference engine's externalTime windows assume the
producer delivers in event-time order; with "millions of devices" feeding one
stream that assumption fails, and a max-seen watermark silently folds late
rows into the wrong pane.

Declared per app as

    @app:eventTime(timestamp='ts', allowed.lateness='5 sec',
                   idle.timeout='1 min')

and attached by the app runtime to every INGRESS junction whose stream
carries the timestamp attribute. The gate sits at the junction's single
row->EventBatch choke point (`StreamJunction._flush_rows`):

  admit    each row's event time is read from the annotated attribute; rows
           older than the current watermark divert to the ErrorStore as
           REPLAYABLE `kind="late"` entries (never silently dropped); the
           rest enter a min-heap keyed (event_ts, arrival_seq)
  release  once the watermark (max_ts − allowed.lateness) passes a buffered
           row's event time, the row is emitted — in event-time order, with
           the EVENT time as its batch timestamp

so the stream the device sees is sorted by event time regardless of arrival
order. That re-binding of the time axis is what makes the downstream plane
deterministic: junction fan-out, fused SharedStepGroups, join sides, and
pattern states all consume the same sorted batches, and the device-side
externalTime watermark (ops/windows.py) merely *lags* it by allowed.lateness
to keep panes open for the gate's in-flight rows.

Determinism contract (proved by the shuffled-replay oracle in
core/upgrade.py): for any arrival permutation whose event-time displacement
is bounded by allowed.lateness, the released sequence — and therefore every
downstream output — is bit-identical to the in-order run, with zero late
diversions. Beyond the bound, rows divert to the side output where
`/errors/replay` re-admits them through `bypass()` for corrected
(upsert-style) re-emission.
"""

from __future__ import annotations

import heapq
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Optional, Sequence


@dataclass(frozen=True)
class EventTimeConfig:
    """Parsed `@app:eventTime(...)` (core/app_runtime.py)."""

    #: stream attribute (INT/LONG) carrying the event's occurrence time (ms)
    attr: str
    #: bounded-disorder budget: watermark = max_ts − lateness_ms
    lateness_ms: int = 0
    #: wall-clock idle window after which buffered rows are force-released
    #: (heartbeat-driven; None = hold until data or an explicit release)
    idle_timeout_ms: Optional[int] = None


class EventTimeGate:
    """Per-junction watermark generator + reorder buffer.

    All mutation happens under the app controller lock: `admit` runs inside
    `StreamJunction._flush_rows` (flush holds the lock), and `bypass()` takes
    the lock for the whole replay so a concurrent producer flush can never
    slip rows through the gate while the late-admission flag is up.
    """

    def __init__(self, junction, cfg: EventTimeConfig) -> None:
        self.junction = junction
        self.cfg = cfg
        names = [a.name for a in junction.definition.attributes]
        self.attr_idx = names.index(cfg.attr)
        self.stream = junction.definition.id
        #: max event time ever admitted (None until the first row)
        self.max_ts: Optional[int] = None
        #: watermark floor left behind by a forced release (idle timeout /
        #: shutdown drain): rows older than a released row must not later
        #: sneak out in front of it, so the floor pins the watermark at the
        #: drained max even though max_ts − lateness sits below it
        self._wm_floor: Optional[int] = None
        self._heap: list = []  # (event_ts, seq, arrival_ts, row)
        self._seq = 0
        self._bypass = 0
        self._last_wm: Optional[int] = None
        self._last_admit = time.monotonic()
        # conservation counters: admitted == released + late + buffered()
        self.admitted = 0
        self.released = 0
        self.late = 0
        self.bypassed = 0

    # ------------------------------------------------------------- watermark

    def watermark(self) -> Optional[int]:
        if self.max_ts is None:
            return self._wm_floor
        wm = self.max_ts - self.cfg.lateness_ms
        if self._wm_floor is not None and self._wm_floor > wm:
            wm = self._wm_floor
        return wm

    def buffered(self) -> int:
        return len(self._heap)

    # ----------------------------------------------------------- admit/release

    def admit(self, tss: Sequence[int], rows: Sequence):
        """Gate one flushed row batch. Returns a list of (event_tss, rows)
        delivery groups — the rows the watermark has passed, sorted by
        event time, timestamped WITH their event time, grouped per
        `_group` — and diverts watermark-older rows to the ErrorStore
        (kind="late") via the junction. Per-row classification depends
        only on the arrival prefix, never on how producers happened to
        chop the batches."""
        idx = self.attr_idx
        heap = self._heap
        bypass = self._bypass > 0
        late: list = []
        for ts, row in zip(tss, rows):
            try:
                ets = int(row[idx])
            except (TypeError, ValueError):
                late.append((ts, row))  # unreadable event time: side output
                continue
            if not bypass:
                wm = self.watermark()
                if wm is not None and ets < wm:
                    late.append((ets, row))
                    continue
            else:
                self.bypassed += 1
            if self.max_ts is None or ets > self.max_ts:
                self.max_ts = ets
            self._seq += 1
            heapq.heappush(heap, (ets, self._seq, ts, row))
        self.admitted += len(rows)
        self.late += len(late)
        released: list = []
        wm = self.watermark()
        if wm is not None:
            # lateness > 0 releases STRICTLY below the watermark: a row
            # with ets == wm is still admissible (the late check is
            # `ets < wm`), so releasing at equality could split its
            # distinct-ts delivery group across two flushes in some
            # arrival orders — the one seam in the determinism proof.
            # Holding until wm passes ets means every non-late row with
            # that ts has already arrived when the group delivers.
            # lateness == 0 keeps `<=` so in-order streams pass through
            # with no one-event delay (pure sorter mode).
            strict = bool(self.cfg.lateness_ms)
            while heap and (heap[0][0] < wm if strict
                            else heap[0][0] <= wm):
                ets, _seq, _ats, row = heapq.heappop(heap)
                released.append((ets, row))
        self.released += len(released)
        if late:
            self.junction._divert_late(late)
        if wm is not None and wm != self._last_wm:
            self._last_wm = wm
            self._on_advance(wm)
        self._last_admit = time.monotonic()
        return self._group(released)

    def release_all(self):
        """Force the watermark to max_ts and drain the buffer (shutdown /
        runtime.release_watermarks() / idle timeout) — sorted, exactly-once.
        The watermark floor stays at the drained max so stragglers arriving
        afterwards classify as late instead of emitting out of order."""
        heap = self._heap
        released: list = []
        while heap:
            ets, _seq, _ats, row = heapq.heappop(heap)
            released.append((ets, row))
        self.released += len(released)
        if self.max_ts is not None and (self._wm_floor is None
                                        or self.max_ts > self._wm_floor):
            self._wm_floor = self.max_ts
        wm = self.watermark()
        if wm is not None and wm != self._last_wm:
            self._last_wm = wm
            self._on_advance(wm)
        return self._group(released)

    def maybe_idle(self):
        """Heartbeat hook: when idle.timeout wall-clock has passed with no
        admissions and rows are still buffered, release them — an idle
        stream must not hold its panes open forever."""
        cfg = self.cfg
        if (cfg.idle_timeout_ms is None or not self._heap
                or (time.monotonic() - self._last_admit) * 1000.0
                < cfg.idle_timeout_ms):
            return []
        return self.release_all()

    def _group(self, released):
        """Chop released (event_ts, row) pairs into delivery batches — one
        batch per distinct event time, rows inside a batch in a
        content-canonical order. Every row carrying event time t releases
        at the same watermark crossing in EVERY lateness-bounded arrival
        order, so per-ts batch boundaries (and therefore per-batch
        aggregate emissions downstream) are permutation-invariant — the
        property the shuffled-replay oracle certifies. With lateness 0 the
        gate is a pure pass-through sorter: arrival batching is kept as-is
        (nothing buffers, so there is no determinism to buy and no
        batching worth shattering)."""
        if not released:
            return []
        if not self.cfg.lateness_ms:
            return [([e for e, _ in released], [r for _, r in released])]
        groups: list = []
        cur = object()
        for ets, row in released:
            if ets != cur:
                groups.append(([], []))
                cur = ets
            g = groups[-1]
            g[0].append(ets)
            g[1].append(row)
        for _tss_g, rows_g in groups:
            if len(rows_g) > 1:
                rows_g.sort(key=repr)  # arrival order is not reproducible
        return groups

    @contextmanager
    def bypass(self):
        """Late-admission window for ErrorStore replay: rows flushed while
        the flag is up skip the lateness check and re-enter the sorted
        buffer (releasing immediately when older than the watermark), so a
        replayed correction flows to sinks instead of re-diverting forever.
        Holds the controller lock for the whole window: no concurrent
        producer flush can ride the bypass."""
        with self.junction.ctx.controller_lock:
            self._bypass += 1
            try:
                yield
            finally:
                self._bypass -= 1

    # --------------------------------------------------------------- reporting

    def _on_advance(self, wm_ms: int) -> None:
        tele = getattr(self.junction.ctx, "telemetry", None)
        if tele is None:
            return
        tele.record_watermark(self.stream, wm_ms)
        if self.max_ts is not None:
            # delivery lag re-sampled at every watermark advance (not just
            # at delivery) so an idle stream's lag gauge keeps moving
            tele.record_lag(self.stream, self.max_ts)

    def snapshot(self) -> dict:
        """statistics_report()["watermarks"][stream]."""
        return {
            "attr": self.cfg.attr,
            "lateness_ms": self.cfg.lateness_ms,
            "idle_timeout_ms": self.cfg.idle_timeout_ms,
            "watermark": self.watermark(),
            "max_event_ts": self.max_ts,
            "buffered": self.buffered(),
            "admitted": self.admitted,
            "released": self.released,
            "late": self.late,
            "bypassed": self.bypassed,
        }
